"""The autoscaler reconciler loop.

Reference: ray ``python/ray/autoscaler/v2/autoscaler.py:50`` +
``monitor.py`` — each round: poll the control plane's load state, compute a
scaling decision, drive the provider.  Runs in any process that can reach
the control plane (typically the head node, via ``Autoscaler.run``).

Lifecycle transitions route through ``elastic.py``: launches gate on a
per-type jittered backoff after provider failures, and terminations go
through the drain state machine (mark unschedulable -> evict residents
via prepare_evict -> terminate) instead of killing nodes under load.
Control-plane RPCs ride ONE persistent ``RetryableRpcClient`` with the
HA leader resolver attached, so the loop survives a failover window
instead of erroring through it.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time
from typing import Dict, Optional

from .config import AutoscalingConfig
from .elastic import LaunchBackoff, NodeDrainer, build_status
from .provider import NodeProvider, PROVIDER_ID_LABEL
from .scheduler import ScalingDecision, compute_scaling_decision

logger = logging.getLogger(__name__)


class Autoscaler:
    def __init__(
        self,
        config: AutoscalingConfig,
        provider: NodeProvider,
        cp_address: str,
        cp_ha_dir: Optional[str] = None,
    ):
        self.config = config
        self.provider = provider
        self.cp_address = cp_address
        self._cp_ha_dir = cp_ha_dir or os.environ.get("RAY_TPU_CP_HA_DIR")
        self._stop = threading.Event()
        self.last_decision: Optional[ScalingDecision] = None
        self._backoffs: Dict[str, LaunchBackoff] = {}
        self.drainer = NodeDrainer(
            self._call, provider, timeout_s=config.drain_timeout_s
        )
        # provider_id -> monotonic first-seen: the reclaim grace clock for
        # nodes the control plane never (or no longer) reports alive.
        self._first_seen: Dict[str, float] = {}
        # Dedicated event-loop thread owning the persistent RPC client
        # (RetryableRpcClient is async; the reconcile loop is a plain
        # thread).  Created lazily on first use.
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread: Optional[threading.Thread] = None
        self._rpc = None

    # -------------------------------------------------------------- rpc plane
    def _ensure_rpc(self):
        if self._rpc is not None:
            return
        from ..core.cp_ha import make_cp_resolver
        from ..core.rpc import RetryableRpcClient

        resolver = (
            make_cp_resolver(self._cp_ha_dir, self.cp_address)
            if self._cp_ha_dir
            else None
        )
        self._rpc = RetryableRpcClient(
            self.cp_address, address_resolver=resolver
        )
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, daemon=True,
            name="rtpu-autoscaler-rpc",
        )
        self._loop_thread.start()

    def _call(self, method: str, payload: Optional[dict] = None,
              timeout: float = 30.0):
        """One synchronous control-plane RPC.  Prefers a connected global
        worker's client (same process as the driver); otherwise the
        autoscaler's own persistent retryable client — NEVER a throwaway
        connection per round."""
        from ..core.core_worker import try_global_worker

        worker = try_global_worker()
        if worker is not None and worker.cp_address == self.cp_address:
            return worker._run_sync(worker.cp.call(method, payload))
        self._ensure_rpc()
        fut = asyncio.run_coroutine_threadsafe(
            self._rpc.call(method, payload), self._loop
        )
        return fut.result(timeout)

    def _get_load_state(self) -> dict:
        return self._call("get_load_state")

    def _backoff_for(self, tname: str) -> LaunchBackoff:
        b = self._backoffs.get(tname)
        if b is None:
            b = LaunchBackoff(
                base_s=self.config.launch_backoff_base_s,
                cap_s=self.config.launch_backoff_cap_s,
            )
            self._backoffs[tname] = b
        return b

    # ------------------------------------------------------------- one round
    def update(self) -> ScalingDecision:
        """One reconcile round; returns the decision it acted on."""
        from ..util import flight_recorder

        state = self._get_load_state()
        provider_nodes = self.provider.non_terminated_nodes()
        decision = compute_scaling_decision(
            state, self.config, provider_nodes
        )
        flight_recorder.record_autoscaler_pending_demand(
            decision.pending_demand
        )

        # ---- launches, gated by the per-type backoff
        now = time.monotonic()
        for tname, count in decision.to_launch.items():
            node_type = self.config.node_types[tname]
            backoff = self._backoff_for(tname)
            for _ in range(count):
                if not backoff.ready(now):
                    flight_recorder.record_autoscaler_launch(
                        tname, "backoff"
                    )
                    continue
                try:
                    pid = self.provider.create_node(node_type)
                    self._first_seen[pid] = time.monotonic()
                    backoff.record_success()
                    flight_recorder.record_autoscaler_launch(tname, "ok")
                    logger.info("launched %s (%s)", pid, tname)
                except Exception as e:  # noqa: BLE001 — provider flake; backoff gates the retry
                    delay = backoff.record_failure()
                    flight_recorder.record_autoscaler_launch(tname, "error")
                    logger.warning(
                        "launch of %s failed (%d consecutive, next attempt "
                        "in %.1fs): %s",
                        tname, backoff.consecutive_failures, delay, e,
                    )
                    break  # same type would fail again this round

        # ---- terminations: drain first (the state machine owns retirement)
        pid_to_node = {
            node.get("labels", {}).get(PROVIDER_ID_LABEL): nid_hex
            for nid_hex, node in state["nodes"].items()
        }
        for pid in decision.to_terminate:
            if self.drainer.is_draining(pid):
                continue
            if self.config.drain_before_terminate:
                self.drainer.request(
                    pid, pid_to_node.get(pid), cause="idle timeout"
                )
            else:
                try:
                    self.provider.terminate_node(pid)
                    flight_recorder.record_autoscaler_termination("direct")
                    logger.info("terminated %s", pid)
                except Exception as e:  # noqa: BLE001
                    flight_recorder.record_autoscaler_termination("error")
                    logger.warning("terminate of %s failed: %s", pid, e)
        self.drainer.poll()

        # ---- reclaim: provider records with no live control-plane node
        # past the grace window (crashed VM, failed provisioning) — churn
        # convergence, and the counter-half of double-launch protection.
        alive_pids = {
            node.get("labels", {}).get(PROVIDER_ID_LABEL)
            for node in state["nodes"].values()
            if node.get("alive")
        }
        for pid in list(provider_nodes):
            if pid in alive_pids or self.drainer.is_draining(pid):
                self._first_seen.setdefault(pid, now)
                continue
            first = self._first_seen.setdefault(pid, now)
            if now - first >= self.config.reclaim_grace_s:
                try:
                    self.provider.terminate_node(pid)
                    flight_recorder.record_autoscaler_termination(
                        "reclaimed"
                    )
                    logger.warning(
                        "reclaimed %s: no live node after %.0fs",
                        pid, now - first,
                    )
                except Exception as e:  # noqa: BLE001
                    logger.warning("reclaim of %s failed: %s", pid, e)
                self._first_seen.pop(pid, None)
        # A node the control plane reports alive restarts its grace clock
        # if it later disappears (e.g. killed by chaos).
        for pid in list(self._first_seen):
            if pid in alive_pids:
                self._first_seen[pid] = now

        if decision.infeasible:
            logger.warning(
                "infeasible resource demands (no node type fits): %s",
                decision.infeasible[:5],
            )

        # ---- surface backoff + drain state in the decision and the panel
        for tname, b in self._backoffs.items():
            if b.consecutive_failures:
                decision.launch_failures[tname] = b.consecutive_failures
            rem = b.remaining_s(time.monotonic())
            if rem > 0:
                decision.backoff_remaining_s[tname] = round(rem, 3)
        decision.draining = [
            d["provider_id"] for d in self.drainer.active()
        ]
        self.last_decision = decision
        self._publish_status(decision)
        return decision

    def _publish_status(self, decision: ScalingDecision) -> None:
        per_type: Dict[str, int] = {}
        for tname in self.provider.non_terminated_nodes().values():
            per_type[tname] = per_type.get(tname, 0) + 1
        for tname in self.config.node_types:
            self._backoff_for(tname)
        status = build_status(
            decision, per_type, self._backoffs, self.drainer,
            provider_nodes=sum(per_type.values()),
        )
        status["ts"] = time.time()
        try:
            self._call(
                "kv_put",
                {"namespace": "autoscaler", "key": "status",
                 "value": status},
            )
        except Exception as e:  # noqa: BLE001 — panel is best-effort telemetry
            logger.debug("autoscaler status publish failed: %s", e)

    # ------------------------------------------------------------------ loop
    def run(self, period_s: float = 5.0) -> None:
        """Blocking reconcile loop (``ray_tpu.autoscaler.monitor`` analog)."""
        while not self._stop.is_set():
            try:
                self.update()
            except Exception as e:  # noqa: BLE001
                logger.warning("autoscaler round failed: %s", e)
            self._stop.wait(period_s)

    def start_background(self, period_s: float = 5.0) -> threading.Thread:
        thread = threading.Thread(
            target=self.run, args=(period_s,), daemon=True,
            name="rtpu-autoscaler",
        )
        self._thread = thread
        thread.start()
        return thread

    def stop(self, join_timeout_s: float = 10.0) -> None:
        """Signal the reconcile loop and join a background thread if one
        was started, so teardown observes the last round completing
        instead of abandoning it mid-provider-call."""
        self._stop.set()
        thread = getattr(self, "_thread", None)
        if thread is not None and thread.is_alive():
            thread.join(timeout=join_timeout_s)
        if self._loop is not None:
            if self._rpc is not None:
                try:
                    asyncio.run_coroutine_threadsafe(
                        self._rpc.close(), self._loop
                    ).result(timeout=5.0)
                except Exception as e:  # noqa: BLE001 — teardown best-effort
                    logger.debug("autoscaler rpc close failed: %s", e)
            self._loop.call_soon_threadsafe(self._loop.stop)
            if self._loop_thread is not None:
                self._loop_thread.join(timeout=5.0)


def wait_for_nodes(n: int, cp_address: str, timeout: float = 60.0) -> None:
    """Test/ops helper: block until n nodes are alive."""
    from ..util.state.api import StateApiClient

    client = StateApiClient(cp_address)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        nodes = client.get_state()["nodes"]
        if sum(1 for v in nodes.values() if v["alive"]) >= n:
            return
        time.sleep(0.3)
    raise TimeoutError(f"cluster did not reach {n} nodes in {timeout}s")
