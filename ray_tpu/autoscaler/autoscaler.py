"""The autoscaler reconciler loop.

Reference: ray ``python/ray/autoscaler/v2/autoscaler.py:50`` +
``monitor.py`` — each round: poll the control plane's load state, compute a
scaling decision, drive the provider.  Runs in any process that can reach
the control plane (typically the head node, via ``Autoscaler.run``).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Optional

from .config import AutoscalingConfig
from .provider import NodeProvider
from .scheduler import ScalingDecision, compute_scaling_decision

logger = logging.getLogger(__name__)


class Autoscaler:
    def __init__(
        self,
        config: AutoscalingConfig,
        provider: NodeProvider,
        cp_address: str,
    ):
        self.config = config
        self.provider = provider
        self.cp_address = cp_address
        self._stop = threading.Event()
        self.last_decision: Optional[ScalingDecision] = None

    # ------------------------------------------------------------- one round
    def _get_load_state(self) -> dict:
        from ..core.core_worker import try_global_worker
        from ..core.rpc import RpcClient

        worker = try_global_worker()
        if worker is not None and worker.cp_address == self.cp_address:
            return worker._run_sync(worker.cp.call("get_load_state"))

        async def run():
            client = RpcClient(self.cp_address)
            await client.connect()
            try:
                return await client.call("get_load_state")
            finally:
                await client.close()

        return asyncio.run(run())

    def update(self) -> ScalingDecision:
        """One reconcile round; returns the decision it acted on."""
        state = self._get_load_state()
        decision = compute_scaling_decision(
            state, self.config, self.provider.non_terminated_nodes()
        )
        for tname, count in decision.to_launch.items():
            node_type = self.config.node_types[tname]
            for _ in range(count):
                try:
                    pid = self.provider.create_node(node_type)
                    logger.info("launched %s (%s)", pid, tname)
                except Exception as e:  # noqa: BLE001
                    logger.warning("launch of %s failed: %s", tname, e)
        for pid in decision.to_terminate:
            try:
                self.provider.terminate_node(pid)
                logger.info("terminated %s", pid)
            except Exception as e:  # noqa: BLE001
                logger.warning("terminate of %s failed: %s", pid, e)
        if decision.infeasible:
            logger.warning(
                "infeasible resource demands (no node type fits): %s",
                decision.infeasible[:5],
            )
        self.last_decision = decision
        return decision

    # ------------------------------------------------------------------ loop
    def run(self, period_s: float = 5.0) -> None:
        """Blocking reconcile loop (``ray_tpu.autoscaler.monitor`` analog)."""
        while not self._stop.is_set():
            try:
                self.update()
            except Exception as e:  # noqa: BLE001
                logger.warning("autoscaler round failed: %s", e)
            self._stop.wait(period_s)

    def start_background(self, period_s: float = 5.0) -> threading.Thread:
        thread = threading.Thread(
            target=self.run, args=(period_s,), daemon=True,
            name="rtpu-autoscaler",
        )
        self._thread = thread
        thread.start()
        return thread

    def stop(self, join_timeout_s: float = 10.0) -> None:
        """Signal the reconcile loop and join a background thread if one
        was started, so teardown observes the last round completing
        instead of abandoning it mid-provider-call."""
        self._stop.set()
        thread = getattr(self, "_thread", None)
        if thread is not None and thread.is_alive():
            thread.join(timeout=join_timeout_s)


def wait_for_nodes(n: int, cp_address: str, timeout: float = 60.0) -> None:
    """Test/ops helper: block until n nodes are alive."""
    from ..util.state.api import StateApiClient

    client = StateApiClient(cp_address)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        nodes = client.get_state()["nodes"]
        if sum(1 for v in nodes.values() if v["alive"]) >= n:
            return
        time.sleep(0.3)
    raise TimeoutError(f"cluster did not reach {n} nodes in {timeout}s")
