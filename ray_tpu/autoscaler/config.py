"""Autoscaling configuration (reference: the node-types section of the
cluster YAML, ray ``python/ray/autoscaler/ray-schema.json``)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class NodeTypeConfig:
    """One launchable node shape."""

    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = field(default_factory=dict)
    # Provider-specific knobs (e.g. GKE machine type / TPU topology).
    node_config: Dict[str, object] = field(default_factory=dict)


@dataclass
class AutoscalingConfig:
    node_types: Dict[str, NodeTypeConfig]
    idle_timeout_s: float = 60.0
    max_launch_batch: int = 8
    # Global cap across all worker types (None = sum of per-type maxes).
    max_workers: Optional[int] = None

    @staticmethod
    def from_dict(d: dict) -> "AutoscalingConfig":
        types = {
            name: NodeTypeConfig(
                name=name,
                resources=dict(t.get("resources", {})),
                min_workers=t.get("min_workers", 0),
                max_workers=t.get("max_workers", 10),
                labels=dict(t.get("labels", {})),
                node_config=dict(t.get("node_config", {})),
            )
            for name, t in d.get("node_types", {}).items()
        }
        return AutoscalingConfig(
            node_types=types,
            idle_timeout_s=d.get("idle_timeout_s", 60.0),
            max_launch_batch=d.get("max_launch_batch", 8),
            max_workers=d.get("max_workers"),
        )
