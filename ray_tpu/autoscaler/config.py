"""Autoscaling configuration (reference: the node-types section of the
cluster YAML, ray ``python/ray/autoscaler/ray-schema.json``)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class NodeTypeConfig:
    """One launchable node shape."""

    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = field(default_factory=dict)
    # Provider-specific knobs (e.g. GKE machine type / TPU topology).
    node_config: Dict[str, object] = field(default_factory=dict)


@dataclass
class AutoscalingConfig:
    node_types: Dict[str, NodeTypeConfig]
    idle_timeout_s: float = 60.0
    max_launch_batch: int = 8
    # Global cap across all worker types (None = sum of per-type maxes).
    max_workers: Optional[int] = None
    # Scale-down path: route idle-timeout terminations through the drain
    # state machine (mark unschedulable -> evict residents via
    # prepare_evict -> terminate) instead of a direct provider terminate.
    drain_before_terminate: bool = True
    # Deadline for a drain to empty (None = GlobalConfig.drain_timeout_s);
    # on expiry the node is terminated anyway.
    drain_timeout_s: Optional[float] = None
    # Per-node-type launch backoff (decorrelated jitter between these
    # bounds) after a provider create failure.
    launch_backoff_base_s: float = 1.0
    launch_backoff_cap_s: float = 30.0
    # How long a provider node may stay unknown to the control plane
    # (still provisioning, or crashed without the provider noticing)
    # before the autoscaler reclaims its record.
    reclaim_grace_s: float = 30.0

    @staticmethod
    def from_dict(d: dict) -> "AutoscalingConfig":
        types = {
            name: NodeTypeConfig(
                name=name,
                resources=dict(t.get("resources", {})),
                min_workers=t.get("min_workers", 0),
                max_workers=t.get("max_workers", 10),
                labels=dict(t.get("labels", {})),
                node_config=dict(t.get("node_config", {})),
            )
            for name, t in d.get("node_types", {}).items()
        }
        return AutoscalingConfig(
            node_types=types,
            idle_timeout_s=d.get("idle_timeout_s", 60.0),
            max_launch_batch=d.get("max_launch_batch", 8),
            max_workers=d.get("max_workers"),
            drain_before_terminate=d.get("drain_before_terminate", True),
            drain_timeout_s=d.get("drain_timeout_s"),
            launch_backoff_base_s=d.get("launch_backoff_base_s", 1.0),
            launch_backoff_cap_s=d.get("launch_backoff_cap_s", 30.0),
            reclaim_grace_s=d.get("reclaim_grace_s", 30.0),
        )
