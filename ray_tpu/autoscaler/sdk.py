"""Programmatic autoscaling requests (reference: ray
``python/ray/autoscaler/sdk.py`` ``request_resources``)."""

from __future__ import annotations

from typing import Dict, List, Optional


def request_resources(
    bundles: Optional[List[Dict[str, float]]] = None,
    num_cpus: Optional[int] = None,
) -> None:
    """Ask the autoscaler to provision capacity for these bundles
    immediately (a standing request, replaced on each call; pass no args to
    clear).  Requires a connected driver."""
    from ..core.core_worker import global_worker

    out: List[Dict[str, float]] = list(bundles or [])
    if num_cpus:
        out.append({"CPU": float(num_cpus)})
    worker = global_worker()
    worker._run_sync(worker.cp.call("request_resources", {"bundles": out}))
