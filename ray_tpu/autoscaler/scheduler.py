"""Bin-packing scaling decisions.

Reference: ray ``python/ray/autoscaler/v2/scheduler.py`` — simulate packing
unmet demand onto (existing + planned) nodes; launch the fewest nodes whose
shapes fit what's left; terminate nodes idle beyond the timeout, respecting
per-type ``min_workers``/``max_workers``.

Gang awareness: a STRICT_PACK placement group's bundles are merged into one
atomic demand (they must land on a single node/slice), and STRICT_SPREAD
bundles are forbidden from sharing a planned node.  Standing
``request_resources`` bundles are checked against node *totals*, not free
capacity — they express "the cluster should have this much", not "this much
must be free right now".
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .config import AutoscalingConfig, NodeTypeConfig
from .provider import NODE_TYPE_LABEL, PROVIDER_ID_LABEL


@dataclass
class ScalingDecision:
    to_launch: Dict[str, int] = field(default_factory=dict)  # type -> count
    to_terminate: List[str] = field(default_factory=list)  # provider ids
    infeasible: List[dict] = field(default_factory=list)  # unmet demands
    # Demand summary: how many unmet demands fed this round's packing and
    # their aggregate shape (the cli/dashboard pending-demand panel).
    pending_demand: int = 0
    pending_resources: Dict[str, float] = field(default_factory=dict)
    # Filled by the Autoscaler after acting: per-type consecutive launch
    # failures and the remaining backoff gate (0 = clear to launch).
    launch_failures: Dict[str, int] = field(default_factory=dict)
    backoff_remaining_s: Dict[str, float] = field(default_factory=dict)
    # Provider ids the drain state machine currently holds (informational;
    # never re-listed in to_terminate).
    draining: List[str] = field(default_factory=list)


@dataclass
class _Demand:
    resources: dict
    exclusive: bool = False  # STRICT_SPREAD: must not share a planned node
    against_total: bool = False  # standing request: packs against totals


@dataclass
class _SimNode:
    avail: Dict[str, float]
    total: Dict[str, float]
    provider_id: Optional[str]
    type_name: str
    idle_s: float
    used: bool = False  # absorbed demand this round → not terminable
    planned: bool = False
    exclusive_used: bool = False


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in demand.items() if v > 0)


def _sub(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


def _merge(bundles: List[dict]) -> dict:
    out: Dict[str, float] = {}
    for b in bundles:
        for k, v in b.items():
            out[k] = out.get(k, 0.0) + v
    return out


def _collect_demands(load_state: dict) -> List[_Demand]:
    demands: List[_Demand] = []
    for node in load_state["nodes"].values():
        if node["alive"]:
            demands.extend(
                _Demand(dict(d)) for d in node.get("pending_demands", [])
            )
    demands.extend(
        _Demand(dict(d)) for d in load_state.get("pending_actors", [])
    )
    demands.extend(
        _Demand(dict(d)) for d in load_state.get("unplaceable_demands", [])
    )
    # Over-quota task leases queued by admission: no PENDING table holds
    # them, so the control plane exports a recency window (the JobArbiter
    # demand the tentpole wires in — queued work provisions nodes instead
    # of waiting forever).
    demands.extend(
        _Demand(dict(d)) for d in load_state.get("queued_task_demands", [])
    )
    for pg in load_state.get("pending_pgs", []):
        if isinstance(pg, dict):
            strategy, bundles = pg.get("strategy", "PACK"), pg["bundles"]
        else:  # bare bundle list (older snapshot)
            strategy, bundles = "PACK", pg
        if strategy == "STRICT_PACK":
            demands.append(_Demand(_merge(bundles)))
        elif strategy == "STRICT_SPREAD":
            demands.extend(_Demand(dict(b), exclusive=True) for b in bundles)
        else:
            demands.extend(_Demand(dict(b)) for b in bundles)
    demands.extend(
        _Demand(dict(b), against_total=True)
        for b in load_state.get("requested_resources", [])
    )
    demands.sort(key=lambda d: -sum(d.resources.values()))
    return demands


def compute_scaling_decision(
    load_state: dict, config: AutoscalingConfig,
    provider_nodes: Dict[str, str],
) -> ScalingDecision:
    decision = ScalingDecision()
    demands = _collect_demands(load_state)
    decision.pending_demand = len(demands)
    for d in demands:
        for k, v in d.resources.items():
            decision.pending_resources[k] = (
                decision.pending_resources.get(k, 0.0) + v
            )

    sim_nodes: List[_SimNode] = []
    for node in load_state["nodes"].values():
        if not node["alive"]:
            continue
        if node.get("draining"):
            # A draining node is leaving: nothing may pack onto it, and it
            # must not be re-selected for idle termination — the drain
            # state machine already owns its retirement.
            continue
        labels = node.get("labels", {})
        sim_nodes.append(
            _SimNode(
                avail=dict(node["available"]),
                total=dict(node["total"]),
                provider_id=labels.get(PROVIDER_ID_LABEL),
                type_name=labels.get(NODE_TYPE_LABEL, ""),
                idle_s=node.get("idle_s", 0.0),
            )
        )

    # Provider records whose node has not REGISTERED yet (still
    # provisioning — e.g. a slow cloud boot) count as planned capacity,
    # or every round between create_node and the agent's first heartbeat
    # would launch another copy for the same demand.  A record the
    # control plane KNOWS but reports dead is excluded: that node is not
    # coming back on its own (the reclaim grace owns its record), and
    # suppressing a relaunch would strand the demand.
    known_pids = {
        node.get("labels", {}).get(PROVIDER_ID_LABEL)
        for node in load_state["nodes"].values()
    }
    for pid, tname in provider_nodes.items():
        if pid in known_pids:
            continue
        t = config.node_types.get(tname)
        if t is None:
            continue
        sim_nodes.append(
            _SimNode(
                avail=dict(t.resources),
                total=dict(t.resources),
                provider_id=pid,
                type_name=tname,
                idle_s=0.0,
                planned=True,
            )
        )

    per_type: Dict[str, int] = {}
    for tname in provider_nodes.values():
        per_type[tname] = per_type.get(tname, 0) + 1
    total_workers = sum(per_type.values())
    global_cap = (
        config.max_workers
        if config.max_workers is not None
        else sum(t.max_workers for t in config.node_types.values())
    )

    def try_launch(demand: _Demand) -> bool:
        if total_workers + sum(decision.to_launch.values()) >= global_cap:
            return False
        candidates = sorted(
            (
                t
                for t in config.node_types.values()
                if _fits(dict(t.resources), demand.resources)
                and per_type.get(t.name, 0) + decision.to_launch.get(t.name, 0)
                < t.max_workers
            ),
            key=lambda t: sum(t.resources.values()),
        )
        if not candidates:
            return False
        t = candidates[0]
        node = _SimNode(
            avail=dict(t.resources),
            total=dict(t.resources),
            provider_id=None,
            type_name=t.name,
            idle_s=0.0,
            planned=True,
        )
        _sub(node.avail, demand.resources)
        node.used = True
        node.exclusive_used = demand.exclusive
        sim_nodes.append(node)
        decision.to_launch[t.name] = decision.to_launch.get(t.name, 0) + 1
        return True

    for demand in demands:
        placed = False
        for node in sim_nodes:
            # STRICT_SPREAD bundles refuse to share a node with anything
            # placed this round, and nothing joins a node they claimed.
            if demand.exclusive and node.used:
                continue
            if node.exclusive_used:
                continue
            capacity = node.total if demand.against_total else node.avail
            if _fits(capacity, demand.resources):
                if demand.against_total:
                    _sub(node.total, demand.resources)
                else:
                    _sub(node.avail, demand.resources)
                node.used = True
                node.exclusive_used = node.exclusive_used or demand.exclusive
                placed = True
                break
        if not placed and not try_launch(demand):
            decision.infeasible.append(demand.resources)

    # ---- min_workers floor
    for t in config.node_types.values():
        have = per_type.get(t.name, 0) + decision.to_launch.get(t.name, 0)
        if have < t.min_workers:
            decision.to_launch[t.name] = (
                decision.to_launch.get(t.name, 0) + (t.min_workers - have)
            )

    # ---- scale down: idle past the timeout, not absorbed into this round's
    # packing, above the type's min_workers floor
    remaining = dict(per_type)
    for node in sim_nodes:
        if node.planned or node.provider_id is None or node.used:
            continue
        t: Optional[NodeTypeConfig] = config.node_types.get(node.type_name)
        floor = t.min_workers if t else 0
        if (
            node.idle_s >= config.idle_timeout_s
            and remaining.get(node.type_name, 0) > floor
        ):
            decision.to_terminate.append(node.provider_id)
            remaining[node.type_name] = remaining.get(node.type_name, 0) - 1

    # ---- launch batch cap
    launching = sum(decision.to_launch.values())
    if launching > config.max_launch_batch:
        budget = config.max_launch_batch
        trimmed: Dict[str, int] = {}
        for tname, n in decision.to_launch.items():
            take = min(n, budget)
            if take:
                trimmed[tname] = take
            budget -= take
            if budget <= 0:
                break
        decision.to_launch = trimmed
    return decision
