"""Dashboard: live HTML UI + cluster state + metrics over HTTP.

Role-equivalent of the reference dashboard (ray ``python/ray/dashboard/``:
the head process aggregating state + the metrics pipeline to Prometheus),
with a single-file HTML frontend (``dashboard_ui.py``) instead of the
TypeScript app.  Endpoints:

    GET /                    live dashboard UI (auto-refreshing tables)
    GET /api                 endpoint index
    GET /api/cluster         resource + actor/job summary
    GET /api/nodes|actors|tasks|jobs|placement_groups
    GET /api/timeline        Chrome-trace events
    GET /metrics             Prometheus exposition (ray.util.metrics analog)
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

_state: Dict[str, Any] = {}


def start_dashboard(
    host: str = "127.0.0.1", port: int = 8265, address: Optional[str] = None
) -> str:
    """Start the dashboard HTTP server (connects a driver if needed)."""
    import ray_tpu
    from aiohttp import web

    if _state:
        # Only one dashboard per process; replace the previous instance
        # instead of orphaning its loop/thread/socket.
        stop_dashboard()
    if not ray_tpu.is_initialized():
        ray_tpu.init(address=address or "auto")

    from .core.config import GlobalConfig

    if GlobalConfig.enable_remediation:
        # Self-healing opt-in: attach the process-wide remediation
        # controller to the aggregation beat (util/remediation.py).
        from .util import remediation as remediation_mod

        if remediation_mod.get_remediation_controller() is None:
            remediation_mod.start()

    from .util.state import api as state_api
    from .util.state.api import StateApiClient, chrome_trace_events

    client = StateApiClient()

    def _json(data, status=200):
        return web.json_response(
            json.loads(json.dumps(data, default=str)), status=status
        )

    async def run_sync(fn, *args, **kw):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, lambda: fn(*args, **kw))

    async def index(request):
        from .dashboard_ui import INDEX_HTML

        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def api_index(request):
        return _json(
            {
                "endpoints": [
                    "/api/cluster", "/api/nodes", "/api/actors",
                    "/api/tasks", "/api/jobs", "/api/placement_groups",
                    "/api/timeline", "/api/timeline?cluster=1",
                    "/api/task_phases", "/api/slo", "/metrics",
                ]
            }
        )

    async def cluster(request):
        state = await run_sync(client.get_state)
        alive = [n for n in state["nodes"].values() if n["alive"]]
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for info in alive:
            for k, v in info["snapshot"]["total"].items():
                total[k] = total.get(k, 0) + v
            for k, v in info["snapshot"]["available"].items():
                avail[k] = avail.get(k, 0) + v
        actors: Dict[str, int] = {}
        for a in state["actors"]:
            actors[a["state"]] = actors.get(a["state"], 0) + 1
        return _json(
            {
                "nodes_alive": len(alive),
                "nodes_total": len(state["nodes"]),
                "resources_total": total,
                "resources_available": avail,
                "actors_by_state": actors,
                "jobs_running": sum(
                    1 for j in state["jobs"].values()
                    if j["state"] == "RUNNING"
                ),
                # Per-job arbitration state (priority, quota, charged
                # usage, admission-queued counts) — who is starving whom.
                "scheduling": state.get("scheduling", {}),
                # Control-plane HA: role, lease epoch, journal stats and
                # per-standby replication lag (see docs/ha.md).
                "cp": state.get("cp", {}),
                # Elastic capacity: the autoscaler's per-round status blob
                # (last decision, pending demand, per-type counts/backoff,
                # in-flight drains — see docs/elastic.md).
                "autoscaler": state.get("autoscaler", {}),
                "nodes_draining": sum(
                    1 for n in alive if n.get("draining")
                ),
            }
        )

    async def nodes(request):
        return _json(await run_sync(state_api.list_nodes))

    async def actors(request):
        return _json(await run_sync(state_api.list_actors))

    async def tasks(request):
        limit = int(request.query.get("limit", "1000"))
        filters = None
        if "name" in request.query:
            filters = {"name": request.query["name"]}
        return _json(
            await run_sync(state_api.list_tasks, None, filters, limit)
        )

    async def jobs(request):
        """Driver jobs (cluster state) + submission jobs (REST-managed)
        in one listing: driver jobs carry ``job_id``, submissions carry
        ``submission_id`` — the client filters by the field it knows."""
        driver_jobs = await run_sync(state_api.list_jobs)
        try:
            subs = await run_sync(
                lambda: [j.__dict__ for j in _job_client().list_jobs()]
            )
        except Exception:  # noqa: BLE001 — submissions list is best-effort
            subs = []
        return _json(driver_jobs + subs)

    # ---- REST job submission (reference: dashboard/modules/job/
    # job_manager.py:61 + sdk.py:36 — JobSubmissionClient speaks HTTP to
    # the dashboard; the implementation behind the endpoint is the
    # supervisor-actor machinery in ray_tpu.job).
    def _job_client():
        from .job.sdk import JobSubmissionClient

        return JobSubmissionClient()

    async def submit_job(request):
        body = await request.json()
        if "entrypoint" not in body:
            return _json({"error": "entrypoint required"}, status=400)
        try:
            sid = await run_sync(
                lambda: _job_client().submit_job(
                    entrypoint=body["entrypoint"],
                    submission_id=body.get("submission_id"),
                    runtime_env=body.get("runtime_env"),
                    metadata=body.get("metadata"),
                )
            )
        except ValueError as e:
            return _json({"error": str(e)}, status=409)
        except Exception as e:  # noqa: BLE001
            return _json({"error": str(e)}, status=500)
        return _json({"submission_id": sid})

    async def job_info(request):
        sid = request.match_info["sid"]
        info = await run_sync(lambda: _job_client().get_job_info(sid))
        if info is None:
            return _json({"error": f"no job {sid}"}, status=404)
        return _json(info.__dict__)

    async def job_logs(request):
        sid = request.match_info["sid"]
        text = await run_sync(lambda: _job_client().get_job_logs(sid))
        return _json({"logs": text})

    async def job_stop(request):
        sid = request.match_info["sid"]
        ok = await run_sync(lambda: _job_client().stop_job(sid))
        return _json({"stopped": bool(ok)})

    async def job_delete(request):
        sid = request.match_info["sid"]
        try:
            ok = await run_sync(lambda: _job_client().delete_job(sid))
        except RuntimeError as e:
            return _json({"error": str(e)}, status=400)
        return _json({"deleted": bool(ok)})

    async def pgs(request):
        return _json(await run_sync(state_api.list_placement_groups))

    async def timeline(request):
        if request.query.get("cluster", "") not in ("", "0", "false"):
            # Cluster-merged Chrome trace: spans from every process,
            # cross-process flow links, explicit truncation metadata.
            from .util import obs

            return _json(await run_sync(obs.cluster_timeline))
        reply = await run_sync(client.list_task_events, None, 100000)
        return _json(chrome_trace_events(reply))

    async def task_phases(request):
        """Flight-recorder phase percentiles (queue wait, arg resolution,
        execute, return-put, backpressure wait)."""
        return _json(await run_sync(state_api.summarize_task_phases))

    async def slo(request):
        """SLO/anomaly engine findings over the aggregated stream (one
        process-wide engine: rate/sustain rules accumulate state across
        requests), plus the remediation controller's actions/quarantine
        state when one is running (here or elsewhere in the cluster)."""
        from .util import remediation as remediation_mod
        from .util.slo import get_slo_engine

        engine = get_slo_engine()
        await run_sync(engine.evaluate)
        report = engine.report()
        rem = await run_sync(remediation_mod.report_snapshot)
        if rem is not None:
            report["remediation"] = rem
        return _json(report)

    async def metrics(request):
        from .util import metrics as metrics_mod

        text = await run_sync(metrics_mod.prometheus_text)
        return web.Response(text=text, content_type="text/plain")

    app = web.Application()
    app.router.add_get("/", index)
    app.router.add_get("/api", api_index)
    app.router.add_get("/api/cluster", cluster)
    app.router.add_get("/api/nodes", nodes)
    app.router.add_get("/api/actors", actors)
    app.router.add_get("/api/tasks", tasks)
    app.router.add_get("/api/jobs", jobs)
    app.router.add_post("/api/jobs", submit_job)
    app.router.add_get("/api/jobs/{sid}", job_info)
    app.router.add_get("/api/jobs/{sid}/logs", job_logs)
    app.router.add_post("/api/jobs/{sid}/stop", job_stop)
    app.router.add_delete("/api/jobs/{sid}", job_delete)
    app.router.add_get("/api/placement_groups", pgs)
    app.router.add_get("/api/timeline", timeline)
    app.router.add_get("/api/task_phases", task_phases)
    app.router.add_get("/api/slo", slo)
    app.router.add_get("/metrics", metrics)

    loop = asyncio.new_event_loop()
    started = threading.Event()
    runner_box: Dict[str, Any] = {}

    def serve_forever():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, host, port)
        loop.run_until_complete(site.start())
        runner_box["runner"] = runner
        started.set()
        loop.run_forever()

    t = threading.Thread(target=serve_forever, daemon=True,
                         name="rtpu-dashboard")
    t.start()
    if not started.wait(timeout=10):
        raise RuntimeError("dashboard failed to start")
    _state.update(loop=loop, thread=t, runner=runner_box.get("runner"))
    return f"http://{host}:{port}"


def stop_dashboard() -> None:
    loop = _state.get("loop")
    runner = _state.get("runner")
    if loop is None:
        return
    if runner is not None:
        # Release the listening socket before stopping the loop, else a
        # restart on the same port hits address-in-use until GC.
        fut = asyncio.run_coroutine_threadsafe(runner.cleanup(), loop)
        try:
            fut.result(timeout=5)
        except Exception as e:
            # Socket release is best-effort; a restart on this port may
            # hit address-in-use until GC, so leave a trail.
            logger.warning("dashboard runner cleanup failed: %s", e)
    loop.call_soon_threadsafe(loop.stop)
    _state.clear()
