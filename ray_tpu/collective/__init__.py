from .collective import (  # noqa: F401
    allgather,
    allreduce,
    alltoall,
    barrier,
    broadcast,
    collective_stats,
    destroy_collective_group,
    get_collective_group_size,
    get_group,
    get_rank,
    init_collective_group,
    init_local_group,
    is_group_initialized,
    recv,
    reducescatter,
    send,
)
from .device_objects import DeviceObjectStore, DeviceRef, device_object_store  # noqa: F401
from .p2p import Mailbox, StageChannel, local_mailbox  # noqa: F401
from .tuner import get_tuner, reset_tuner  # noqa: F401
from .types import Backend, GroupInfo, ReduceOp, Topology  # noqa: F401
from .experimental import (  # noqa: F401
    RemoteCommunicatorManager,
    create_collective_group,
    get_collective_groups,
)
from .experimental import (  # noqa: F401
    destroy_collective_group as destroy_actor_collective_group,
)
