"""Online collective-algorithm autotuner.

Per (op, message-size bucket, world size, ICI-vs-DCN topology) the tuner
picks one of the algorithms in ``algorithms.py``.  It starts from a
static size/topology heuristic table, explores every eligible candidate
a fixed number of times on a deterministic round-robin schedule, commits
to the measured-best algorithm (achieved bandwidth fed back from the
flight recorder's per-op capture), and keeps re-probing alternatives on
a geometrically decaying schedule so a drifting fabric can flip the
decision later.  Every decision is observable: ``collective_stats()``
returns the per-bucket table (chosen algorithm, per-algorithm attempts,
samples, mean bandwidth) and the ``ray_tpu_collective_tuner_*`` /
``ray_tpu_collective_algo_ops_total`` metrics ride the Prometheus
endpoint.

Determinism contract (the SPMD caveat): selection depends only on the
CALL SEQUENCE (per-bucket call counts and attempt counts), never on
wall-clock or randomness, so group members that issue the same
collectives in the same order — the same contract the groups' compiled-
function caches already assume — stay in lockstep through the explore
phase.  Measured bandwidths DO differ across member processes, so
multi-member groups pass a ``sync`` callback (a small always-flat
allreduce) that averages the measurement table at the deterministic
commit points; every member then computes the same argmax and compiles
the same program.  Single-process groups pass ``sync=None``.

If the flight recorder is disabled no bandwidth ever arrives and the
tuner commits to the heuristic choice — the static table is the
fallback, not an error.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from . import algorithms as alg

# Message-size buckets (bytes, per-rank payload).  Boundaries follow the
# classic latency->bandwidth crossover decades; labels are stable metric
# tag values.
SIZE_BUCKET_EDGES: Tuple[int, ...] = (4 << 10, 64 << 10, 1 << 20, 16 << 20)
SIZE_BUCKET_LABELS: Tuple[str, ...] = (
    "le4KiB", "le64KiB", "le1MiB", "le16MiB", "gt16MiB",
)

# Explore each candidate this many times before committing.
MIN_ATTEMPTS = 2
# After commit, re-probe at call counts committed_at * 2^k (geometric
# decay), capped so a long-running job still re-probes occasionally.
REPROBE_MAX_INTERVAL = 4096


def size_bucket(nbytes: int) -> str:
    for edge, label in zip(SIZE_BUCKET_EDGES, SIZE_BUCKET_LABELS):
        if nbytes <= edge:
            return label
    return SIZE_BUCKET_LABELS[-1]


def heuristic_choice(op: str, nbytes: int, world_size: int, topology,
                     candidates: Tuple[str, ...]) -> str:
    """Static seed table: small messages are latency-bound (one fused
    XLA op wins), large messages are bandwidth-bound (ring), mid sizes
    on power-of-two worlds take the log-round tree, and any two-level
    topology prefers the hierarchical decomposition for non-small
    payloads (the DCN hop carries 1/n_ici of the bytes)."""
    if alg.TWO_LEVEL_Q8 in candidates:
        return alg.TWO_LEVEL_Q8
    if alg.FLAT_Q8 in candidates:
        return alg.FLAT_Q8
    if topology is not None and topology.is_two_level and nbytes > (64 << 10) \
            and alg.TWO_LEVEL in candidates:
        return alg.TWO_LEVEL
    if nbytes <= (64 << 10):
        return alg.FLAT
    if nbytes <= (1 << 20) and alg.TREE in candidates:
        return alg.TREE
    if alg.RING in candidates:
        return alg.RING
    return candidates[0]


@dataclass
class _AlgoStats:
    attempts: int = 0          # selections (deterministic, select-side)
    samples: int = 0           # warm bandwidth observations
    bw_sum: float = 0.0

    @property
    def mean_bw(self) -> float:
        return self.bw_sum / self.samples if self.samples else 0.0


@dataclass
class _Bucket:
    op: str
    size_label: str
    world_size: int
    topology: str
    candidates: Tuple[str, ...]
    calls: int = 0
    explorations: int = 0
    commits: int = 0
    committed: Optional[str] = None
    committed_at: int = 0
    next_probe: int = 0
    pending_recommit: bool = False
    algos: Dict[str, _AlgoStats] = field(default_factory=dict)

    def stats_for(self, a: str) -> _AlgoStats:
        st = self.algos.get(a)
        if st is None:
            st = self.algos[a] = _AlgoStats()
        return st

    @property
    def quantized(self) -> bool:
        return any(c.endswith("_q8") for c in self.candidates)

    @property
    def key(self) -> str:
        base = f"{self.op}|{self.size_label}|w{self.world_size}|{self.topology}"
        return base + ("|q8" if self.quantized else "")


class CollectiveTuner:
    """Process-wide selection state, bucketed by
    (op, size bucket, world size, topology kind)."""

    def __init__(self, enabled: Optional[bool] = None,
                 min_attempts: int = MIN_ATTEMPTS):
        self._lock = threading.Lock()
        self._buckets: Dict[tuple, _Bucket] = {}
        self._enabled = enabled
        self.min_attempts = min_attempts

    # ------------------------------------------------------------- config
    @property
    def enabled(self) -> bool:
        if self._enabled is not None:
            return self._enabled
        from ..core.config import GlobalConfig

        return GlobalConfig.collective_autotune

    # ------------------------------------------------------------ selection
    def _bucket(self, op: str, nbytes: int, world_size: int, topology,
                candidates: Tuple[str, ...]) -> _Bucket:
        label = size_bucket(nbytes)
        kind = topology.kind if topology is not None else "ici"
        key = (op, label, world_size, kind, candidates)
        b = self._buckets.get(key)
        if b is None:
            b = self._buckets[key] = _Bucket(
                op, label, world_size, kind, candidates
            )
        return b

    def select(self, op: str, nbytes: int, world_size: int, topology,
               candidates: Tuple[str, ...],
               sync: Optional[Callable] = None) -> dict:
        """Pick the algorithm for one op call.  Returns a decision dict
        ``{algo, bucket, topology, explored}``; ``sync``, when given, is
        an allreduce-MEAN over group members used at commit points (see
        module docstring)."""
        heuristic = heuristic_choice(op, nbytes, world_size, topology,
                                     candidates)
        with self._lock:
            b = self._bucket(op, nbytes, world_size, topology, candidates)
            b.calls += 1
            explored = False
            if len(candidates) == 1:
                algo = candidates[0]
                b.committed = algo  # nothing to tune
            elif not self.enabled:
                algo = heuristic  # static table only
            elif b.committed is None:
                # Explore phase: round-robin the least-attempted candidate
                # (heuristic first on ties via ordering below); commit once
                # every candidate has min_attempts attempts.
                if all(
                    b.stats_for(c).attempts >= self.min_attempts
                    for c in candidates
                ):
                    algo = self._commit(b, heuristic, sync)
                else:
                    order = [heuristic] + [
                        c for c in candidates if c != heuristic
                    ]
                    algo = min(order, key=lambda c: b.stats_for(c).attempts)
                    explored = True
                    b.explorations += 1
            else:
                if b.pending_recommit:
                    # The call after a decayed probe: fold the probe's
                    # measurement in and re-evaluate the argmax (synced).
                    b.pending_recommit = False
                    algo = self._commit(b, heuristic, sync)
                elif b.calls >= b.next_probe:
                    # Decaying re-exploration: probe the least-recently
                    # attempted non-committed candidate.
                    others = [c for c in candidates if c != b.committed]
                    algo = min(
                        others, key=lambda c: b.stats_for(c).attempts
                    )
                    explored = True
                    b.explorations += 1
                    b.pending_recommit = True
                    interval = min(
                        max(b.next_probe - b.committed_at, 1) * 2,
                        REPROBE_MAX_INTERVAL,
                    )
                    b.next_probe = b.calls + interval
                else:
                    algo = b.committed
            b.stats_for(algo).attempts += 1
            decision = {
                "algo": algo,
                "bucket": b.size_label,
                "topology": b.topology,
                "explored": explored,
            }
        self._record_decision(op, decision)
        return decision

    def _commit(self, b: _Bucket, heuristic: str,
                sync: Optional[Callable]) -> str:
        """Commit (or re-commit) to the measured-best algorithm.  With a
        ``sync`` callback the per-candidate (bw_sum, samples) table is
        averaged across group members first so every member computes the
        same argmax.  Called under the lock at deterministic call
        indices."""
        sums = np.array(
            [b.stats_for(c).bw_sum for c in b.candidates], np.float64
        )
        counts = np.array(
            [b.stats_for(c).samples for c in b.candidates], np.float64
        )
        if sync is not None:
            # One vector, one tiny allreduce; MEAN keeps magnitudes sane.
            vec = np.concatenate([sums, counts])
            try:
                vec = np.asarray(sync(vec), np.float64)
                sums, counts = vec[: len(sums)], vec[len(sums):]
            except Exception:  # noqa: BLE001 — a failed sync must not
                # break the op; fall back to local measurements (members
                # may then diverge only if their local argmaxes differ,
                # which the next synced commit repairs).
                from ..util import flight_recorder

                flight_recorder.count_suppressed("collective_tuner_sync")
        means = np.where(counts > 0, sums / np.maximum(counts, 1), 0.0)
        if means.max() > 0:
            chosen = b.candidates[int(np.argmax(means))]
        else:
            chosen = heuristic  # no measurements (recorder off)
        b.committed = chosen
        b.committed_at = b.calls
        b.commits += 1
        if not b.next_probe or b.next_probe <= b.calls:
            b.next_probe = b.calls * 2
        self._record_commit(b, chosen, float(means.max()))
        return chosen

    # ----------------------------------------------------------- feedback
    def observe(self, op: str, nbytes: int, world_size: int, topology,
                algo: str, bandwidth: float, cold: bool = False) -> None:
        """One achieved-bandwidth sample from the flight recorder's
        per-op capture.  Cold samples (first call of a compiled shape —
        the duration is trace+compile) are excluded from the tuner's
        bandwidth table."""
        if cold or bandwidth <= 0:
            return
        candidates = alg.candidates_for(
            op, world_size, topology,
            quantized=algo in (alg.FLAT_Q8, alg.TWO_LEVEL_Q8),
        )
        with self._lock:
            b = self._bucket(op, nbytes, world_size, topology, candidates)
            st = b.stats_for(algo)
            st.samples += 1
            st.bw_sum += bandwidth

    def force_reprobe(self, op: Optional[str] = None) -> int:
        """Arm an immediate re-probe on every committed multi-candidate
        bucket (optionally restricted to one ``op``): the next call in
        each bucket explores an alternative and the call after re-commits
        to the measured argmax — the SLO remediation path for bandwidth
        drift, skipping the geometric wait.

        SPMD caveat: arming ONE member of a multi-member group makes its
        call sequence diverge from its peers until the next synced
        commit.  The remediation broadcast therefore fans the directive
        to EVERY worker process (node-agent ``remediate`` fan-out), so
        members re-probe in lockstep and the synced re-commit realigns
        any residue.  Returns the number of buckets armed."""
        armed = 0
        with self._lock:
            for b in self._buckets.values():
                if op is not None and b.op != op:
                    continue
                if b.committed is None or len(b.candidates) <= 1:
                    continue
                b.next_probe = b.calls + 1
                b.pending_recommit = False
                armed += 1
        return armed

    # -------------------------------------------------------------- export
    def stats(self) -> Dict[str, dict]:
        """Per-bucket decision table keyed ``op|bucket|w<world>|<topo>``:
        chosen algorithm, call/exploration counts, and the per-algorithm
        attempts/samples/mean-bandwidth table."""
        out: Dict[str, dict] = {}
        with self._lock:
            for b in self._buckets.values():
                out[b.key] = {
                    "op": b.op,
                    "bucket": b.size_label,
                    "world_size": b.world_size,
                    "topology": b.topology,
                    "quantized": b.quantized,
                    "chosen": b.committed,
                    "calls": b.calls,
                    "explorations": b.explorations,
                    "commits": b.commits,
                    "algorithms": {
                        a: {
                            "attempts": st.attempts,
                            "samples": st.samples,
                            "mean_bandwidth_bytes_per_s": round(st.mean_bw, 1),
                        }
                        for a, st in sorted(b.algos.items())
                    },
                }
        return out

    def reset(self) -> None:
        with self._lock:
            self._buckets.clear()

    # ------------------------------------------------------------- metrics
    def _record_decision(self, op: str, decision: dict) -> None:
        from ..util import flight_recorder

        flight_recorder.counter(
            flight_recorder.COLLECTIVE_ALGO_OPS_TOTAL, 1.0,
            {"op": op, "algo": decision["algo"],
             "bucket": decision["bucket"],
             "topology": decision["topology"]},
        )
        if decision["explored"]:
            flight_recorder.counter(
                flight_recorder.COLLECTIVE_TUNER_EXPLORATIONS_TOTAL, 1.0,
                {"op": op, "bucket": decision["bucket"]},
            )

    def _record_commit(self, b: _Bucket, chosen: str, best_bw: float) -> None:
        from ..util import flight_recorder

        tags = {"op": b.op, "bucket": b.size_label, "topology": b.topology}
        flight_recorder.counter(
            flight_recorder.COLLECTIVE_TUNER_COMMITS_TOTAL, 1.0,
            {**tags, "algo": chosen},
        )
        if best_bw > 0:
            flight_recorder.gauge(
                flight_recorder.COLLECTIVE_TUNER_BEST_BANDWIDTH, best_bw,
                tags,
            )


_tuner: Optional[CollectiveTuner] = None
_tuner_lock = threading.Lock()


def get_tuner() -> CollectiveTuner:
    global _tuner
    if _tuner is None:
        with _tuner_lock:
            if _tuner is None:
                _tuner = CollectiveTuner()
    return _tuner


def select_for_group(group, op: str, per_rank_nbytes: int,
                     quantized: bool = False,
                     sync: Optional[Callable] = None) -> str:
    """One tuner decision for a group op: build the candidate set from
    the group's world/topology, select, and stamp the decision on
    ``group._last_decision`` where the flight-recorder wrapper picks it
    up (record tags + the bandwidth observation feed).  Shared by both
    group backends."""
    cands = alg.candidates_for(
        op, group.world_size, group.topology, quantized
    )
    dec = get_tuner().select(
        op, per_rank_nbytes, group.world_size, group.topology, cands,
        sync=sync,
    )
    dec["nbytes"] = per_rank_nbytes
    dec["world_size"] = group.world_size
    dec["quantized"] = quantized
    group._last_decision = dec
    return dec["algo"]


def reset_tuner() -> None:
    """Drop all buckets (tests / bench stages)."""
    get_tuner().reset()
