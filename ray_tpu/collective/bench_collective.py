"""Collective algorithm-selection bench stage (``bench.py collective``).

Runs in a subprocess (the virtual-device flags must bind before jax
imports) and prints one JSON line per record; ``bench.py`` parses them
into the harness summary.  Full mode expects an 8-device CPU mesh
(``xla_force_host_platform_device_count=8``) and treats it as 2 "slices"
of 4 (``slice_size=4``) so the inter-slice axis stands in for DCN — the
controllable part of the 2-slice story on a box without two real slices
(same methodology as the scaling suite).

Stages:
  1. **per-algorithm A/B** — device-side steady-state bandwidth of every
     eligible allreduce algorithm on pre-staged arrays (times the
     collective executable itself, not host staging) at the headline
     payload.  The flat ``psum`` row is the pre-selection baseline.
  2. **tuner loop** — the production feedback cycle against those real
     measurements: ``select`` -> run the selected algorithm -> ``observe``
     the achieved bandwidth, until the tuner commits.  The headline
     record is the committed algorithm's bandwidth with the flat row as
     ``baseline`` — the ``vs`` ratio is the selection layer's win on this
     fabric (>= 1 by construction at steady state: flat is a candidate).
  3. **quantized** — the opt-in block-quantized allreduce: bandwidth,
     wire-byte reduction, max abs error vs the exact sum.
  4. **group end-to-end** — the user-facing ``allreduce()`` path
     (host-staged per-rank lists) exercising selection + stats + metrics;
     recorded for completeness, not compared against stage 1.

``--quick`` is the tier-1 smoke: whatever devices exist (1 on a plain
``JAX_PLATFORMS=cpu`` run), tiny payloads, a handful of iterations —
checks the machinery end to end, makes no bandwidth claims.
"""

from __future__ import annotations

import json
import sys
import time


def _emit(record: dict) -> None:
    print(json.dumps({"collective": record}), flush=True)


def _steady_bw(fn, nbytes: int, warmup: int = 2, iters: int = 8) -> float:
    """Steady-state bandwidth (best-of-iters sheds scheduler noise)."""
    for _ in range(warmup):
        fn()
    best = 0.0
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        if dt > 0:
            best = max(best, nbytes / dt)
    return best


def _one_bw(fn, nbytes: int) -> float:
    t0 = time.perf_counter()
    fn()
    return nbytes / max(time.perf_counter() - t0, 1e-9)


def main(quick: bool = False) -> None:
    import jax
    import numpy as np

    import ray_tpu.collective as col
    from ray_tpu.collective import algorithms as alg
    from ray_tpu.collective.tuner import get_tuner, reset_tuner
    from ray_tpu.collective.types import Topology, compat_shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n = len(jax.devices())
    two_level_ok = not quick and n >= 8 and n % 4 == 0
    ici = 4 if two_level_ok else n
    topo = Topology(n, ici)
    elems = 4 * 1024 if quick else 256 * 1024  # fp32/rank: 16KiB / 1MiB
    iters = 4 if quick else 8

    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("world",))
    stack = np.random.default_rng(0).normal(size=(n, elems)).astype(
        np.float32
    )
    total_bytes = stack.nbytes
    g1 = jax.device_put(stack, NamedSharding(mesh, P("world")))
    g2 = None
    if topo.is_two_level:
        mesh2 = Mesh(devs.reshape(topo.dcn_size, topo.ici_size),
                     ("dcn", "ici"))
        g2 = jax.device_put(stack, NamedSharding(mesh2, P(("dcn", "ici"))))

    def build(algo: str):
        """(callable, input) running one device-side allreduce."""
        if algo in (alg.TWO_LEVEL, alg.TWO_LEVEL_Q8):
            fn = jax.jit(compat_shard_map(
                lambda t: alg.two_level_allreduce(
                    t[0], "ici", "dcn", topo.ici_size,
                    quantized=(algo == alg.TWO_LEVEL_Q8),
                )[None],
                mesh2, (P(("dcn", "ici")),), P(("dcn", "ici")),
            ))
            arr = g2
        else:
            body = {
                alg.FLAT: lambda t: jax.lax.psum(t, "world"),
                alg.RING: lambda t: alg.ring_allreduce(
                    t[0], "world", n)[None],
                alg.TREE: lambda t: alg.tree_allreduce(
                    t[0], "world", n)[None],
                alg.FLAT_Q8: lambda t: alg.quantized_allreduce(
                    t[0], "world")[None],
            }[algo]
            fn = jax.jit(compat_shard_map(
                body, mesh, (P("world"),), P("world")))
            arr = g1
        return (lambda: jax.block_until_ready(fn(arr)))

    # ---- stage 1: device-side per-algorithm A/B --------------------------
    candidates = alg.allreduce_candidates(n, topo)
    runners = {a: build(a) for a in candidates}
    ab = {a: _steady_bw(runners[a], total_bytes, iters=iters)
          for a in candidates}
    flat_bw = ab[alg.FLAT]
    _emit({
        "metric": "collective_allreduce_algo_ab",
        "bandwidth_bytes_per_s": {a: round(bw, 1) for a, bw in ab.items()},
        "world": n, "slices": topo.dcn_size,
        "payload_bytes_per_rank": elems * 4,
    })

    # ---- stage 2: tuner loop on real measurements ------------------------
    reset_tuner()
    tuner = get_tuner()
    nbytes_rank = elems * 4
    committed = None
    for _ in range(48 if not quick else 8):
        dec = tuner.select("allreduce", nbytes_rank, n, topo, candidates)
        bw = _one_bw(runners[dec["algo"]], total_bytes)
        tuner.observe("allreduce", nbytes_rank, n, topo, dec["algo"], bw)
        committed = dec["algo"] if not dec["explored"] else committed
    chosen = next(iter(tuner.stats().values()))["chosen"] or committed
    # Same-window interleaved comparison: this box's throughput swings
    # 2x between measurement windows, so the tuned-vs-flat ratio is only
    # meaningful when both sides share one window.  chosen == flat means
    # the tuner (correctly) kept the baseline — ratio exactly 1.0.
    if chosen == alg.FLAT:
        chosen_bw = flat_same = _steady_bw(
            runners[alg.FLAT], total_bytes, iters=iters
        )
    else:
        flat_w, chosen_w = [], []
        for _ in range(max(iters, 6)):
            flat_w.append(_one_bw(runners[alg.FLAT], total_bytes))
            chosen_w.append(_one_bw(runners[chosen], total_bytes))
        flat_same, chosen_bw = max(flat_w), max(chosen_w)
    _emit({
        "metric": "collective_dcn_allreduce_bytes_per_s"
        if topo.is_two_level else "collective_allreduce_bytes_per_s",
        "value": chosen_bw, "baseline": flat_same, "chosen": chosen,
        "topology": topo.kind, "decisions": tuner.stats(),
    })

    # ---- stage 3: quantized allreduce ------------------------------------
    qalgo = alg.TWO_LEVEL_Q8 if topo.is_two_level else alg.FLAT_Q8
    qrun = build(qalgo)
    quant_bw = _steady_bw(qrun, total_bytes, iters=iters)
    # Correctness probe vs the exact fp32 sum (pre-staged device run).
    ref = stack.sum(axis=0)
    qfn_out = None
    if qalgo == alg.TWO_LEVEL_Q8:
        qfn = jax.jit(compat_shard_map(
            lambda t: alg.two_level_allreduce(
                t[0], "ici", "dcn", topo.ici_size, quantized=True)[None],
            mesh2, (P(("dcn", "ici")),), P(("dcn", "ici"))))
        qfn_out = np.asarray(qfn(g2))
    else:
        qfn = jax.jit(compat_shard_map(
            lambda t: alg.quantized_allreduce(t[0], "world")[None],
            mesh, (P("world"),), P("world")))
        qfn_out = np.asarray(qfn(g1))
    err = float(np.abs(qfn_out[0] - ref).max())
    rel = err / max(float(np.abs(ref).max()), 1e-9)
    _emit({
        "metric": "collective_allreduce_quantized_bytes_per_s",
        "value": quant_bw, "algo": qalgo, "max_abs_error": round(err, 6),
        "max_rel_error": round(rel, 6),
        "wire_bytes_per_rank": alg.quantized_wire_bytes(
            nbytes_rank, np.dtype(np.float32)),
        "logical_bytes_per_rank": nbytes_rank,
    })

    # ---- stage 4: user-facing group path (selection + stats + metrics) ---
    reset_tuner()
    group = col.init_local_group(
        "bench", slice_size=topo.ici_size if topo.is_two_level else None
    )
    x = [np.full((elems,), float(i + 1), np.float32) for i in range(n)]
    expected = n * (n + 1) / 2.0

    def run_group():
        out = group.allreduce(x)
        assert float(np.asarray(out[0]).reshape(-1)[0]) == expected

    for _ in range(24 if not quick else 6):
        run_group()
    e2e_bw = _steady_bw(run_group, total_bytes, iters=iters)
    stats = col.collective_stats()
    _emit({
        "metric": "collective_group_allreduce_e2e_bytes_per_s",
        "value": e2e_bw,
        "tuner_buckets": sum(
            1 for r in stats["tuner"].values() if r["chosen"]
        ),
        "ops_recorded": stats.get("allreduce", {}).get("ops", 0),
    })
    col.destroy_collective_group("bench")


if __name__ == "__main__":
    main(quick="--quick" in sys.argv)
