"""Stage-boundary point-to-point channels over the worker RPC plane.

The pipeline-parallel trainer (``ray_tpu.train.pipeline``) streams
activations and gradients between adjacent stage actors.  The existing
``collective.send/recv`` path stages every tensor through a named Queue
actor — one extra process hop and two extra copies per message.  This
module is the direct path: the sender serializes the value once into a
``SerializedPayload`` (pickle-5 out-of-band buffers) and pushes it
straight to the receiving worker's RPC server, where framing v2 delivers
the buffers as memoryviews into the read buffer.  No intermediate
``bytes()`` copies on either side, and ``pipeline_push`` is lane-safe
(PR 6), so microbatch traffic never queues behind the receiving
process's control loop.

Addressing: edges are named (``"<tag>:<src>-><dst>"``) and messages are
keyed by an application sequence id (the pipeline uses ``(step,
microbatch)``), so a late or duplicate delivery can never be confused
with the next step's tensor.  Same-process edges short-circuit through
the local mailbox without serializing (and without an RPC), which also
lets the scheduler unit tests run without a cluster.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..core.serialization import SerializedPayload, serialize_payload

logger = logging.getLogger(__name__)

_RECV_POLL_S = 1.0  # condition re-check cadence while waiting for a message


class _TracedMsg:
    """In-process envelope for a pushed message that carries the sender's
    trace context.  Never serialized: the remote path ships the context
    as a sibling field of the push RPC and this wrapper is rebuilt on the
    receiving side (``deposit_push``), so the wire format of untraced
    pushes is unchanged."""

    __slots__ = ("value", "trace", "deposit_ts")

    def __init__(self, value, trace, deposit_ts):
        self.value = value
        self.trace = trace  # sender's (trace_id, span_id)
        self.deposit_ts = deposit_ts


def _consume_traced(edge: str, seq, value):
    """Unwrap a traced message at take time, stitching the cross-process
    edge: records a ``p2p.recv`` span parented to the SENDER's span (the
    deposit→consume interval on the receiving process)."""
    if type(value) is not _TracedMsg:
        return value
    from ..util import tracing

    tracing.record_span(
        f"p2p.recv:{edge}", value.deposit_ts, time.time(),
        {"edge": edge, "seq": str(seq)}, context=value.trace,
    )
    return value.value


def deposit_push(edge: str, seq, data, trace=None) -> None:
    """RPC-server side of ``pipeline_push``: park the (still-serialized)
    payload, wrapping it with the sender's trace context when the push
    carried one.  Lane-safe — one dict insert + notify."""
    if trace is not None:
        data = _TracedMsg(data, tuple(trace), time.time())
    local_mailbox().deposit(edge, seq, data)


class Mailbox:
    """Process-local buffer of pushed messages, keyed (edge, seq).

    ``deposit`` is called from the RPC server (any lane thread);
    ``take`` blocks the consuming actor thread until the message lands
    or the deadline passes.  Values are stored exactly as pushed — a
    ``SerializedPayload`` stays serialized until the consumer takes it,
    so the deposit path never pays deserialization on a lane thread.
    """

    def __init__(self):
        from ..util.debug_locks import make_condition

        self._cond = make_condition("p2p-mailbox")
        self._slots: Dict[Tuple[str, Any], Any] = {}

    def deposit(self, edge: str, seq, value) -> None:
        with self._cond:
            self._slots[(edge, seq)] = value
            self._cond.notify_all()

    def take(self, edge: str, seq, timeout: float):
        """Remove and return the (edge, seq) message; TimeoutError if it
        has not arrived within ``timeout`` seconds."""
        deadline = time.monotonic() + timeout
        key = (edge, seq)
        with self._cond:
            while key not in self._slots:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"p2p recv timed out after {timeout:.1f}s waiting "
                        f"for edge {edge!r} seq {seq!r}"
                    )
                self._cond.wait(timeout=min(_RECV_POLL_S, remaining))
            value = self._slots.pop(key)
        return _consume_traced(edge, seq, value)

    def try_take_latest(self, edge: str):
        """Non-blocking: remove and return ``(seq, value)`` for the
        HIGHEST seq parked on ``edge``, discarding older ones (parameter
        broadcast: a runner that slept through three versions wants the
        newest, not a replay).  Seqs on one edge must be mutually
        comparable (the broadcast path uses ints).  None if empty."""
        with self._cond:
            keys = [k for k in self._slots if k[0] == edge]
            if not keys:
                return None
            best = max(keys, key=lambda k: k[1])
            value = self._slots.pop(best)
            for k in keys:
                if k != best:
                    del self._slots[k]
        return best[1], _consume_traced(edge, best[1], value)

    def drop_prefix(self, prefix: str) -> int:
        """Discard every parked message whose edge name starts with
        ``prefix`` (stage restart: a new generation must not consume the
        aborted generation's tensors).  Returns the number dropped."""
        with self._cond:
            victims = [k for k in self._slots if k[0].startswith(prefix)]
            for k in victims:
                del self._slots[k]
            return len(victims)

    def __len__(self):
        with self._cond:
            return len(self._slots)


_mailbox: Optional[Mailbox] = None
_mailbox_lock = threading.Lock()


def local_mailbox() -> Mailbox:
    global _mailbox
    if _mailbox is None:
        with _mailbox_lock:
            if _mailbox is None:
                _mailbox = Mailbox()
    return _mailbox


class StageChannel:
    """One process's endpoint for named p2p edges.

    ``send`` is asynchronous: the payload is serialized on the calling
    thread (capture-at-call semantics) and the RPC rides the worker's
    event loop; ``flush`` awaits every outstanding ack and surfaces the
    first error.  ``recv`` blocks on the local mailbox.  Peers are
    addressed by their worker RPC address (``rpc_address()`` of the
    process hosting the peer actor).
    """

    def __init__(self, tag: str, recv_timeout_s: float = 120.0):
        self.tag = tag
        self.recv_timeout_s = recv_timeout_s
        self._pending: List[tuple] = []  # (future, nbytes, t_send)
        self._sent_msgs = 0
        self._sent_bytes = 0
        self._local_msgs = 0

    # ------------------------------------------------------------ addressing
    @staticmethod
    def self_address() -> str:
        """This process's worker RPC address ('' outside a cluster)."""
        from ..core.core_worker import try_global_worker

        w = try_global_worker()
        return w.address if w is not None else ""

    def edge(self, src, dst) -> str:
        return f"{self.tag}:{src}->{dst}"

    # ----------------------------------------------------------------- send
    def send(self, edge: str, seq, value, dst_address: str,
             timeout: Optional[float] = None) -> None:
        """Push ``value`` for (edge, seq) to the worker at
        ``dst_address``.  Empty/self address delivers locally without
        serializing."""
        if not dst_address or dst_address == self.self_address():
            deposit_push(edge, seq, value, self._trace_ctx())
            self._local_msgs += 1
            return
        # Zero-copy capture: the payload's buffers are NOT snapshotted —
        # the caller must not mutate them until flush() (pipeline sends
        # are fresh host views of immutable jax arrays, so this holds by
        # construction and saves one full copy per activation).
        payload = serialize_payload(value, prefer_plain=True)
        self._push_remote(edge, seq, payload, dst_address, timeout)

    @staticmethod
    def _trace_ctx():
        """Sender's trace context, propagated with every push so the
        receiving process can stitch the p2p edge into the same trace."""
        from ..util import tracing

        return tracing.current_context()

    def _push_remote(self, edge: str, seq, payload: SerializedPayload,
                     dst_address: str, timeout: Optional[float]) -> None:
        import asyncio

        from ..core.core_worker import global_worker

        nbytes = payload.nbytes
        worker = global_worker()
        client = worker.worker_clients.get(dst_address)
        msg = {"edge": edge, "seq": seq, "data": payload}
        trace = self._trace_ctx()
        if trace is not None:
            msg["trace"] = trace
        fut = asyncio.run_coroutine_threadsafe(
            client.call(
                "pipeline_push",
                msg,
                timeout=timeout or self.recv_timeout_s,
            ),
            worker.loop,
        )
        self._pending.append((fut, nbytes, time.perf_counter()))
        self._sent_msgs += 1
        self._sent_bytes += nbytes

    def broadcast(self, seq, value, destinations,
                  timeout: Optional[float] = None) -> int:
        """Fan ``value`` out to many endpoints, serializing ONCE.

        ``destinations`` is an iterable of ``(edge, dst_address)``; the
        same ``SerializedPayload`` (same out-of-band buffer views) backs
        every remote push, so an N-runner parameter broadcast pays one
        serialization however wide the fan-out.  Local endpoints get
        the raw value deposited directly.  Returns the serialized size
        in bytes (0 if every destination was local).  Like ``send``,
        delivery is async — ``flush()`` collects the acks.
        """
        payload = None
        for edge, addr in destinations:
            if not addr or addr == self.self_address():
                deposit_push(edge, seq, value, self._trace_ctx())
                self._local_msgs += 1
                continue
            if payload is None:
                payload = serialize_payload(value, prefer_plain=True)
            self._push_remote(edge, seq, payload, addr, timeout)
        return payload.nbytes if payload is not None else 0

    def flush(self, timeout: Optional[float] = None) -> None:
        """Wait for every in-flight push to be acknowledged; raises the
        first delivery error.  Records achieved per-push bandwidth."""
        from ..util import flight_recorder

        pending, self._pending = self._pending, []
        deadline = time.monotonic() + (timeout or self.recv_timeout_s)
        err = None
        for fut, nbytes, t0 in pending:
            remaining = max(0.1, deadline - time.monotonic())
            try:
                fut.result(timeout=remaining)
                dt = time.perf_counter() - t0
                if nbytes and dt > 0:
                    flight_recorder.record_pipeline_transfer(nbytes, dt)
            except Exception as e:  # noqa: BLE001 — surfaced after drain
                if err is None:
                    err = e
        if err is not None:
            raise err

    # ----------------------------------------------------------------- recv
    def recv(self, edge: str, seq, timeout: Optional[float] = None):
        """Blocking receive of the (edge, seq) message pushed to THIS
        process.  Deserializes payloads on the consuming thread."""
        value = local_mailbox().take(
            edge, seq, timeout if timeout is not None else self.recv_timeout_s
        )
        if type(value) is SerializedPayload:
            return value.deserialize()
        return value

    # ------------------------------------------------------------- lifecycle
    def reset(self) -> int:
        """Abandon in-flight sends and drop every parked message under
        this channel's tag (stage restart / new schedule generation)."""
        for fut, _nbytes, _t0 in self._pending:
            fut.cancel()
        self._pending = []
        return local_mailbox().drop_prefix(f"{self.tag}:")

    def stats(self) -> Dict[str, int]:
        return {
            "sent_msgs": self._sent_msgs,
            "sent_bytes": self._sent_bytes,
            "local_msgs": self._local_msgs,
        }
