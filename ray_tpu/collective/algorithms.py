"""Collective algorithm library — the lowering targets of the selection
layer (``tuner.py``).

Every ``allreduce``/``allgather``/``reducescatter`` used to lower to a
single flat XLA collective (``psum``/``all_gather``/``psum_scatter``)
regardless of message size, world size, or whether the hop rides
intra-slice ICI or cross-slice DCN.  TACCL (arxiv 2111.04867) shows no
single algorithm wins across that space; this module provides the
alternatives, each as a pure function usable inside a ``shard_map`` body
(and therefore inside any jitted user step):

  ``flat``       one fused XLA collective — latency-optimal for small
                 messages (the compiler schedules the ring itself).
  ``ring``       chunked ``ppermute`` pipeline: 2(n-1) steps moving
                 ``size/n`` bytes each — bandwidth-optimal for large
                 messages, and the stages overlap.
  ``tree``       recursive halving-doubling: 2·log2(n) steps — fewer
                 rounds than ring for latency-bound mid sizes; requires a
                 power-of-two world.
  ``two_level``  hierarchical decomposition for multi-slice topologies:
                 reduce-scatter over the intra-slice (ICI) axis, exchange
                 only ``size/n_ici`` bytes over the inter-slice (DCN)
                 axis, all-gather back over ICI — the DCN hop, the
                 bottleneck, carries 1/n_ici of the payload.
  ``*_q8``       EQuARX-style block-quantized variants (arxiv
                 2506.17615): int8 blocks with per-block fp32 scales cut
                 wire bytes ~4x on bandwidth-bound gradient exchange with
                 a bounded per-block error (see ``docs/collective.md``).
                 Opt-in only — SUM is exact when quantization is off.

All non-flat algorithms are SUM-only; other reduce ops keep the flat
lowering.  Numerical note: ring/tree/two_level reassociate the sum, so
float results can differ from flat psum by normal rounding — integer-
valued payloads reduce exactly.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .types import ReduceOp

# Per-block quantization width (elements).  128-1024 trades scale
# overhead (4 bytes per block) against outlier blast radius; EQuARX uses
# comparable block shapes.
DEFAULT_QUANT_BLOCK = 256

# Algorithm names (the tuner's candidate vocabulary).
FLAT = "flat"
RING = "ring"
TREE = "tree"
TWO_LEVEL = "two_level"
FLAT_Q8 = "flat_q8"
TWO_LEVEL_Q8 = "two_level_q8"

_QUANT_DTYPES = ("float32", "bfloat16", "float16")


def is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def quantizable_dtype(dtype) -> bool:
    return str(dtype) in _QUANT_DTYPES


def allreduce_candidates(world_size: int, topology,
                         quantized: bool = False) -> Tuple[str, ...]:
    """Eligible allreduce algorithms for (world, topology, quantized) —
    deterministic order; the first entry is the safe default."""
    if world_size <= 1:
        return (FLAT,)
    if quantized:
        # Quantization targets the bandwidth-bound exchange: the DCN hop
        # of a two-level decomposition when the topology has one, else
        # the gather-based one-shot.
        if topology is not None and topology.is_two_level:
            return (TWO_LEVEL_Q8, FLAT_Q8)
        return (FLAT_Q8,)
    cands = [FLAT, RING]
    if is_pow2(world_size):
        cands.append(TREE)
    if topology is not None and topology.is_two_level:
        cands.append(TWO_LEVEL)
    return tuple(cands)


def allgather_candidates(world_size: int, topology) -> Tuple[str, ...]:
    if world_size <= 1:
        return (FLAT,)
    return (FLAT, RING)


def reducescatter_candidates(world_size: int, topology) -> Tuple[str, ...]:
    if world_size <= 1:
        return (FLAT,)
    return (FLAT, RING)


def candidates_for(op: str, world_size: int, topology,
                   quantized: bool = False) -> Tuple[str, ...]:
    if op == "allreduce":
        return allreduce_candidates(world_size, topology, quantized)
    if op == "allgather":
        return allgather_candidates(world_size, topology)
    if op == "reducescatter":
        return reducescatter_candidates(world_size, topology)
    return (FLAT,)


def resolve_quantized(op: ReduceOp, dtype, quantized) -> bool:
    """Resolve a per-call ``quantized`` flag (None = process default)
    and validate eligibility.  The blanket process opt-in silently skips
    ineligible payloads (int, non-SUM); an EXPLICIT ``quantized=True``
    on an ineligible call raises.  Shared by both group backends."""
    if quantized is None:
        from ..core.config import GlobalConfig

        quantized = GlobalConfig.collective_quantized_allreduce
        if quantized and not (
            op == ReduceOp.SUM and quantizable_dtype(dtype)
        ):
            return False
    if quantized:
        if op != ReduceOp.SUM:
            raise ValueError(
                f"quantized allreduce supports SUM only (got {op})"
            )
        if not quantizable_dtype(dtype):
            raise ValueError(
                f"quantized allreduce needs a float payload, got {dtype}"
            )
    return bool(quantized)


# ------------------------------------------------------------ shape plumbing
def _pad_flat(x, multiple):
    """Flatten ``x`` and zero-pad to a multiple; returns (flat, orig_size)."""
    import jax.numpy as jnp

    flat = x.reshape(-1)
    pad = (-flat.size) % multiple
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat, x.size


def _unpad(flat, size, shape):
    return flat[:size].reshape(shape)


# --------------------------------------------------------------- ring family
def ring_allreduce(x, axis: str, n: int):
    """Bandwidth-optimal ring: n-1 reduce-scatter steps + n-1 all-gather
    steps, each moving one 1/n chunk over ``ppermute``."""
    import jax
    import jax.numpy as jnp

    if n <= 1:
        return x
    flat, size = _pad_flat(x, n)
    chunks = flat.reshape(n, -1)
    csize = chunks.shape[1]
    r = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def rs_step(s, send):
        recv = jax.lax.ppermute(send, axis, perm)
        idx = jnp.mod(r - s - 1, n)
        return recv + jax.lax.dynamic_slice(chunks, (idx, 0), (1, csize))[0]

    send = jax.lax.dynamic_slice(chunks, (jnp.mod(r, n), 0), (1, csize))[0]
    send = jax.lax.fori_loop(0, n - 1, rs_step, send)
    # ``send`` now holds the fully reduced chunk (r+1) mod n.
    out = jnp.zeros_like(chunks)
    out = jax.lax.dynamic_update_slice(
        out, send[None], (jnp.mod(r + 1, n), 0)
    )

    def ag_step(s, carry):
        out, buf = carry
        buf = jax.lax.ppermute(buf, axis, perm)
        idx = jnp.mod(r - s, n)
        out = jax.lax.dynamic_update_slice(out, buf[None], (idx, 0))
        return out, buf

    out, _ = jax.lax.fori_loop(0, n - 1, ag_step, (out, send))
    return _unpad(out.reshape(-1), size, x.shape)


def ring_reducescatter(x, axis: str, n: int):
    """Rank r keeps chunk r (axis-0 split) of the elementwise sum — the
    reduce-scatter half of the ring.  ``x.shape[0]`` must divide by n."""
    import jax
    import jax.numpy as jnp

    if n <= 1:
        return x
    rows = x.shape[0] // n
    chunks = x.reshape(n, rows, *x.shape[1:])
    r = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(s, send):
        recv = jax.lax.ppermute(send, axis, perm)
        idx = jnp.mod(r - s - 1, n)
        return recv + jax.lax.dynamic_index_in_dim(
            chunks, idx, keepdims=False
        )

    send = jax.lax.dynamic_index_in_dim(chunks, jnp.mod(r, n), keepdims=False)
    send = jax.lax.fori_loop(0, n - 1, step, send)
    # After n-1 steps rank r holds reduced chunk (r+1)%n; one final shift
    # aligns chunk r with rank r (matching psum_scatter's layout).
    return jax.lax.ppermute(send, axis, perm)


def ring_allgather(x, axis: str, n: int):
    """All ranks end with the (n, *shape) stack of every rank's tensor,
    built by circulating tensors n-1 hops around the ring."""
    import jax
    import jax.numpy as jnp

    if n <= 1:
        return x[None]
    r = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    out = jnp.zeros((n, *x.shape), x.dtype)
    out = jax.lax.dynamic_update_index_in_dim(out, x, r, 0)

    def step(s, carry):
        out, buf = carry
        buf = jax.lax.ppermute(buf, axis, perm)
        idx = jnp.mod(r - s - 1, n)
        out = jax.lax.dynamic_update_index_in_dim(out, buf, idx, 0)
        return out, buf

    out, _ = jax.lax.fori_loop(0, n - 1, step, (out, x))
    return out


# ---------------------------------------------------------------- tree family
def tree_allreduce(x, axis: str, n: int):
    """Recursive halving-doubling (a butterfly over rank-XOR partners):
    log2(n) halving-reduce steps then log2(n) doubling-gather steps.
    Requires power-of-two ``n``; the Python-level loop keeps every
    intermediate shape static."""
    import jax
    import jax.numpy as jnp

    if n <= 1:
        return x
    assert is_pow2(n), f"tree allreduce needs a power-of-two world, got {n}"
    flat, size = _pad_flat(x, n)
    r = jax.lax.axis_index(axis)
    buf = flat
    d = n // 2
    while d >= 1:
        perm = [(i, i ^ d) for i in range(n)]
        half = buf.shape[0] // 2
        low, high = buf[:half], buf[half:]
        bit = jnp.asarray(r & d, bool)
        # Bit clear -> this rank owns the LOW half after the step: it
        # sends the high half and reduces into the low.  Bit set: mirror.
        send = jnp.where(bit, low, high)
        keep = jnp.where(bit, high, low)
        recv = jax.lax.ppermute(send, axis, perm)
        buf = keep + recv
        d //= 2
    # buf is the reduced 1/n chunk starting at r*chunk; gather back.
    d = 1
    while d < n:
        perm = [(i, i ^ d) for i in range(n)]
        recv = jax.lax.ppermute(buf, axis, perm)
        bit = jnp.asarray(r & d, bool)
        # Bit clear: my chunk precedes the partner's.
        buf = jnp.where(
            bit,
            jnp.concatenate([recv, buf]),
            jnp.concatenate([buf, recv]),
        )
        d *= 2
    return _unpad(buf, size, x.shape)


# --------------------------------------------------------- two-level family
def two_level_allreduce(x, ici_axis: str, dcn_axis: str, n_ici: int,
                        quantized: bool = False,
                        block_size: int = DEFAULT_QUANT_BLOCK):
    """Hierarchical allreduce for multi-slice topologies: reduce-scatter
    over ICI, allreduce the 1/n_ici chunk over DCN (optionally block-
    quantized — the DCN hop is the bandwidth bottleneck), all-gather
    over ICI."""
    import jax

    flat, size = _pad_flat(x, n_ici)
    chunk = jax.lax.psum_scatter(flat, ici_axis, scatter_dimension=0,
                                 tiled=True)
    if quantized:
        chunk = quantized_allreduce(chunk, dcn_axis, block_size=block_size)
    else:
        chunk = jax.lax.psum(chunk, dcn_axis)
    full = jax.lax.all_gather(chunk, ici_axis, tiled=True)
    return _unpad(full, size, x.shape)


# ------------------------------------------------------------- quantization
def _safe_scales(amax):
    """Per-block scale ``amax/127`` with all-zero blocks mapped to scale 1
    (their quantized payload is exactly zero either way — no div-by-zero,
    no NaN)."""
    import jax.numpy as jnp

    scale = amax / 127.0
    return jnp.where(amax > 0, scale, jnp.ones_like(scale))


def quantize_blocks(x, block_size: int = DEFAULT_QUANT_BLOCK):
    """Block-quantize a tensor: int8 payload + per-block fp32 scales.
    Returns ``(q, scales, orig_size)``; blocks are ``block_size`` flat
    elements, zero-padded at the tail."""
    import jax.numpy as jnp

    flat, size = _pad_flat(x, block_size)
    blocks = flat.reshape(-1, block_size).astype(jnp.float32)
    amax = jnp.max(jnp.abs(blocks), axis=1)
    scales = _safe_scales(amax)
    q = jnp.clip(jnp.round(blocks / scales[:, None]), -127, 127).astype(
        jnp.int8
    )
    return q, scales, size


def dequantize_blocks(q, scales, size, shape, dtype):
    import jax.numpy as jnp

    deq = q.astype(jnp.float32) * scales[:, None]
    return _unpad(deq.reshape(-1), size, shape).astype(dtype)


def quantized_allreduce(x, axis: str, block_size: int = DEFAULT_QUANT_BLOCK):
    """One-shot block-quantized allreduce: quantize locally, all-gather
    the int8 payload + scales (~4x fewer wire bytes than fp32), then
    dequantize-and-sum in fp32.  Per-element error is bounded by
    ``sum_r amax_block_r / 254`` (round-to-nearest of each rank's
    contribution; see docs/collective.md)."""
    import jax
    import jax.numpy as jnp

    q, scales, size = quantize_blocks(x, block_size)
    qg = jax.lax.all_gather(q, axis)          # (n, nblocks, B) int8
    sg = jax.lax.all_gather(scales, axis)     # (n, nblocks) f32
    deq = qg.astype(jnp.float32) * sg[:, :, None]
    total = deq.sum(axis=0)
    return _unpad(total.reshape(-1), size, x.shape).astype(x.dtype)


def quantized_wire_bytes(nbytes: int, dtype, block_size: int =
                         DEFAULT_QUANT_BLOCK) -> int:
    """Bytes actually exchanged per rank for a quantized payload of
    ``nbytes`` logical bytes: int8 payload + one fp32 scale per block."""
    itemsize = max(1, np.dtype(str(dtype)).itemsize if str(dtype) !=
                   "bfloat16" else 2)
    elems = nbytes // itemsize
    nblocks = -(-elems // block_size)
    return elems + 4 * nblocks


# ------------------------------------------------- host-side (numpy) variant
# The pipeline trainer quantizes inter-stage gradient pushes on the host
# (the payload is already a host view at that point); same block format.
def quantize_blocks_np(arr: np.ndarray,
                       block_size: int = DEFAULT_QUANT_BLOCK):
    flat = np.asarray(arr, np.float32).reshape(-1)
    size = flat.size
    pad = (-size) % block_size
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    blocks = flat.reshape(-1, block_size)
    amax = np.abs(blocks).max(axis=1)
    scales = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(blocks / scales[:, None]), -127, 127).astype(np.int8)
    return q, scales, size


def dequantize_blocks_np(q: np.ndarray, scales: np.ndarray, size: int,
                         shape, dtype) -> np.ndarray:
    deq = q.astype(np.float32) * scales[:, None]
    return deq.reshape(-1)[:size].reshape(shape).astype(dtype)
