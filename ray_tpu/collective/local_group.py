"""Single-controller collective group over this process's local devices.

The TPU-native replacement for the reference's single-process multi-GPU
collectives (ray ``util/collective``'s ``*_multigpu`` variants backed by
cupy-NCCL, ``collective_group/nccl_collective_group.py:121``): here every op
is a jitted ``shard_map`` over a 1-D device mesh, so allreduce lowers to one
XLA ``psum`` riding ICI — no per-peer streams/events to manage, the compiler
schedules the ring.

Input convention: a list of per-rank arrays (rank i's tensor lives on local
device i), or a single already-sharded global ``jax.Array``.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .types import Backend, GroupInfo, ReduceOp


class LocalXlaGroup:
    """Collective group whose ranks are this process's local devices."""

    def __init__(self, group_name: str, devices: Sequence = None,
                 slice_size: int = None):
        import jax

        self.group_name = group_name
        self.devices = list(devices) if devices is not None else jax.devices()
        self.world_size = len(self.devices)
        from jax.sharding import Mesh

        from .types import Topology

        # ``slice_size``: devices per ICI slice.  Default: every device in
        # one slice (pure-ICI topology).  A multi-slice local group (e.g.
        # megascale hosts, or a CPU mesh standing in for a 2-slice DCN
        # fabric in tests/bench) unlocks the two-level algorithms.
        self.topology = Topology(self.world_size,
                                 slice_size or self.world_size)
        self.mesh = Mesh(np.array(self.devices), ("world",))
        self._mesh2 = None  # (dcn, ici) view, built on first two-level op
        self._fn_cache: Dict[tuple, object] = {}
        self._last_decision = None  # tuner decision of the most recent op
        # Flight recorder: op/bytes/world-size/duration + achieved-bandwidth
        # capture on every collective (no-op when disabled).
        from ..util import flight_recorder

        flight_recorder.instrument_group(self, "local")

    def info(self, rank: int = 0) -> GroupInfo:
        return GroupInfo(self.group_name, self.world_size, rank, Backend.LOCAL)

    # ------------------------------------------------------------- plumbing
    def _stack(self, tensors: List):
        """Place rank i's tensor on device i and form a global array sharded
        along the leading (world) axis — no host round-trip for arrays that
        are already on the right device."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        assert len(tensors) == self.world_size, (
            f"expected {self.world_size} per-rank tensors, got {len(tensors)}"
        )
        shape = tensors[0].shape
        dtype = tensors[0].dtype if hasattr(tensors[0], "dtype") else None
        shards = [
            jax.device_put(np.asarray(t)[None], d)
            for t, d in zip(tensors, self.devices)
        ]
        sharding = NamedSharding(self.mesh, P("world"))
        return jax.make_array_from_single_device_arrays(
            (self.world_size, *shape), sharding, shards
        )

    def _unstack(self, global_arr) -> List:
        return [s.data[0] for s in sorted(
            global_arr.addressable_shards, key=lambda s: s.index[0].start or 0
        )]

    def _shard_map(self, fn, out_spec_rank_axis=True):
        import jax
        from jax.sharding import PartitionSpec as P

        from .types import compat_shard_map

        in_spec = P("world")
        out_spec = P("world") if out_spec_rank_axis else P()
        return jax.jit(
            compat_shard_map(fn, self.mesh, (in_spec,), out_spec)
        )

    def _cached(self, key, builder):
        fn = self._fn_cache.get(key)
        if fn is None:
            fn = builder()
            self._fn_cache[key] = fn
        return fn

    def _shard_map2(self, fn):
        """shard_map over the (dcn, ici) two-level view of the same
        devices — row-major reshape keeps device order, so resharding
        from the 1-D mesh is layout-only."""
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        from .types import compat_shard_map

        if self._mesh2 is None:
            topo = self.topology
            self._mesh2 = Mesh(
                np.array(self.devices).reshape(topo.dcn_size, topo.ici_size),
                ("dcn", "ici"),
            )
        spec = P(("dcn", "ici"))
        return jax.jit(compat_shard_map(fn, self._mesh2, (spec,), spec))

    def _select(self, op: str, per_rank_nbytes: int, quantized: bool) -> str:
        """Tuner decision for one op call (single-controller group:
        every rank lives in this process, so the tuner's measurement
        table needs no cross-member sync)."""
        from .tuner import select_for_group

        return select_for_group(self, op, per_rank_nbytes, quantized)

    def _resolve_quantized(self, op: ReduceOp, dtype, quantized) -> bool:
        from .algorithms import resolve_quantized

        return resolve_quantized(op, dtype, quantized)

    @staticmethod
    def _quant_block() -> int:
        from ..core.config import GlobalConfig

        return GlobalConfig.collective_quant_block_size

    # ------------------------------------------------------------------ ops
    def allreduce(self, tensors: List, op: ReduceOp = ReduceOp.SUM,
                  quantized: bool = None) -> List:
        import jax
        import jax.numpy as jnp

        from . import algorithms as alg

        g = self._stack(tensors)
        quantized = self._resolve_quantized(op, g.dtype, quantized)
        self._last_decision = None

        if op != ReduceOp.SUM:
            # Non-SUM reductions keep the flat lowering (no algorithm
            # family implements reassociation-safe MAX/MIN/MEAN/PRODUCT).
            def build():
                def body(x):  # x: (1, *shape) per rank
                    if op == ReduceOp.PRODUCT:
                        # No pprod primitive: reduce via allgather.
                        gathered = jax.lax.all_gather(x[0], "world")
                        return jnp.prod(gathered, axis=0)[None]
                    red = {
                        ReduceOp.MAX: jax.lax.pmax,
                        ReduceOp.MIN: jax.lax.pmin,
                        ReduceOp.MEAN: jax.lax.pmean,
                    }[op]
                    return red(x, "world")

                return self._shard_map(body)

            out = self._cached(("ar", op, g.shape, str(g.dtype)), build)(g)
            return self._unstack(out)

        per_rank_nbytes = g.nbytes // max(1, self.world_size)
        algo = self._select("allreduce", per_rank_nbytes, quantized)
        n = self.world_size
        topo = self.topology
        block = self._quant_block()

        def build():
            if algo in (alg.TWO_LEVEL, alg.TWO_LEVEL_Q8):
                def body(x):
                    return alg.two_level_allreduce(
                        x[0], "ici", "dcn", topo.ici_size,
                        quantized=(algo == alg.TWO_LEVEL_Q8),
                        block_size=block,
                    )[None]

                return self._shard_map2(body)

            def body(x):
                if algo == alg.RING:
                    return alg.ring_allreduce(x[0], "world", n)[None]
                if algo == alg.TREE:
                    return alg.tree_allreduce(x[0], "world", n)[None]
                if algo == alg.FLAT_Q8:
                    return alg.quantized_allreduce(
                        x[0], "world", block_size=block
                    )[None]
                return jax.lax.psum(x, "world")

            return self._shard_map(body)

        out = self._cached(
            ("ar", op, algo, block if quantized else 0, g.shape,
             str(g.dtype)),
            build,
        )(g)
        return self._unstack(out)

    def allgather(self, tensors: List) -> List[List]:
        import jax

        from . import algorithms as alg

        g = self._stack(tensors)
        self._last_decision = None
        per_rank_nbytes = g.nbytes // max(1, self.world_size)
        algo = self._select("allgather", per_rank_nbytes, False)
        n = self.world_size

        def build():
            def body(x):
                if algo == alg.RING:
                    return alg.ring_allgather(x[0], "world", n)[None]
                return jax.lax.all_gather(x[0], "world")[None]

            return self._shard_map(body)

        out = self._cached(("ag", algo, g.shape, str(g.dtype)), build)(g)
        per_rank = self._unstack(out)
        return [[r[i] for i in range(self.world_size)] for r in per_rank]

    def reducescatter(self, tensors: List, op: ReduceOp = ReduceOp.SUM) -> List:
        """Rank i receives chunk i of the elementwise reduction (inputs must
        be divisible by world_size along axis 0)."""
        import jax
        import jax.numpy as jnp

        from . import algorithms as alg

        g = self._stack(tensors)
        n = self.world_size
        self._last_decision = None
        algo = alg.FLAT
        if op == ReduceOp.SUM:
            per_rank_nbytes = g.nbytes // max(1, n)
            algo = self._select("reducescatter", per_rank_nbytes, False)

        def build():
            def body(x):
                if op == ReduceOp.SUM:
                    if algo == alg.RING:
                        return alg.ring_reducescatter(x[0], "world", n)[None]
                    # The fast path: one XLA reduce-scatter over ICI.
                    return jax.lax.psum_scatter(
                        x[0], "world", scatter_dimension=0, tiled=True
                    )[None]
                gathered = jax.lax.all_gather(x[0], "world")  # (n, *shape)
                reducer = {
                    ReduceOp.MAX: jnp.max,
                    ReduceOp.MIN: jnp.min,
                    ReduceOp.MEAN: jnp.mean,
                    ReduceOp.PRODUCT: jnp.prod,
                }[op]
                red = reducer(gathered, axis=0)
                rank = jax.lax.axis_index("world")
                chunk = red.shape[0] // n
                return jax.lax.dynamic_slice_in_dim(red, rank * chunk, chunk)[None]

            return self._shard_map(body)

        out = self._cached(("rs", op, algo, g.shape, str(g.dtype)), build)(g)
        return self._unstack(out)

    def broadcast(self, tensors: List, src_rank: int = 0) -> List:
        import jax

        g = self._stack(tensors)

        def build():
            def body(x):
                gathered = jax.lax.all_gather(x[0], "world")
                return gathered[src_rank][None]

            return self._shard_map(body)

        out = self._cached(("bc", src_rank, g.shape, str(g.dtype)), build)(g)
        return self._unstack(out)

    def alltoall(self, tensors: List) -> List:
        """Rank i's output chunk j = rank j's input chunk i (axis 0)."""
        import jax

        g = self._stack(tensors)

        def build():
            def body(x):
                return jax.lax.all_to_all(
                    x, "world", split_axis=1, concat_axis=0, tiled=False
                ).reshape(x.shape)

            return self._shard_map(body)

        out = self._cached(("a2a", g.shape, str(g.dtype)), build)(g)
        return self._unstack(out)

    def sendrecv_ring(self, tensors: List, shift: int = 1) -> List:
        """ppermute ring shift: rank i's tensor goes to rank (i+shift)%n."""
        import jax

        g = self._stack(tensors)
        n = self.world_size

        def build():
            perm = [(i, (i + shift) % n) for i in range(n)]

            def body(x):
                return jax.lax.ppermute(x, "world", perm)

            return self._shard_map(body)

        out = self._cached(("pp", shift, g.shape, str(g.dtype)), build)(g)
        return self._unstack(out)

    def barrier(self):
        import numpy as _np

        self.allreduce([_np.zeros((1,), _np.float32)] * self.world_size)
