"""Single-controller collective group over this process's local devices.

The TPU-native replacement for the reference's single-process multi-GPU
collectives (ray ``util/collective``'s ``*_multigpu`` variants backed by
cupy-NCCL, ``collective_group/nccl_collective_group.py:121``): here every op
is a jitted ``shard_map`` over a 1-D device mesh, so allreduce lowers to one
XLA ``psum`` riding ICI — no per-peer streams/events to manage, the compiler
schedules the ring.

Input convention: a list of per-rank arrays (rank i's tensor lives on local
device i), or a single already-sharded global ``jax.Array``.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .types import Backend, GroupInfo, ReduceOp


class LocalXlaGroup:
    """Collective group whose ranks are this process's local devices."""

    def __init__(self, group_name: str, devices: Sequence = None):
        import jax

        self.group_name = group_name
        self.devices = list(devices) if devices is not None else jax.devices()
        self.world_size = len(self.devices)
        from jax.sharding import Mesh

        self.mesh = Mesh(np.array(self.devices), ("world",))
        self._fn_cache: Dict[tuple, object] = {}
        # Flight recorder: op/bytes/world-size/duration + achieved-bandwidth
        # capture on every collective (no-op when disabled).
        from ..util import flight_recorder

        flight_recorder.instrument_group(self, "local")

    def info(self, rank: int = 0) -> GroupInfo:
        return GroupInfo(self.group_name, self.world_size, rank, Backend.LOCAL)

    # ------------------------------------------------------------- plumbing
    def _stack(self, tensors: List):
        """Place rank i's tensor on device i and form a global array sharded
        along the leading (world) axis — no host round-trip for arrays that
        are already on the right device."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        assert len(tensors) == self.world_size, (
            f"expected {self.world_size} per-rank tensors, got {len(tensors)}"
        )
        shape = tensors[0].shape
        dtype = tensors[0].dtype if hasattr(tensors[0], "dtype") else None
        shards = [
            jax.device_put(np.asarray(t)[None], d)
            for t, d in zip(tensors, self.devices)
        ]
        sharding = NamedSharding(self.mesh, P("world"))
        return jax.make_array_from_single_device_arrays(
            (self.world_size, *shape), sharding, shards
        )

    def _unstack(self, global_arr) -> List:
        return [s.data[0] for s in sorted(
            global_arr.addressable_shards, key=lambda s: s.index[0].start or 0
        )]

    def _shard_map(self, fn, out_spec_rank_axis=True):
        import jax
        from jax.sharding import PartitionSpec as P

        from .types import compat_shard_map

        in_spec = P("world")
        out_spec = P("world") if out_spec_rank_axis else P()
        return jax.jit(
            compat_shard_map(fn, self.mesh, (in_spec,), out_spec)
        )

    def _cached(self, key, builder):
        fn = self._fn_cache.get(key)
        if fn is None:
            fn = builder()
            self._fn_cache[key] = fn
        return fn

    # ------------------------------------------------------------------ ops
    def allreduce(self, tensors: List, op: ReduceOp = ReduceOp.SUM) -> List:
        import jax
        import jax.numpy as jnp

        g = self._stack(tensors)

        def build():
            def body(x):  # x: (1, *shape) per rank
                if op == ReduceOp.PRODUCT:
                    # No pprod primitive: reduce via log/exp-free allgather.
                    gathered = jax.lax.all_gather(x[0], "world")
                    return jnp.prod(gathered, axis=0)[None]
                red = {
                    ReduceOp.SUM: jax.lax.psum,
                    ReduceOp.MAX: jax.lax.pmax,
                    ReduceOp.MIN: jax.lax.pmin,
                    ReduceOp.MEAN: jax.lax.pmean,
                }[op]
                return red(x, "world")

            return self._shard_map(body)

        out = self._cached(("ar", op, g.shape, str(g.dtype)), build)(g)
        return self._unstack(out)

    def allgather(self, tensors: List) -> List[List]:
        import jax

        g = self._stack(tensors)

        def build():
            def body(x):
                return jax.lax.all_gather(x[0], "world")[None]

            return self._shard_map(body)

        out = self._cached(("ag", g.shape, str(g.dtype)), build)(g)
        per_rank = self._unstack(out)
        return [[r[i] for i in range(self.world_size)] for r in per_rank]

    def reducescatter(self, tensors: List, op: ReduceOp = ReduceOp.SUM) -> List:
        """Rank i receives chunk i of the elementwise reduction (inputs must
        be divisible by world_size along axis 0)."""
        import jax
        import jax.numpy as jnp

        g = self._stack(tensors)
        n = self.world_size

        def build():
            def body(x):
                if op == ReduceOp.SUM:
                    # The fast path: one XLA reduce-scatter over ICI.
                    return jax.lax.psum_scatter(
                        x[0], "world", scatter_dimension=0, tiled=True
                    )[None]
                gathered = jax.lax.all_gather(x[0], "world")  # (n, *shape)
                reducer = {
                    ReduceOp.MAX: jnp.max,
                    ReduceOp.MIN: jnp.min,
                    ReduceOp.MEAN: jnp.mean,
                    ReduceOp.PRODUCT: jnp.prod,
                }[op]
                red = reducer(gathered, axis=0)
                rank = jax.lax.axis_index("world")
                chunk = red.shape[0] // n
                return jax.lax.dynamic_slice_in_dim(red, rank * chunk, chunk)[None]

            return self._shard_map(body)

        out = self._cached(("rs", op, g.shape, str(g.dtype)), build)(g)
        return self._unstack(out)

    def broadcast(self, tensors: List, src_rank: int = 0) -> List:
        import jax

        g = self._stack(tensors)

        def build():
            def body(x):
                gathered = jax.lax.all_gather(x[0], "world")
                return gathered[src_rank][None]

            return self._shard_map(body)

        out = self._cached(("bc", src_rank, g.shape, str(g.dtype)), build)(g)
        return self._unstack(out)

    def alltoall(self, tensors: List) -> List:
        """Rank i's output chunk j = rank j's input chunk i (axis 0)."""
        import jax

        g = self._stack(tensors)

        def build():
            def body(x):
                return jax.lax.all_to_all(
                    x, "world", split_axis=1, concat_axis=0, tiled=False
                ).reshape(x.shape)

            return self._shard_map(body)

        out = self._cached(("a2a", g.shape, str(g.dtype)), build)(g)
        return self._unstack(out)

    def sendrecv_ring(self, tensors: List, shift: int = 1) -> List:
        """ppermute ring shift: rank i's tensor goes to rank (i+shift)%n."""
        import jax

        g = self._stack(tensors)
        n = self.world_size

        def build():
            perm = [(i, (i + shift) % n) for i in range(n)]

            def body(x):
                return jax.lax.ppermute(x, "world", perm)

            return self._shard_map(body)

        out = self._cached(("pp", shift, g.shape, str(g.dtype)), build)(g)
        return self._unstack(out)

    def barrier(self):
        import numpy as _np

        self.allreduce([_np.zeros((1,), _np.float32)] * self.world_size)
