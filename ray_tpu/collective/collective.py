"""Declarative collective API — the ``ray.util.collective`` equivalent.

Reference surface (ray ``python/ray/util/collective/collective.py``):
``init_collective_group`` (:171), ``create_collective_group`` (:211),
``allreduce/reduce/broadcast/allgather/reducescatter/barrier`` (:328-725),
with a per-process ``GroupManager`` (:71).  Backends here are XLA-native
(see ``types.Backend``): no NCCL communicators or per-peer CUDA streams —
groups are JAX meshes and every op is one compiled XLA collective.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from .types import Backend, GroupInfo, ReduceOp

logger = logging.getLogger(__name__)


class GroupManager:
    """Per-process registry of named collective groups."""

    def __init__(self):
        self._groups: Dict[str, object] = {}

    def create(self, backend: Backend, group_name: str, world_size: int, rank: int,
               **kwargs):
        if group_name in self._groups:
            raise ValueError(f"collective group {group_name!r} already exists")
        if backend == Backend.LOCAL:
            from .local_group import LocalXlaGroup

            group = LocalXlaGroup(group_name, kwargs.get("devices"),
                                  slice_size=kwargs.get("slice_size"))
        else:
            from .xla_group import XlaGroup

            group = XlaGroup(group_name, world_size, rank, **kwargs)
        # The caller-declared rank identifies THIS member for p2p edges
        # even when the group object itself is rank-less (LOCAL backend is
        # single-controller, so its own rank is always 0).
        group.declared_rank = rank
        self._groups[group_name] = group
        return group

    def get(self, group_name: str):
        group = self._groups.get(group_name)
        if group is None:
            raise ValueError(
                f"collective group {group_name!r} is not initialized; call "
                f"init_collective_group first"
            )
        return group

    def destroy(self, group_name: str):
        group = self._groups.pop(group_name, None)
        if group is not None and hasattr(group, "shutdown"):
            group.shutdown()

    def names(self) -> List[str]:
        return list(self._groups)


_manager = GroupManager()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "xla",
    group_name: str = "default",
    **kwargs,
):
    """Initialize a named collective group in this process (each member
    process/actor calls this with its own rank)."""
    b = Backend.normalize(backend)
    return _manager.create(b, group_name, world_size, rank, **kwargs)


def init_local_group(group_name: str = "default", devices=None,
                     slice_size: int = None):
    """Single-controller group over this process's local devices (all ranks
    live here; ops take per-rank tensor lists).  ``slice_size`` declares
    devices-per-ICI-slice for algorithm selection: a multi-slice group
    unlocks the two-level (ICI reduce-scatter / DCN exchange / ICI
    all-gather) decomposition — see docs/collective.md."""
    return _manager.create(Backend.LOCAL, group_name, 0, 0, devices=devices,
                           slice_size=slice_size)


def is_group_initialized(group_name: str = "default") -> bool:
    try:
        _manager.get(group_name)
        return True
    except ValueError:
        return False


def destroy_collective_group(group_name: str = "default"):
    _destroy_p2p_edges(group_name)
    _manager.destroy(group_name)


def get_group(group_name: str = "default"):
    return _manager.get(group_name)


def get_rank(group_name: str = "default") -> int:
    g = _manager.get(group_name)
    return getattr(g, "rank", 0)


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get(group_name).world_size


# ---------------------------------------------------------------------- ops
def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM,
              quantized: bool = None):
    """SUM allreduce routes through the topology-aware algorithm
    selection layer (docs/collective.md).  ``quantized=True`` opts this
    call into the EQuARX-style block-quantized exchange (float payloads,
    SUM only; bounded per-block error); ``None`` defers to the
    ``collective_quantized_allreduce`` process default (off)."""
    return _manager.get(group_name).allreduce(tensor, op, quantized=quantized)


def allgather(tensor, group_name: str = "default"):
    return _manager.get(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return _manager.get(group_name).reducescatter(tensor, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _manager.get(group_name).broadcast(tensor, src_rank)


def alltoall(tensor, group_name: str = "default"):
    return _manager.get(group_name).alltoall(tensor)


def barrier(group_name: str = "default"):
    return _manager.get(group_name).barrier()


def collective_stats(cluster: bool = False) -> Dict[str, dict]:
    """Collective telemetry from the flight recorder.

    Local view (default): per-op aggregates (ops, bytes, mean warm
    duration) keyed by op name, plus a ``"tuner"`` entry with the
    algorithm-selection table — per (op, size bucket, world size,
    topology): the chosen algorithm, call/exploration counts, and
    per-algorithm attempts/samples/mean achieved bandwidth.

    ``cluster=True``: the per-group merge over all workers via the
    owner-service metrics registry (each worker flushes its registry to
    the control-plane KV; the driver reads them all back) — see
    ``flight_recorder.cluster_collective_stats()``.  Requires a running
    cluster; the tuner decision counters appear under ``"algorithms"``."""
    from ..util import flight_recorder

    if cluster:
        return flight_recorder.cluster_collective_stats()
    from .tuner import get_tuner

    stats: Dict[str, dict] = dict(flight_recorder.local_collective_stats())
    stats["tuner"] = get_tuner().stats()
    return stats


def send(tensor, dst_rank: int, group_name: str = "default"):
    """Point-to-point send (reference: ``ray.util.collective.send``,
    NCCL p2p).  TPU-native path: the tensor rides the object plane —
    host-staged through a named per-edge queue actor, so it works across
    any pair of group members without a matching collective on the others.
    For device-resident bulk transfer inside a jitted step, use
    ``jax.lax.ppermute`` over the mesh instead."""
    import numpy as np

    group = _manager.get(group_name)
    src = getattr(group, "declared_rank", get_rank(group_name))
    queue = _p2p_queue(group_name, src, dst_rank)
    queue.put(np.asarray(tensor))


def recv(src_rank: int, group_name: str = "default", timeout: float = 60.0):
    """Blocking receive of the next tensor sent by ``src_rank``."""
    group = _manager.get(group_name)
    dst = getattr(group, "declared_rank", get_rank(group_name))
    queue = _p2p_queue(group_name, src_rank, dst)
    return queue.get(timeout=timeout)


# (group, src, dst) -> Queue; handles are cached so the hot p2p path pays
# the named-actor rendezvous once per edge, not per message.
_p2p_cache: Dict[tuple, object] = {}


def _p2p_queue(group_name: str, src: int, dst: int):
    """Named queue actor for the (group, src→dst) edge, created on first
    use by either end (get_if_exists rendezvous)."""
    from ..util.queue import Queue

    key = (group_name, src, dst)
    queue = _p2p_cache.get(key)
    if queue is None:
        queue = Queue(
            maxsize=64,
            name=f"_rtpu_p2p:{group_name}:{src}->{dst}",
            get_if_exists=True,
        )
        _p2p_cache[key] = queue
    return queue


def _destroy_p2p_edges(group_name: str):
    """Kill ALL p2p queue actors for a group (cluster-wide, by name) — a
    later group reusing the name must not receive stale tensors, including
    on edges only a peer process ever touched.  Peers still holding handles
    see a dead-actor error on their next send/recv (loud, not stale)."""
    import ray_tpu

    # Cached handles die unconditionally (no state-API dependency)...
    for key in [k for k in _p2p_cache if k[0] == group_name]:
        queue = _p2p_cache.pop(key)
        try:
            ray_tpu.kill(queue.actor)
        except Exception:  # raylint: waive[RTL003] teardown kill is best-effort; actor may be gone
            pass
    # ...and a best-effort cluster-wide sweep catches edges only peer
    # processes ever touched.  Edge names end with "src->dst" and contain
    # no further ':' after the group name, so "train" never matches
    # "train:eval" edges.
    import re

    edge_re = re.compile(
        re.escape(f"_rtpu_p2p:{group_name}:") + r"\d+->\d+$"
    )
    try:
        from ..util.state import list_actors

        for row in list_actors():
            name = row.get("name")
            if name and edge_re.fullmatch(name) and row["state"] != "DEAD":
                try:
                    ray_tpu.kill(ray_tpu.get_actor(name))
                except Exception:  # raylint: waive[RTL003] teardown kill is best-effort; actor may be gone
                    pass
    except Exception:  # raylint: waive[RTL003] best effort without a driver
        pass
