"""Declarative collective API — the ``ray.util.collective`` equivalent.

Reference surface (ray ``python/ray/util/collective/collective.py``):
``init_collective_group`` (:171), ``create_collective_group`` (:211),
``allreduce/reduce/broadcast/allgather/reducescatter/barrier`` (:328-725),
with a per-process ``GroupManager`` (:71).  Backends here are XLA-native
(see ``types.Backend``): no NCCL communicators or per-peer CUDA streams —
groups are JAX meshes and every op is one compiled XLA collective.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from .types import Backend, GroupInfo, ReduceOp

logger = logging.getLogger(__name__)


class GroupManager:
    """Per-process registry of named collective groups."""

    def __init__(self):
        self._groups: Dict[str, object] = {}

    def create(self, backend: Backend, group_name: str, world_size: int, rank: int,
               **kwargs):
        if group_name in self._groups:
            raise ValueError(f"collective group {group_name!r} already exists")
        if backend == Backend.LOCAL:
            from .local_group import LocalXlaGroup

            group = LocalXlaGroup(group_name, kwargs.get("devices"))
        else:
            from .xla_group import XlaGroup

            group = XlaGroup(group_name, world_size, rank, **kwargs)
        self._groups[group_name] = group
        return group

    def get(self, group_name: str):
        group = self._groups.get(group_name)
        if group is None:
            raise ValueError(
                f"collective group {group_name!r} is not initialized; call "
                f"init_collective_group first"
            )
        return group

    def destroy(self, group_name: str):
        group = self._groups.pop(group_name, None)
        if group is not None and hasattr(group, "shutdown"):
            group.shutdown()

    def names(self) -> List[str]:
        return list(self._groups)


_manager = GroupManager()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "xla",
    group_name: str = "default",
    **kwargs,
):
    """Initialize a named collective group in this process (each member
    process/actor calls this with its own rank)."""
    b = Backend.normalize(backend)
    return _manager.create(b, group_name, world_size, rank, **kwargs)


def init_local_group(group_name: str = "default", devices=None):
    """Single-controller group over this process's local devices (all ranks
    live here; ops take per-rank tensor lists)."""
    return _manager.create(Backend.LOCAL, group_name, 0, 0, devices=devices)


def is_group_initialized(group_name: str = "default") -> bool:
    try:
        _manager.get(group_name)
        return True
    except ValueError:
        return False


def destroy_collective_group(group_name: str = "default"):
    _manager.destroy(group_name)


def get_group(group_name: str = "default"):
    return _manager.get(group_name)


def get_rank(group_name: str = "default") -> int:
    g = _manager.get(group_name)
    return getattr(g, "rank", 0)


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get(group_name).world_size


# ---------------------------------------------------------------------- ops
def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return _manager.get(group_name).allreduce(tensor, op)


def allgather(tensor, group_name: str = "default"):
    return _manager.get(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return _manager.get(group_name).reducescatter(tensor, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _manager.get(group_name).broadcast(tensor, src_rank)


def alltoall(tensor, group_name: str = "default"):
    return _manager.get(group_name).alltoall(tensor)


def barrier(group_name: str = "default"):
    return _manager.get(group_name).barrier()
