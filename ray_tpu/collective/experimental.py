"""Actor-bound collective groups (the ``ray.experimental.collective``
analog).

Reference: ray ``python/ray/experimental/collective/collective.py:66,88``
— ``create_collective_group(actors, backend)`` declares a communicator
over a set of actor handles; a named ``RemoteCommunicatorManager`` actor
tracks the declarations so any process can look up which group an actor
belongs to (the routing table device-object transfers consult).

TPU-native: group init runs *inside* each actor via the generic
``execute_on_actor`` hook (no methods required on user classes); the
transport is the local (CPU shard_map) or XLA (ICI) backend from
``ray_tpu.collective``.
"""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu.core.api_frontend import execute_on_actor


@ray_tpu.remote
class RemoteCommunicatorManager:
    """Cluster-wide registry of actor-bound collective groups."""

    def __init__(self):
        # group_name -> {"actor_ids": [hex...], "backend": str, "world": n}
        self._groups: Dict[str, dict] = {}

    def register(self, group_name: str, actor_ids: List[str],
                 backend: str) -> bool:
        self._groups[group_name] = {
            "actor_ids": list(actor_ids),
            "backend": backend,
            "world": len(actor_ids),
        }
        return True

    def unregister(self, group_name: str) -> bool:
        return self._groups.pop(group_name, None) is not None

    def get(self, group_name: str) -> Optional[dict]:
        return self._groups.get(group_name)

    def group_of_actor(self, actor_id: str) -> Optional[str]:
        for name, info in self._groups.items():
            if actor_id in info["actor_ids"]:
                return name
        return None

    def list_groups(self) -> Dict[str, dict]:
        return dict(self._groups)


_MANAGER_NAME = "_rtpu_communicator_manager"


def _manager():
    try:
        return ray_tpu.get_actor(_MANAGER_NAME)
    except Exception:
        return RemoteCommunicatorManager.options(
            name=_MANAGER_NAME, get_if_exists=True
        ).remote()


def create_collective_group(
    actors: List,
    backend: str = "local",
    group_name: Optional[str] = None,
) -> str:
    """Declare + eagerly initialize a collective group over actor handles.

    Each actor becomes rank i (the order of ``actors``); the group is
    registered with the communicator manager and initialized inside every
    actor process.  Returns the group name."""
    name = group_name or f"actor_group_{uuid.uuid4().hex[:8]}"
    world = len(actors)

    def init_in_actor(_instance, group_name, world_size, rank, backend):
        from ray_tpu import collective

        if collective.is_group_initialized(group_name):
            return True
        if backend == "local":
            # The group's logical world is the ACTOR count: size the local
            # device mesh to match so group ops take one tensor per member.
            import jax

            collective.init_local_group(
                group_name, devices=jax.devices()[:world_size]
            )
        else:
            collective.init_collective_group(
                world_size, rank, backend=backend, group_name=group_name
            )
        return True

    refs = [
        execute_on_actor(a, init_in_actor, name, world, rank, backend)
        for rank, a in enumerate(actors)
    ]
    ray_tpu.get(refs, timeout=120)
    mgr = _manager()
    ray_tpu.get(
        mgr.register.remote(
            name, [a._actor_id.hex() for a in actors], backend
        ),
        timeout=60,
    )
    return name


def destroy_collective_group(group_name: str) -> None:
    mgr = _manager()
    info = ray_tpu.get(mgr.get.remote(group_name), timeout=60)
    ray_tpu.get(mgr.unregister.remote(group_name), timeout=60)
    _ = info


def get_collective_groups(actor) -> List[str]:
    """Groups the given actor handle belongs to."""
    mgr = _manager()
    name = ray_tpu.get(
        mgr.group_of_actor.remote(actor._actor_id.hex()), timeout=60
    )
    return [name] if name else []
