"""Collective types (reference: ray ``python/ray/util/collective/types.py``).

Backends: the reference exposes {NCCL, GLOO}; here the native backend is XLA —
collectives lower to ``jax.lax.psum``/``all_gather``/``psum_scatter``/
``all_to_all``/``ppermute`` over ICI within a slice (DCN across slices), and
a LOCAL backend runs the same ops over this process's local devices (used for
single-host groups and CPU-mesh tests).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum


class Backend(str, Enum):
    XLA = "xla"  # multi-host jax.distributed group
    LOCAL = "local"  # this process's devices only (single-controller)

    @classmethod
    def normalize(cls, value) -> "Backend":
        if isinstance(value, cls):
            return value
        v = str(value).lower()
        if v in ("xla", "tpu", "ici"):
            return cls.XLA
        if v in ("local", "cpu", "host"):
            return cls.LOCAL
        raise ValueError(f"unknown collective backend {value!r}")


class ReduceOp(str, Enum):
    SUM = "sum"
    PRODUCT = "product"
    MAX = "max"
    MIN = "min"
    MEAN = "mean"


@dataclass
class GroupInfo:
    group_name: str
    world_size: int
    rank: int
    backend: Backend
