"""Collective types (reference: ray ``python/ray/util/collective/types.py``).

Backends: the reference exposes {NCCL, GLOO}; here the native backend is XLA —
collectives lower to ``jax.lax.psum``/``all_gather``/``psum_scatter``/
``all_to_all``/``ppermute`` over ICI within a slice (DCN across slices), and
a LOCAL backend runs the same ops over this process's local devices (used for
single-host groups and CPU-mesh tests).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum


class Backend(str, Enum):
    XLA = "xla"  # multi-host jax.distributed group
    LOCAL = "local"  # this process's devices only (single-controller)

    @classmethod
    def normalize(cls, value) -> "Backend":
        if isinstance(value, cls):
            return value
        v = str(value).lower()
        if v in ("xla", "tpu", "ici"):
            return cls.XLA
        if v in ("local", "cpu", "host"):
            return cls.LOCAL
        raise ValueError(f"unknown collective backend {value!r}")


class ReduceOp(str, Enum):
    SUM = "sum"
    PRODUCT = "product"
    MAX = "max"
    MIN = "min"
    MEAN = "mean"


def compat_shard_map(body, mesh, in_specs, out_specs):
    """shard_map across jax versions: the top-level ``jax.shard_map`` (with
    ``check_vma``) moved namespaces over releases; older versions ship
    ``jax.experimental.shard_map`` (with ``check_rep``).  Replication
    checking is disabled either way — the collective bodies intentionally
    return per-rank values."""
    import inspect

    try:
        from jax import shard_map as _sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
    params = inspect.signature(_sm).parameters
    kw = {}
    if "check_vma" in params:
        kw["check_vma"] = False
    elif "check_rep" in params:
        kw["check_rep"] = False
    return _sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def compat_axis_size(axis_name):
    """Static mapped-axis size inside a shard_map body across jax versions:
    ``jax.lax.axis_size`` where it exists; otherwise ``psum(1, axis)``,
    which constant-folds to a Python int under shard_map."""
    import jax

    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)


@dataclass(frozen=True)
class Topology:
    """Physical shape of a collective group for algorithm selection:
    ``world_size`` members arranged as slices of ``ici_size`` members
    each.  One slice (``ici_size == world_size``) means every hop rides
    ICI; multiple slices mean cross-slice hops ride DCN and a two-level
    decomposition (intra-slice reduce-scatter, inter-slice exchange,
    intra-slice all-gather) becomes eligible."""

    world_size: int
    ici_size: int

    def __post_init__(self):
        if self.ici_size < 1 or self.world_size < 1:
            raise ValueError("topology sizes must be >= 1")
        if self.world_size % self.ici_size:
            raise ValueError(
                f"world_size {self.world_size} not divisible by slice size "
                f"{self.ici_size}"
            )

    @property
    def dcn_size(self) -> int:
        return self.world_size // self.ici_size

    @property
    def is_two_level(self) -> bool:
        return 1 < self.ici_size < self.world_size

    @property
    def kind(self) -> str:
        """``"ici"`` when every hop is intra-slice, ``"dcn"`` otherwise."""
        return "ici" if self.dcn_size == 1 else "dcn"


@dataclass
class GroupInfo:
    group_name: str
    world_size: int
    rank: int
    backend: Backend
