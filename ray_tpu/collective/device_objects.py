"""Device-resident object store — the GPU-objects ("RDT") analog.

Reference: ray ``python/ray/experimental/gpu_object_manager/`` — objects
created with ``tensor_transport="nccl"`` stay on device and move peer-to-peer,
bypassing plasma.  TPU-native version: ``jax.Array``s stay resident in HBM in
the owning actor process, keyed by object id; consumers on the same process
get the array directly; consumers in other members of a collective group
receive it via a broadcast/ppermute over ICI instead of a host round-trip.

Integration point: actor methods can return ``DeviceRef``s; the plain object
plane carries only the (id, shape, dtype, owner_rank) metadata.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from ray_tpu.core.ids import ObjectID


@dataclass
class DeviceRef:
    """Metadata handle to a device-resident array (picklable; the tensor
    itself never leaves HBM unless explicitly fetched)."""

    object_id: ObjectID
    shape: Tuple[int, ...]
    dtype: str
    owner_rank: int = 0
    group_name: str = "default"


class DeviceObjectStore:
    """Per-process store of device-resident jax.Arrays."""

    def __init__(self):
        self._objects: Dict[ObjectID, object] = {}
        self._lock = threading.Lock()

    def put(self, array, group_name: str = "default", rank: int = 0) -> DeviceRef:
        oid = ObjectID.from_random()
        with self._lock:
            self._objects[oid] = array
        return DeviceRef(
            oid, tuple(array.shape), str(array.dtype), rank, group_name
        )

    def get_local(self, ref: DeviceRef):
        with self._lock:
            arr = self._objects.get(ref.object_id)
        if arr is None:
            raise KeyError(f"device object {ref.object_id} not resident here")
        return arr

    def contains(self, ref: DeviceRef) -> bool:
        with self._lock:
            return ref.object_id in self._objects

    def free(self, ref: DeviceRef):
        with self._lock:
            self._objects.pop(ref.object_id, None)

    def fetch(self, ref: DeviceRef):
        """Resolve a DeviceRef: local hit returns the resident array; remote
        owner → the owning rank broadcasts over the collective group (all
        members must call fetch() collectively, like the reference's NCCL
        transport)."""
        if self.contains(ref):
            return self.get_local(ref)
        from .collective import get_group

        group = get_group(ref.group_name)
        import numpy as np
        import jax.numpy as jnp

        placeholder = jnp.zeros(ref.shape, dtype=ref.dtype)
        return group.broadcast(placeholder, src_rank=ref.owner_rank)

    def serve_fetch(self, ref: DeviceRef):
        """Owner side of a collective fetch."""
        from .collective import get_group

        group = get_group(ref.group_name)
        return group.broadcast(self.get_local(ref), src_rank=ref.owner_rank)

    def __len__(self):
        return len(self._objects)


_store: Optional[DeviceObjectStore] = None


def device_object_store() -> DeviceObjectStore:
    global _store
    if _store is None:
        _store = DeviceObjectStore()
    return _store
