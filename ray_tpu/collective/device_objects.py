"""Device-resident object store — the GPU-objects ("RDT") analog.

Reference: ray ``python/ray/experimental/gpu_object_manager/`` — objects
created with ``tensor_transport="nccl"`` stay on device and move peer-to-peer,
bypassing plasma.  TPU-native version: ``jax.Array``s stay resident in HBM in
the owning actor process, keyed by object id; consumers on the same process
get the array directly; consumers in other members of a collective group
receive it via a broadcast/ppermute over ICI instead of a host round-trip.

Integration point: actor methods can return ``DeviceRef``s; the plain object
plane carries only the (id, shape, dtype, owner_rank) metadata.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from ray_tpu.core.ids import ObjectID


@dataclass
class DeviceRef:
    """Metadata handle to a device-resident array (picklable; the tensor
    itself never leaves HBM unless explicitly fetched)."""

    object_id: ObjectID
    shape: Tuple[int, ...]
    dtype: str
    owner_rank: int = 0
    group_name: str = "default"
    # RPC address of the owning worker process (set automatically when the
    # owner runs inside a core worker) — enables point-to-point fetch
    # without a collective group.
    owner_address: str = ""


class DeviceObjectStore:
    """Per-process store of device-resident jax.Arrays.

    Residency is governed by owner-side REFERENCE COUNTS (the reference's
    ``gpu_object_store.py:169`` semantics), not a fixed LRU cap: ``put``
    creates one reference, ``retain``/``release`` adjust it (remotely via
    the owner's worker RPC for borrowed refs), and the array leaves HBM
    exactly when the count hits zero."""

    def __init__(self):
        self._objects: Dict[ObjectID, object] = {}
        self._refcounts: Dict[ObjectID, int] = {}
        self._lock = threading.Lock()
        # Instrumentation: how the most recent fetch() resolved —
        # "local" | "collective" | "p2p_rpc" (tests assert the transfer
        # path; ops dashboards read it as a counter source).
        self.last_transfer_path: Optional[str] = None

    def put(self, array, group_name: str = "default", rank: int = 0) -> DeviceRef:
        oid = ObjectID.from_random()
        with self._lock:
            self._objects[oid] = array
            self._refcounts[oid] = 1
        owner_address = ""
        from ray_tpu.core.core_worker import try_global_worker

        worker = try_global_worker()
        if worker is not None:
            owner_address = worker.address
        return DeviceRef(
            oid, tuple(array.shape), str(array.dtype), rank, group_name,
            owner_address,
        )

    def retain(self, ref: DeviceRef) -> int:
        """Add one owner-side reference (local fast path, RPC otherwise)."""
        with self._lock:
            if ref.object_id in self._objects:
                self._refcounts[ref.object_id] += 1
                return self._refcounts[ref.object_id]
        return self._owner_call(ref, "device_retain")

    def refcount(self, ref: DeviceRef) -> int:
        with self._lock:
            if ref.object_id in self._refcounts:
                return self._refcounts[ref.object_id]
        return self._owner_call(ref, "device_refcount")

    def _owner_call(self, ref: DeviceRef, method: str) -> int:
        from ray_tpu.core.core_worker import global_worker

        worker = global_worker()
        client = worker.worker_clients.get(ref.owner_address)
        return worker._run_sync(
            client.call(method, {"object_id": ref.object_id})
        )

    def get_local(self, ref: DeviceRef):
        with self._lock:
            arr = self._objects.get(ref.object_id)
        if arr is None:
            raise KeyError(f"device object {ref.object_id} not resident here")
        return arr

    def contains(self, ref: DeviceRef) -> bool:
        with self._lock:
            return ref.object_id in self._objects

    def fetch(self, ref: DeviceRef):
        """Resolve a DeviceRef.  Resolution order:

        1. local hit → the resident array, zero movement;
        2. owner_address set → point-to-point RPC to the owning worker
           (one host hop; works anywhere in the cluster);
        3. fall back to a collective broadcast from the owner rank — all
           group members must call fetch() collectively (the reference's
           NCCL-transport shape; pair with ``serve_fetch`` on the owner).
        """
        if self.contains(ref):
            self.last_transfer_path = "local"
            return self.get_local(ref)
        from .collective import is_group_initialized

        if is_group_initialized(ref.group_name):
            # Collective path: the transfer is a device-level broadcast
            # (jax collective over the mesh — ICI on TPU), no host-staged
            # byte copy.  All group members call fetch() collectively; the
            # owner pairs it with serve_fetch().
            from .collective import get_group

            group = get_group(ref.group_name)
            import jax.numpy as jnp

            placeholder = jnp.zeros(ref.shape, dtype=ref.dtype)
            out = group.broadcast(placeholder, src_rank=ref.owner_rank)
            self.last_transfer_path = "collective"
            return out
        if ref.owner_address:
            out = self._fetch_rpc(ref)
            self.last_transfer_path = "p2p_rpc"
            return out
        raise KeyError(
            f"device object {ref.object_id}: no group initialized and no "
            "owner address to fetch from"
        )

    def _fetch_rpc(self, ref: DeviceRef):
        from ray_tpu.core.core_worker import global_worker

        worker = global_worker()
        client = worker.worker_clients.get(ref.owner_address)
        reply = worker._run_sync(
            client.call("device_fetch", {"object_id": ref.object_id})
        )
        return array_from_fetch_reply(ref, reply)

    def free(self, ref: DeviceRef) -> bool:
        """Drop one reference; the array leaves HBM at refcount zero.
        Remote-owned refs release at the owner via RPC."""
        with self._lock:
            if ref.object_id in self._objects:
                self._refcounts[ref.object_id] -= 1
                if self._refcounts[ref.object_id] <= 0:
                    del self._objects[ref.object_id]
                    del self._refcounts[ref.object_id]
                    return True
                return False
        if ref.owner_address:
            from ray_tpu.core.core_worker import try_global_worker

            worker = try_global_worker()
            if worker is not None:
                try:
                    client = worker.worker_clients.get(ref.owner_address)
                    return worker._run_sync(
                        client.call(
                            "device_free", {"object_id": ref.object_id}
                        )
                    )
                except Exception:  # noqa: BLE001 — owner gone = freed
                    return False
        return False

    def serve_fetch(self, ref: DeviceRef):
        """Owner side of a collective fetch."""
        from .collective import get_group

        group = get_group(ref.group_name)
        return group.broadcast(self.get_local(ref), src_rank=ref.owner_rank)

    def __len__(self):
        return len(self._objects)


def array_from_fetch_reply(ref: DeviceRef, reply: dict):
    """Decode a ``device_fetch`` RPC reply into a device array."""
    import jax.numpy as jnp
    import numpy as np

    if not reply.get("found"):
        raise KeyError(
            f"device object {ref.object_id} no longer resident at "
            f"{ref.owner_address} (evicted or actor restarted)"
        )
    arr = np.frombuffer(
        reply["data"], dtype=np.dtype(ref.dtype)
    ).reshape(ref.shape)
    return jnp.asarray(arr)


_store: Optional[DeviceObjectStore] = None


def device_object_store() -> DeviceObjectStore:
    global _store
    if _store is None:
        _store = DeviceObjectStore()
    return _store
