"""Multi-host XLA collective group.

The TPU-native replacement for the reference's NCCL process group (ray
``util/collective/collective_group/nccl_collective_group.py:121``): instead
of exchanging a NCCL unique-id and managing per-peer streams, members
rendezvous on a JAX coordination-service address (published through the
control-plane KV — the analog of the unique-id-through-GCS-KV pattern in
``nccl_util.py``), call ``jax.distributed.initialize``, and all ops compile
to XLA collectives over the global device mesh: ICI within a slice, DCN
across slices.

Each member process calls every op with its *local* per-host tensor; results
come back as local numpy/jax values, exactly like the reference's eager NCCL
calls — but the op itself is a jitted shard_map, so repeated calls of the
same shape hit the XLA executable cache.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

import numpy as np

from .types import Backend, GroupInfo, ReduceOp

logger = logging.getLogger(__name__)

_KV_NAMESPACE = "collective"


def _kv_rendezvous(group_name: str, rank: int, world_size: int,
                   coordinator_port: Optional[int] = None,
                   timeout: float = 60.0) -> str:
    """Rank 0 publishes the coordination-service address in the control-plane
    KV; everyone else polls for it."""
    from ray_tpu.core.core_worker import global_worker
    from ray_tpu.core.rpc import find_free_port

    worker = global_worker()
    key = f"coord:{group_name}"
    if rank == 0:
        port = coordinator_port or find_free_port()
        addr = f"127.0.0.1:{port}"
        import socket

        try:
            addr = f"{socket.gethostbyname(socket.gethostname())}:{port}"
        except Exception as e:
            # Loopback fallback is correct single-host; multi-host ranks
            # on other machines cannot reach 127.0.0.1, so say so.
            logger.info(
                "hostname resolution failed (%s); publishing loopback "
                "coordinator address %s", e, addr,
            )
        worker._run_sync(
            worker.cp.call(
                "kv_put",
                {"namespace": _KV_NAMESPACE, "key": key, "value": addr.encode()},
            )
        )
        return addr
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        val = worker._run_sync(
            worker.cp.call("kv_get", {"namespace": _KV_NAMESPACE, "key": key})
        )
        if val is not None:
            return val.decode()
        time.sleep(0.1)
    raise TimeoutError(f"rendezvous for group {group_name!r} timed out")


class XlaGroup:
    """One member (process) of a multi-host collective group."""

    def __init__(
        self,
        group_name: str,
        world_size: int,
        rank: int,
        coordinator_address: Optional[str] = None,
        local_device_count: Optional[int] = None,
        hosts_per_slice: Optional[int] = None,
    ):
        import jax

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        if coordinator_address is None:
            coordinator_address = _kv_rendezvous(group_name, rank, world_size)
        self.coordinator_address = coordinator_address
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=world_size,
            process_id=rank,
        )
        from jax.sharding import Mesh

        from .types import Topology

        devices = jax.devices()
        self.devices_per_host = len(devices) // world_size
        self.mesh = Mesh(
            np.array(devices).reshape(world_size, self.devices_per_host),
            ("host", "device"),
        )
        # ``hosts_per_slice``: group members per TPU slice.  Default: the
        # whole group is one slice (every hop ICI).  Multi-slice groups
        # (cross-slice DCN) unlock the two-level algorithms, whose DCN
        # hop carries 1/hosts_per_slice of the payload.
        self.topology = Topology(world_size, hosts_per_slice or world_size)
        self._mesh3 = None  # (dcn, ici, device) view for two-level ops
        self._fn_cache: Dict[tuple, object] = {}
        self._last_decision = None
        # Flight recorder: per-op bytes/duration/bandwidth capture.  These
        # ops materialize results to numpy (host sync), so the recorded
        # durations reflect the real collective, ICI included.
        from ..util import flight_recorder

        flight_recorder.instrument_group(self, "xla")

    def info(self) -> GroupInfo:
        return GroupInfo(self.group_name, self.world_size, self.rank, Backend.XLA)

    # ------------------------------------------------------------- plumbing
    def _global_from_local(self, tensor):
        """Treat each host's tensor as one shard along the leading axis."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        local = np.asarray(tensor)
        sharding = NamedSharding(self.mesh, P(("host",)))
        global_shape = (self.world_size, *local.shape)
        return jax.make_array_from_process_local_data(
            sharding, local[None], global_shape
        )

    def _local_from_global(self, arr):
        shards = arr.addressable_shards
        return np.asarray(shards[0].data)

    def _build(self, key, body, out_replicated=False):
        import jax
        from jax.sharding import PartitionSpec as P

        from .types import compat_shard_map

        fn = self._fn_cache.get(key)
        if fn is None:
            out_spec = P() if out_replicated else P(("host",))
            fn = jax.jit(
                compat_shard_map(body, self.mesh, (P(("host",)),), out_spec)
            )
            self._fn_cache[key] = fn
        return fn

    def _build2(self, key, body):
        """shard_map over the (dcn, ici, device) three-axis view — the
        host axis split into inter-slice x intra-slice for the two-level
        algorithms; the per-host device axis stays replicated exactly as
        in the flat path."""
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        from .types import compat_shard_map

        fn = self._fn_cache.get(key)
        if fn is None:
            if self._mesh3 is None:
                topo = self.topology
                self._mesh3 = Mesh(
                    np.array(jax.devices()).reshape(
                        topo.dcn_size, topo.ici_size, self.devices_per_host
                    ),
                    ("dcn", "ici", "device"),
                )
            spec = P(("dcn", "ici"))
            fn = jax.jit(compat_shard_map(body, self._mesh3, (spec,), spec))
            self._fn_cache[key] = fn
        return fn

    # ----------------------------------------------------- tuner plumbing
    def _tuner_sync(self, vec: np.ndarray) -> np.ndarray:
        """Allreduce-MEAN of the tuner's measurement table across group
        members, via a dedicated always-flat psum (never routed through
        the selection layer — selection must not depend on itself).
        Called at deterministic commit points, so every member reaches
        this collective at the same point in its call sequence."""
        import jax

        g = self._global_from_local(np.asarray(vec, np.float64))

        def body(x):
            return jax.lax.psum(x, "host")

        out = self._build(("tuner_sync", g.shape), body)(g)
        return self._local_from_global(out)[0] / self.world_size

    def _select(self, op: str, nbytes: int, quantized: bool) -> str:
        from .tuner import select_for_group

        return select_for_group(
            self, op, nbytes, quantized,
            sync=self._tuner_sync if self.world_size > 1 else None,
        )

    def _resolve_quantized(self, op: ReduceOp, dtype, quantized) -> bool:
        from .algorithms import resolve_quantized

        return resolve_quantized(op, dtype, quantized)

    # ------------------------------------------------------------------ ops
    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM,
                  quantized: bool = None):
        import jax
        import jax.numpy as jnp

        from . import algorithms as alg
        from ..core.config import GlobalConfig

        g = self._global_from_local(tensor)
        quantized = self._resolve_quantized(op, g.dtype, quantized)
        self._last_decision = None

        if op != ReduceOp.SUM:
            def body(x):
                red = {
                    ReduceOp.MAX: jax.lax.pmax,
                    ReduceOp.MIN: jax.lax.pmin,
                    ReduceOp.MEAN: jax.lax.pmean,
                }.get(op)
                if red is None:  # PRODUCT
                    return jnp.prod(
                        jax.lax.all_gather(x[0], "host"), axis=0
                    )[None]
                return red(x, "host")

            out = self._build(("ar", op, g.shape, str(g.dtype)), body)(g)
            return self._local_from_global(out)[0]

        nbytes = g.nbytes // max(1, self.world_size)
        algo = self._select("allreduce", nbytes, quantized)
        n = self.world_size
        topo = self.topology
        block = GlobalConfig.collective_quant_block_size

        if algo in (alg.TWO_LEVEL, alg.TWO_LEVEL_Q8):
            def body(x):
                return alg.two_level_allreduce(
                    x[0], "ici", "dcn", topo.ici_size,
                    quantized=(algo == alg.TWO_LEVEL_Q8), block_size=block,
                )[None]

            out = self._build2(
                ("ar2", algo, block, g.shape, str(g.dtype)), body
            )(g)
        else:
            def body(x):
                if algo == alg.RING:
                    return alg.ring_allreduce(x[0], "host", n)[None]
                if algo == alg.TREE:
                    return alg.tree_allreduce(x[0], "host", n)[None]
                if algo == alg.FLAT_Q8:
                    return alg.quantized_allreduce(
                        x[0], "host", block_size=block
                    )[None]
                return jax.lax.psum(x, "host")

            out = self._build(
                ("ar", op, algo, block if quantized else 0, g.shape,
                 str(g.dtype)),
                body,
            )(g)
        return self._local_from_global(out)[0]

    def allgather(self, tensor):
        import jax

        from . import algorithms as alg

        g = self._global_from_local(tensor)
        self._last_decision = None
        algo = self._select(
            "allgather", g.nbytes // max(1, self.world_size), False
        )
        n = self.world_size

        def body(x):
            if algo == alg.RING:
                return alg.ring_allgather(x[0], "host", n)[None]
            return jax.lax.all_gather(x[0], "host")[None]

        out = self._build(("ag", algo, g.shape, str(g.dtype)), body)(g)
        return list(self._local_from_global(out)[0])

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        import jax
        import jax.numpy as jnp

        from . import algorithms as alg

        g = self._global_from_local(tensor)
        n = self.world_size
        self._last_decision = None
        algo = alg.FLAT
        if op == ReduceOp.SUM:
            algo = self._select(
                "reducescatter", g.nbytes // max(1, n), False
            )

        def body(x):
            if op == ReduceOp.SUM:
                if algo == alg.RING:
                    return alg.ring_reducescatter(x[0], "host", n)[None]
                return jax.lax.psum_scatter(
                    x[0], "host", scatter_dimension=0, tiled=True
                )[None]
            gathered = jax.lax.all_gather(x[0], "host")
            reducer = {
                ReduceOp.MAX: jnp.max,
                ReduceOp.MIN: jnp.min,
                ReduceOp.MEAN: jnp.mean,
                ReduceOp.PRODUCT: jnp.prod,
            }[op]
            red = reducer(gathered, axis=0)
            rank = jax.lax.axis_index("host")
            chunk = red.shape[0] // n
            return jax.lax.dynamic_slice_in_dim(red, rank * chunk, chunk)[None]

        out = self._build(("rs", op, algo, g.shape, str(g.dtype)), body)(g)
        return self._local_from_global(out)[0]

    def broadcast(self, tensor, src_rank: int = 0):
        import jax

        g = self._global_from_local(tensor)

        def body(x):
            return jax.lax.all_gather(x[0], "host")[src_rank][None]

        out = self._build(("bc", src_rank, g.shape, str(g.dtype)), body)(g)
        return self._local_from_global(out)[0]

    def alltoall(self, tensor):
        import jax

        g = self._global_from_local(tensor)

        def body(x):
            return jax.lax.all_to_all(
                x, "host", split_axis=1, concat_axis=0, tiled=False
            ).reshape(x.shape)

        out = self._build(("a2a", g.shape, str(g.dtype)), body)(g)
        return self._local_from_global(out)[0]

    def ppermute(self, tensor, shift: int = 1):
        import jax

        g = self._global_from_local(tensor)
        n = self.world_size
        perm = [(i, (i + shift) % n) for i in range(n)]

        def body(x):
            return jax.lax.ppermute(x, "host", perm)

        out = self._build(("pp", shift, g.shape, str(g.dtype)), body)(g)
        return self._local_from_global(out)[0]

    def barrier(self):
        self.allreduce(np.zeros((1,), np.float32))

    def shutdown(self):
        # jax.distributed can only be initialized once per process; keep the
        # runtime up but drop the cache.
        self._fn_cache.clear()
