"""Placement groups — Python frontend.

Equivalent of ray ``python/ray/util/placement_group.py``: gang resource
reservation via the control plane's two-phase commit.  The TPU-first addition
is ``SlicePlacementGroup``: reserve an entire TPU slice by topology as one
atomic unit (reference precedent: ray ``python/ray/util/tpu.py:52``).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from .core_worker import global_worker
from .ids import PlacementGroupID
from .scheduler import PlacementGroupStrategy

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 created: bool = False):
        self.id = pg_id
        self.bundles = bundles
        # Creation-reply fast path: the control plane's group-commit sweep
        # runs BEFORE the create RPC replies, so in the common case the
        # reply already says CREATED and ready() never needs a poll.
        # Only a positive CREATED is cached — PENDING always re-polls.
        self._created = created

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the group is created (2-phase commit finished)."""
        if self._created:
            return True
        worker = global_worker()
        deadline = None if timeout is None else time.monotonic() + timeout

        while True:
            info = worker._run_sync(
                worker.cp.call("get_placement_group", {"pg_id": self.id})
            )
            if info is None:
                raise ValueError(f"placement group {self.id} unknown")
            if info["state"] == "CREATED":
                self._created = True
                return True
            if info["state"] == "REMOVED":
                raise ValueError(f"placement group {self.id} was removed")
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.05)

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self.bundles)

    def __reduce__(self):
        # The cached CREATED flag deliberately does not travel: a
        # deserialized handle re-verifies against the control plane.
        return (PlacementGroup, (self.id, self.bundles))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    priority: Optional[int] = None,
) -> PlacementGroup:
    """``priority`` overrides the owning job's priority for this group
    only (higher = more important; the default comes from the job's
    registration, falling back to ``sched_default_priority``).  The
    control plane may checkpoint-then-evict lower-priority groups to
    place this one — see ``docs/scheduling.md``."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    worker = global_worker()
    pg_id = PlacementGroupID.from_random()
    info = worker._run_sync(
        worker.cp.call(
            "create_placement_group",
            {"pg_id": pg_id, "bundles": bundles, "strategy": strategy,
             "name": name, "job_id": worker.job_id, "priority": priority},
        )
    )
    created = bool(info) and info.get("state") == "CREATED"
    return PlacementGroup(pg_id, bundles, created=created)


def remove_placement_group(pg: PlacementGroup):
    worker = global_worker()
    worker._run_sync(worker.cp.call("remove_placement_group", {"pg_id": pg.id}))


def placement_group_strategy(
    pg: PlacementGroup, bundle_index: int = -1
) -> PlacementGroupStrategy:
    """Scheduling-strategy object for @remote(scheduling_strategy=…)."""
    return PlacementGroupStrategy(pg.id.hex(), bundle_index)


def pipeline_stage_placement_group(
    num_stages: int,
    resources_per_stage: Optional[Dict[str, float]] = None,
    chips_per_stage: int = 0,
    accelerator_version: str = "",
    name: str = "",
    priority: Optional[int] = None,
) -> PlacementGroup:
    """One bundle per pipeline stage — the MPMD trainer's placement shape.

    Each stage actor pins to its own bundle so adjacent stages land on
    distinct slices (SPREAD; STRICT_SPREAD when TPU chips are requested,
    matching ``SlicePlacementGroup``'s whole-slice ownership semantics:
    a stage's ICI mesh is never shared with its neighbor).  On a CPU
    cluster the bundles degrade to per-host/per-process CPU bundles,
    which is what the tier-1 tests exercise.
    """
    if num_stages < 1:
        raise ValueError("num_stages must be >= 1")
    if resources_per_stage:
        bundle = dict(resources_per_stage)
    else:
        bundle = {"CPU": 1.0}
    if chips_per_stage:
        bundle["TPU"] = float(chips_per_stage)
        if accelerator_version:
            bundle[f"TPU-{accelerator_version}"] = float(chips_per_stage)
    if num_stages == 1:
        strategy = "PACK"
    elif "TPU" in bundle:
        strategy = "STRICT_SPREAD"
    else:
        strategy = "SPREAD"
    return placement_group(
        [dict(bundle) for _ in range(num_stages)],
        strategy=strategy,
        name=name,
        priority=priority,
    )


class PodracerPlacement:
    """Device-role bundles for podracer RL (arxiv 2104.06272).

    One placement group with two runs of bundles: ``num_actor_bundles``
    "actor"-role bundles (Sebulba env-runner actors doing batched
    inference on their local devices; Anakin jobs pin whole trainers
    here to share chips with other workloads) followed by
    ``num_learner_bundles`` "learner"-role bundles (the v-trace
    learner).  Reserving both roles in ONE group keeps the gang atomic —
    a half-placed Sebulba job (runners without a learner) can never hold
    resources.  SPREAD by default; STRICT_SPREAD when chips are
    requested, matching ``SlicePlacementGroup`` whole-slice ownership.
    """

    def __init__(
        self,
        num_actor_bundles: int,
        num_learner_bundles: int = 1,
        actor_resources: Optional[Dict[str, float]] = None,
        learner_resources: Optional[Dict[str, float]] = None,
        chips_per_actor: int = 0,
        chips_per_learner: int = 0,
        accelerator_version: str = "",
        name: str = "",
        priority: Optional[int] = None,
    ):
        if num_actor_bundles < 1 or num_learner_bundles < 0:
            raise ValueError(
                "need >= 1 actor bundle and >= 0 learner bundles"
            )
        self.num_actor_bundles = num_actor_bundles
        self.num_learner_bundles = num_learner_bundles

        def _bundle(base, chips):
            b = dict(base) if base else {"CPU": 1.0}
            if chips:
                b["TPU"] = float(chips)
                if accelerator_version:
                    b[f"TPU-{accelerator_version}"] = float(chips)
            return b

        actor_bundle = _bundle(actor_resources, chips_per_actor)
        learner_bundle = _bundle(learner_resources, chips_per_learner)
        bundles = [dict(actor_bundle) for _ in range(num_actor_bundles)]
        bundles += [dict(learner_bundle) for _ in range(num_learner_bundles)]
        any_tpu = "TPU" in actor_bundle or "TPU" in learner_bundle
        if len(bundles) == 1:
            strategy = "PACK"
        elif any_tpu:
            strategy = "STRICT_SPREAD"
        else:
            strategy = "SPREAD"
        self.pg = placement_group(
            bundles, strategy=strategy, name=name, priority=priority
        )

    def ready(self, timeout: Optional[float] = None) -> bool:
        return self.pg.ready(timeout)

    def actor_strategy(self, index: int) -> PlacementGroupStrategy:
        """Scheduling strategy pinning into actor-role bundle ``index``."""
        if not 0 <= index < self.num_actor_bundles:
            raise IndexError(f"actor bundle {index} out of range")
        return placement_group_strategy(self.pg, index)

    def learner_strategy(self, index: int = 0) -> PlacementGroupStrategy:
        """Scheduling strategy pinning into learner-role bundle ``index``."""
        if not 0 <= index < self.num_learner_bundles:
            raise IndexError(f"learner bundle {index} out of range")
        return placement_group_strategy(
            self.pg, self.num_actor_bundles + index
        )

    def remove(self):
        remove_placement_group(self.pg)


def podracer_placement_group(
    num_actor_bundles: int,
    num_learner_bundles: int = 1,
    **kwargs,
) -> PodracerPlacement:
    """Reserve actor/learner device-role bundles for a podracer RL job."""
    return PodracerPlacement(
        num_actor_bundles, num_learner_bundles, **kwargs
    )


class SlicePlacementGroup:
    """Reserve a whole TPU slice (all hosts of a pod) as one gang unit.

    One bundle per host, each requesting the host's chips; STRICT_SPREAD so
    each bundle lands on a distinct host of the slice.  Workers of a
    JaxTrainer-style gang schedule into these bundles, guaranteeing the ICI
    mesh is fully owned by one job.
    """

    def __init__(
        self,
        num_hosts: int,
        chips_per_host: int = 4,
        accelerator_version: str = "",
        name: str = "",
        priority: Optional[int] = None,
    ):
        self.num_hosts = num_hosts
        self.chips_per_host = chips_per_host
        resource = f"TPU-{accelerator_version}" if accelerator_version else "TPU"
        bundles = [
            {"TPU": float(chips_per_host)} for _ in range(num_hosts)
        ]
        if accelerator_version:
            for b in bundles:
                b[resource] = float(chips_per_host)
        strategy = "STRICT_SPREAD" if num_hosts > 1 else "PACK"
        self.pg = placement_group(
            bundles, strategy=strategy, name=name, priority=priority
        )

    def ready(self, timeout: Optional[float] = None) -> bool:
        return self.pg.ready(timeout)

    def strategy_for_host(self, host_index: int) -> PlacementGroupStrategy:
        return placement_group_strategy(self.pg, host_index)

    def remove(self):
        remove_placement_group(self.pg)
