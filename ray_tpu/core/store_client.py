"""Pluggable control-plane metadata storage.

Equivalent of the reference's GCS ``StoreClient`` hierarchy
(ray ``src/ray/gcs/store_client/store_client.h``: in-memory default,
``redis_store_client.h:126`` for HA) behind the same two-method surface the
GCS table storage uses (``gcs/gcs_table_storage.h:200``).  TPU-native
redesign: instead of an external Redis, the durable backend is an embedded
sqlite journal under the session directory — one file, crash-atomic
(WAL), zero extra processes to operate — which is the right trade for a
single-control-plane cluster on a TPU pod (the reference needs Redis
because its HA story is multi-GCS; ours is restart-with-reload, covered by
every client's retrying reconnect + re-register protocol).

Tables are string-named ("kv", "actors", "pgs", "jobs"); values are opaque
bytes (callers pickle).  All methods are synchronous and fast (sqlite WAL
commit ~100 µs) — they are called from the control plane's event loop on
mutation paths only, never on reads (reads hit the in-memory state that
``load_all`` rebuilt at startup).
"""

from __future__ import annotations

import logging
import os
import sqlite3
from typing import Dict, Iterator, Optional, Tuple

logger = logging.getLogger(__name__)


class StoreClient:
    """Interface: durable puts/deletes + full-table scan at recovery."""

    durable = False

    def put(self, table: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, table: str, key: str) -> None:
        raise NotImplementedError

    def scan(self, table: str) -> Iterator[Tuple[str, bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStoreClient(StoreClient):
    """The reference's default: no persistence, restart loses state.

    Writes are NO-OPS: the control plane's live tables already hold the
    state, and this store is only ever read back at startup recovery
    (always empty for a non-durable backend) — buffering a pickled copy of
    every mutation here would be pure overhead."""

    def put(self, table: str, key: str, value: bytes) -> None:
        pass

    def delete(self, table: str, key: str) -> None:
        pass

    def scan(self, table: str):
        return iter(())


class SqliteStoreClient(StoreClient):
    """Durable embedded store (the RedisStoreClient role).  WAL mode so a
    control-plane crash mid-write never corrupts the file; synchronous=
    NORMAL bounds the loss window to the last WAL checkpoint on an OS
    crash, which matches the reference's Redis-async-replication window."""

    durable = True

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._db = sqlite3.connect(path)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS store ("
            " tbl TEXT NOT NULL, key TEXT NOT NULL, value BLOB NOT NULL,"
            " PRIMARY KEY (tbl, key))"
        )
        self._db.commit()

    def put(self, table: str, key: str, value: bytes) -> None:
        self._db.execute(
            "INSERT OR REPLACE INTO store (tbl, key, value) VALUES (?, ?, ?)",
            (table, key, sqlite3.Binary(value)),
        )
        self._db.commit()

    def delete(self, table: str, key: str) -> None:
        self._db.execute(
            "DELETE FROM store WHERE tbl = ? AND key = ?", (table, key)
        )
        self._db.commit()

    def scan(self, table: str):
        cur = self._db.execute(
            "SELECT key, value FROM store WHERE tbl = ?", (table,)
        )
        for key, value in cur:
            yield key, bytes(value)

    def close(self) -> None:
        try:
            self._db.close()
        except Exception as e:
            logger.debug("store db close failed: %s", e)


def make_store_client(path: Optional[str]) -> StoreClient:
    return SqliteStoreClient(path) if path else InMemoryStoreClient()
