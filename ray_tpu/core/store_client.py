"""Pluggable control-plane metadata storage.

Equivalent of the reference's GCS ``StoreClient`` hierarchy
(ray ``src/ray/gcs/store_client/store_client.h``: in-memory default,
``redis_store_client.h:126`` for HA) behind the same two-method surface the
GCS table storage uses (``gcs/gcs_table_storage.h:200``).  TPU-native
redesign: instead of an external Redis, the durable backends are embedded
under the session directory — zero extra processes to operate — which is
the right trade for a control plane on a TPU pod:

  - ``SqliteStoreClient``: one crash-atomic (WAL) file, for the
    single-control-plane restart-with-reload story, covered by every
    client's retrying reconnect + re-register protocol.
  - ``JournaledStoreClient``: a segmented write-ahead journal plus
    periodic snapshots, for the HA story (``core/cp_ha.py``) — a warm
    standby TAILS the journal to hold the full table set hot, and on
    lease takeover ``promote()``s into the writer role under a new
    fencing epoch, so the reference's replicated-Redis role is played by
    a shared filesystem journal instead of an external store.

Tables are string-named ("kv", "actors", "pgs", "jobs", "obs_seen");
values are opaque bytes (callers pickle).  All methods are synchronous and
fast — they are called from the control plane's event loop on mutation
paths only, never on reads (reads hit the in-memory state that recovery
rebuilt at startup).
"""

from __future__ import annotations

import contextlib
import logging
import os
import pickle
import re
import sqlite3
import struct
import time
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

logger = logging.getLogger(__name__)


class FencedWriteError(Exception):
    """A journal append was rejected because this writer's leader lease
    epoch is no longer current — a newer leader exists.  The only safe
    reaction is to stop writing and exit; retrying cannot succeed."""


class StoreClient:
    """Interface: durable puts/deletes + full-table scan at recovery."""

    durable = False

    def put(self, table: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, table: str, key: str) -> None:
        raise NotImplementedError

    def scan(self, table: str) -> Iterator[Tuple[str, bytes]]:
        raise NotImplementedError

    @contextlib.contextmanager
    def transaction(self):
        """Group several puts/deletes into one atomic unit where the
        backend supports it (sqlite); elsewhere a no-op grouping."""
        yield

    def close(self) -> None:
        pass


class InMemoryStoreClient(StoreClient):
    """The reference's default: no persistence, restart loses state.

    Writes are NO-OPS: the control plane's live tables already hold the
    state, and this store is only ever read back at startup recovery
    (always empty for a non-durable backend) — buffering a pickled copy of
    every mutation here would be pure overhead."""

    def put(self, table: str, key: str, value: bytes) -> None:
        pass

    def delete(self, table: str, key: str) -> None:
        pass

    def scan(self, table: str):
        return iter(())


class SqliteStoreClient(StoreClient):
    """Durable embedded store (the RedisStoreClient role).  WAL mode so a
    control-plane crash mid-write never corrupts the file; synchronous=
    NORMAL bounds the loss window to the last WAL checkpoint on an OS
    crash, which matches the reference's Redis-async-replication window."""

    durable = True

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._db = sqlite3.connect(path)
        self._in_txn = False
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS store ("
            " tbl TEXT NOT NULL, key TEXT NOT NULL, value BLOB NOT NULL,"
            " PRIMARY KEY (tbl, key))"
        )
        self._db.commit()

    def put(self, table: str, key: str, value: bytes) -> None:
        self._db.execute(
            "INSERT OR REPLACE INTO store (tbl, key, value) VALUES (?, ?, ?)",
            (table, key, sqlite3.Binary(value)),
        )
        if not self._in_txn:
            self._db.commit()

    def delete(self, table: str, key: str) -> None:
        self._db.execute(
            "DELETE FROM store WHERE tbl = ? AND key = ?", (table, key)
        )
        if not self._in_txn:
            self._db.commit()

    @contextlib.contextmanager
    def transaction(self):
        """Multi-table mutations (e.g. a preemption persisting the PG and
        its evicted actors) commit atomically: a crash mid-group leaves
        the store at the previous commit point, never half-applied."""
        if self._in_txn:  # reentrant: inner group joins the outer one
            yield
            return
        self._in_txn = True
        try:
            yield
        except BaseException:
            self._db.rollback()
            raise
        else:
            self._db.commit()
        finally:
            self._in_txn = False

    def scan(self, table: str):
        cur = self._db.execute(
            "SELECT key, value FROM store WHERE tbl = ?", (table,)
        )
        for key, value in cur:
            yield key, bytes(value)

    def close(self) -> None:
        try:
            self._db.close()
        except Exception as e:
            logger.debug("store db close failed: %s", e)


# --------------------------------------------------------------- journal
#
# Record wire format (one file per leader epoch, ``journal-<epoch>.wal``):
#
#     [4B LE payload length][4B LE crc32(payload)][payload]
#     payload = pickle((seq, op, table, key, value))
#
# ``seq`` is a journal-wide monotonic sequence; ``op`` is "put" / "del" /
# "seal".  A seal is the FIRST record of every segment: its value maps
# prior segment filenames to their valid byte lengths, so records a fenced
# stale leader appended after the takeover point are never replayed (and
# crash-torn tails — short reads or crc mismatches — stop replay of a
# segment early by construction).  Snapshots (``snapshot-<seq>.pkl``) are
# whole-table pickles written tmp+rename; replay starts from the newest
# loadable snapshot and skips records at or below its sequence.

_REC_HDR = struct.Struct("<II")
_REC_MAX = 1 << 28  # corruption guard: no record is anywhere near 256 MiB
_SEG_RE = re.compile(r"^journal-(\d{8})\.wal$")
_SNAP_RE = re.compile(r"^snapshot-(\d{16})\.pkl$")


def _encode_record(rec) -> bytes:
    payload = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
    return _REC_HDR.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload


def _read_records(f, limit: Optional[int] = None):
    """Yield ``(record, end_offset)`` for each COMPLETE record from the
    file's current position, stopping cleanly at a torn tail (short read
    or crc mismatch) or at ``limit`` bytes."""
    off = f.tell()
    while True:
        if limit is not None and off >= limit:
            return
        hdr = f.read(_REC_HDR.size)
        if len(hdr) < _REC_HDR.size:
            f.seek(off)
            return
        length, crc = _REC_HDR.unpack(hdr)
        if length > _REC_MAX:
            f.seek(off)
            return
        payload = f.read(length)
        if len(payload) < length or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            f.seek(off)
            return
        off += _REC_HDR.size + length
        try:
            rec = pickle.loads(payload)
        except Exception:  # raylint: waive[RTL003] torn/corrupt tail ends replay
            f.seek(off - _REC_HDR.size - length)
            return
        yield rec, off


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError as e:
        logger.debug("dir fsync failed for %s: %s", path, e)


class JournaledStoreClient(StoreClient):
    """Write-ahead journal + snapshots under a shared directory.

    Two roles over one class:
      - FOLLOWER (constructed without a lease): loads the newest snapshot
        plus journal, then ``tail()`` applies new records incrementally,
        keeping the in-memory table mirror hot for an instant takeover.
      - LEADER (after ``promote(lease)``): opens a fresh epoch segment,
        seals every prior segment at the replayed length, and appends
        mutations — each append first checks the lease (``verify()``
        raises ``FencedWriteError`` once a newer epoch exists), then
        writes + flushes the record to the OS (surviving ``kill -9`` of
        the process) with fsyncs batched on a time interval.

    Compaction: once ``compact_bytes`` of journal accumulate past the
    last snapshot, the leader writes a new snapshot and deletes sealed
    (non-active) segments and older snapshots; the active segment is
    reclaimed at the next promote.
    """

    durable = True

    def __init__(self, dir_path: str, fsync_interval_s: Optional[float] = None,
                 compact_bytes: Optional[int] = None):
        from .config import GlobalConfig

        os.makedirs(dir_path, exist_ok=True)
        self.dir = dir_path
        self._fsync_interval = (
            fsync_interval_s if fsync_interval_s is not None
            else GlobalConfig.cp_journal_fsync_interval_s
        )
        self._compact_bytes = (
            compact_bytes if compact_bytes is not None
            else GlobalConfig.cp_journal_compact_bytes
        )
        self._tables: Dict[str, Dict[str, bytes]] = {}
        self.applied_seq = 0
        self.epoch = 0               # epoch of the segment being read/written
        self.snapshot_seq = 0
        self.records_written = 0
        self._lease = None
        self._write_f = None
        self._read_f = None
        self._read_name: Optional[str] = None
        self._consumed: Dict[str, int] = {}  # segment -> bytes replayed
        self._bytes_since_snap = 0
        self._last_fsync = time.monotonic()
        self._load()

    # ------------------------------------------------------------- loading
    def _segments(self) -> List[Tuple[int, str]]:
        out = []
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return []
        for name in names:
            m = _SEG_RE.match(name)
            if m:
                out.append((int(m.group(1)), name))
        out.sort()
        return out

    def _snapshots(self) -> List[Tuple[int, str]]:
        out = []
        for name in os.listdir(self.dir):
            m = _SNAP_RE.match(name)
            if m:
                out.append((int(m.group(1)), name))
        out.sort()
        return out

    def _load(self) -> None:
        """Full (re)build: newest loadable snapshot, then a two-pass
        journal replay — pass 1 scans every segment for seal caps (seals
        in LATER segments cap EARLIER ones), pass 2 applies put/del
        records inside the capped regions in epoch order."""
        if self._read_f is not None:
            try:
                self._read_f.close()
            except OSError:
                pass
            self._read_f = None
            self._read_name = None
        self._tables = {}
        self.applied_seq = 0
        self.snapshot_seq = 0
        self._consumed = {}
        for snap_seq, name in reversed(self._snapshots()):
            try:
                with open(os.path.join(self.dir, name), "rb") as f:
                    snap = pickle.load(f)
                self._tables = {
                    t: dict(kv) for t, kv in snap["tables"].items()
                }
                self.applied_seq = self.snapshot_seq = snap["seq"]
                break
            except Exception as e:  # raylint: waive[RTL003] torn snapshot: fall back to the previous one
                logger.warning("journal snapshot %s unreadable: %s", name, e)
        segs = self._segments()
        caps: Dict[str, int] = {}
        lengths: Dict[str, int] = {}
        for _epoch, name in segs:
            valid = 0
            try:
                with open(os.path.join(self.dir, name), "rb") as f:
                    for rec, end in _read_records(f):
                        if rec[1] == "seal" and isinstance(rec[4], dict):
                            for capped, length in rec[4].items():
                                caps[capped] = min(
                                    caps.get(capped, length), length
                                )
                        valid = end
            except OSError as e:
                logger.warning("journal segment %s unreadable: %s", name, e)
            lengths[name] = valid
        for epoch, name in segs:
            limit = min(lengths[name], caps.get(name, lengths[name]))
            try:
                with open(os.path.join(self.dir, name), "rb") as f:
                    for rec, end in _read_records(f, limit=limit):
                        self._apply(rec)
                        self._consumed[name] = end
            except OSError:
                continue
            self._consumed.setdefault(name, 0)
            self.epoch = epoch
        if segs:
            # Keep tailing the newest segment from where replay stopped.
            _epoch, name = segs[-1]
            try:
                self._read_f = open(os.path.join(self.dir, name), "rb")
                self._read_f.seek(self._consumed.get(name, 0))
                self._read_name = name
            except OSError:
                self._read_f = None

    def _apply(self, rec) -> None:
        seq, op, table, key, value = rec
        if seq <= self.applied_seq:
            return
        self.applied_seq = seq
        if op == "put":
            self._tables.setdefault(table, {})[key] = value
        elif op == "del":
            self._tables.get(table, {}).pop(key, None)
        # "seal" records only advance the sequence.

    # ------------------------------------------------------------- follower
    def tail(self) -> int:
        """Apply any newly appended complete records; returns the number
        applied.  Crossing into a newer-epoch segment validates the seal
        caps — if this follower somehow replayed PAST a cap (stale-leader
        records), it rebuilds from scratch instead of serving them."""
        applied = 0
        while True:
            applied += self._drain_current()
            nxt = None
            for epoch, name in self._segments():
                if epoch > self.epoch:
                    nxt = (epoch, name)
                    break
            if nxt is None:
                return applied
            epoch, name = nxt
            try:
                f = open(os.path.join(self.dir, name), "rb")
            except OSError:
                # Compacted away mid-switch: rebuild from snapshot.
                self._load()
                return applied
            it = _read_records(f)
            try:
                rec, end = next(it)
            except StopIteration:
                # Seal not flushed yet; retry on the next tail().
                f.close()
                return applied
            if rec[1] == "seal" and isinstance(rec[4], dict):
                for capped, length in rec[4].items():
                    if self._consumed.get(capped, 0) > length:
                        f.close()
                        self._load()
                        return applied
                    if (
                        capped == self._read_name
                        and self._read_f is not None
                        and self._consumed.get(capped, 0) < length
                    ):
                        # Records we haven't replayed yet live below the
                        # cap; drain them before switching (the fd stays
                        # valid even if the file was unlinked).
                        for old_rec, old_end in _read_records(
                            self._read_f, limit=length
                        ):
                            self._apply(old_rec)
                            self._consumed[capped] = old_end
                            applied += 1
            self._apply(rec)
            if self._read_f is not None:
                try:
                    self._read_f.close()
                except OSError:
                    pass
            self._read_f = f
            self._read_name = name
            self._consumed[name] = end
            self.epoch = epoch
            applied += 1

    def _drain_current(self) -> int:
        if self._read_f is None:
            return 0
        n = 0
        for rec, end in _read_records(self._read_f):
            self._apply(rec)
            self._consumed[self._read_name] = end
            n += 1
        return n

    # --------------------------------------------------------------- leader
    def promote(self, lease) -> None:
        """Become the writer for ``lease.epoch``: replay everything still
        in the journal, open the new epoch's segment, seal all prior
        segments at exactly the replayed lengths (excluding torn tails and
        anything a fenced stale leader appends later), snapshot, and
        reclaim the old files."""
        self.tail()
        caps = dict(self._consumed)
        if self._read_f is not None:
            try:
                self._read_f.close()
            except OSError:
                pass
            self._read_f = None
            self._read_name = None
        self._lease = lease
        self.epoch = lease.epoch
        name = f"journal-{lease.epoch:08d}.wal"
        self._write_f = open(os.path.join(self.dir, name), "ab")
        self.applied_seq += 1
        seal = _encode_record((self.applied_seq, "seal", "", "", caps))
        self._write_f.write(seal)
        self._write_f.flush()
        os.fsync(self._write_f.fileno())
        _fsync_dir(self.dir)
        self._last_fsync = time.monotonic()
        self._bytes_since_snap = 0
        self._write_snapshot()
        for _epoch, old in self._segments():
            if old != name:
                try:
                    os.unlink(os.path.join(self.dir, old))
                except OSError as e:
                    logger.debug("stale segment unlink failed: %s", e)

    def put(self, table: str, key: str, value: bytes) -> None:
        self._append("put", table, key, value)
        self._tables.setdefault(table, {})[key] = value

    def delete(self, table: str, key: str) -> None:
        self._append("del", table, key, None)
        self._tables.get(table, {}).pop(key, None)

    def _append(self, op: str, table: str, key: str, value) -> None:
        if self._write_f is None:
            raise FencedWriteError("journal not promoted to writer")
        if self._lease is not None:
            self._lease.verify()  # raises FencedWriteError when superseded
        rec = _encode_record((self.applied_seq + 1, op, table, key, value))
        self._write_f.write(rec)
        # flush() pushes to the OS page cache: a kill -9 of THIS process
        # loses nothing (the standby on the same host reads it back);
        # fsync (whole-host crash safety) is batched on a time interval,
        # the same bounded window as sqlite synchronous=NORMAL.
        self._write_f.flush()
        self.applied_seq += 1
        self.records_written += 1
        self._bytes_since_snap += len(rec)
        now = time.monotonic()
        if now - self._last_fsync >= self._fsync_interval:
            os.fsync(self._write_f.fileno())
            self._last_fsync = now
        if self._bytes_since_snap >= self._compact_bytes:
            self._compact()

    def _write_snapshot(self) -> None:
        name = f"snapshot-{self.applied_seq:016d}.pkl"
        tmp = os.path.join(self.dir, name + ".tmp")
        with open(tmp, "wb") as f:
            pickle.dump(
                {"seq": self.applied_seq, "epoch": self.epoch,
                 "tables": self._tables},
                f, protocol=pickle.HIGHEST_PROTOCOL,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.dir, name))
        _fsync_dir(self.dir)
        for snap_seq, old in self._snapshots():
            if snap_seq < self.applied_seq:
                try:
                    os.unlink(os.path.join(self.dir, old))
                except OSError as e:
                    logger.debug("old snapshot unlink failed: %s", e)
        self.snapshot_seq = self.applied_seq

    def _compact(self) -> None:
        os.fsync(self._write_f.fileno())
        self._last_fsync = time.monotonic()
        self._write_snapshot()
        for _epoch, old in self._segments():
            if _epoch < self.epoch:
                try:
                    os.unlink(os.path.join(self.dir, old))
                except OSError as e:
                    logger.debug("sealed segment unlink failed: %s", e)
        self._bytes_since_snap = 0

    # ---------------------------------------------------------------- reads
    def scan(self, table: str):
        return iter(list(self._tables.get(table, {}).items()))

    def journal_stats(self) -> dict:
        return {
            "epoch": self.epoch,
            "applied_seq": self.applied_seq,
            "snapshot_seq": self.snapshot_seq,
            "records_written": self.records_written,
            "lag_bytes": self.lag_bytes(),
            "role": "leader" if self._write_f is not None else "follower",
        }

    def lag_bytes(self) -> int:
        """Follower: bytes appended to the journal but not yet replayed
        here.  Leader: always 0 (it applies as it writes)."""
        if self._write_f is not None:
            return 0
        lag = 0
        for epoch, name in self._segments():
            try:
                size = os.path.getsize(os.path.join(self.dir, name))
            except OSError:
                continue
            if epoch < self.epoch:
                continue
            lag += max(0, size - self._consumed.get(name, 0))
        return lag

    def close(self) -> None:
        if self._write_f is not None:
            try:
                self._write_f.flush()
                os.fsync(self._write_f.fileno())
                self._write_f.close()
            except OSError as e:
                logger.debug("journal close failed: %s", e)
            self._write_f = None
        if self._read_f is not None:
            try:
                self._read_f.close()
            except OSError:
                pass
            self._read_f = None


def make_store_client(path: Optional[str]) -> StoreClient:
    return SqliteStoreClient(path) if path else InMemoryStoreClient()
