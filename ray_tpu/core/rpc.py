"""Asyncio RPC substrate for the control/data plane.

Role-equivalent of the reference's gRPC wrappers + retryable clients + chaos
injection (Ray ``src/ray/rpc/grpc_server.h``, ``rpc/retryable_grpc_client.h``,
``rpc/rpc_chaos.h``).  We deliberately use a lean length-prefixed pickle
protocol over TCP instead of gRPC: every system process runs a single asyncio
event loop (the analog of the reference's one-``instrumented_io_context``-per-
process discipline), and the hot paths (lease grant, task push) are one
round-trip with zero protobuf marshalling overhead.

Wire format (protocol v2): [8-byte little-endian length][body] where body is

  [1B 0xB2][4B header_len][4B nbufs][nbufs x 8B buf_len][header][buf0][buf1]...

``header`` is the frame tuple pickled with protocol 5 and a
``buffer_callback`` — every ``pickle.PickleBuffer`` (and buffer-protocol
object like a numpy array) inside the payload rides *out of band* as a raw
segment after the header instead of being copied into the pickle stream.
The write path keeps frames as segment lists flushed with ``writelines``,
so a large task-arg / object payload is never copied into an intermediate
bytes object between serialization and the transport.  A body starting
with 0xB3 is a batch container: [4B count] then ``count`` pre-encoded
sub-frames, each [8B sub_len][sub_body] — sub-frames are encoded once at
queue time (exact byte accounting) and never re-pickled at flush.
Handshake frames (``__hello__``/``__goodbye__``) are always sent as a
classic protocol-1 body (a plain pickle, first byte 0x80) so ANY peer
version can parse the negotiation and fail with RpcVersionError instead
of a frame-corruption crash.

  request frame :  (msg_id, method, payload)        msg_id > 0
  oneway frame  :  (0, method, payload)
  reply frame   :  (-msg_id, kind, payload)         kind in ('R', 'E')

Fault injection: set config ``testing_rpc_failure`` to
``"method:p_req:p_resp,…"`` (or ``*`` for all methods) to randomly fail
requests before send / replies after receive — the analog of
``RAY_testing_rpc_failure`` (Ray ``src/ray/rpc/rpc_chaos.h:24-44``).
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import random
import socket
import threading
import time
import traceback
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from .config import GlobalConfig
from ..util import debug_lanes

logger = logging.getLogger(__name__)

Address = str  # "host:port"

# ---------------------------------------------------------------- versioning
# Cross-version story (the reference gets this from protobuf field
# numbering + gRPC service evolution; a pickle-frame protocol needs an
# explicit contract):
#
# * Frames are (msg_id, method, payload) tuples — adding a NEW method is
#   always compatible (unknown methods error per-call, not per-connection),
#   and payload dicts grow by adding keys that handlers .get() with
#   defaults.  Those two rules cover same-version evolution.
# * Incompatible changes bump PROTOCOL_VERSION; MIN_COMPAT_VERSION is the
#   oldest peer still speakable.  Each client announces its version in a
#   pipelined ``__hello__`` oneway frame (zero added round-trips); a server
#   outside the compat window answers ``__goodbye__`` with its own range
#   and closes, so a mixed-version cluster fails fast with a clear error
#   instead of corrupting frames.
#
# v2 (out-of-band buffer-table bodies, see module docstring) is not
# parseable by v1 peers, and hellos are pipelined ahead of real frames —
# so MIN_COMPAT_VERSION moves with it.  The handshake itself stays in the
# v1 body format forever (see _encode_frame_v1), which is what turns a
# mixed-version pairing into a clean RpcVersionError on both sides.
#
# v3: TaskSpec wire tuple grew ``pipeline_depth`` (appended).  The tuple
# __setstate__ is exact-arity, so a v2 peer would fail at unpickle, not
# at handshake — hence the bump; nothing else changed, so the compat
# floor moves with it.
PROTOCOL_VERSION = 3
MIN_COMPAT_VERSION = 3

# Sentinel timeout meaning "no per-call timer": the call completes when the
# reply arrives or the connection dies (read-loop failure fails the future).
# Any finite timeout a caller passes is enforced with a real timer.
UNBOUNDED = float("inf")


class RpcError(Exception):
    pass


class RpcTimeoutError(RpcError):
    """Per-call deadline expired (connection may be healthy)."""


class RpcConnectionError(RpcError):
    """Transport-level failure; safe to retry idempotent calls."""


class RpcVersionError(RpcError):
    """Peer's protocol version is outside our compatibility window."""


class NotLeaderError(RpcError):
    """The peer is a control-plane STANDBY (or a freshly fenced stale
    leader): the request must be retried against the current leader.
    ``RetryableRpcClient`` treats this like a transport failure — drop
    the connection, re-resolve the leader endpoint, back off, retry —
    so callers never see it for idempotent calls."""

    def __init__(self, leader_hint=None):
        super().__init__(f"peer is not the control-plane leader "
                         f"(current: {leader_hint or 'unknown'})")
        self.leader_hint = leader_hint

    def __reduce__(self):
        # Crosses the wire inside an error reply; replay __init__ with
        # the hint, not the joined message (same trap as RpcRemoteError).
        return (NotLeaderError, (self.leader_hint,))


class RpcRemoteError(RpcError):
    """The remote handler raised; carries the remote traceback string."""

    def __init__(self, method, exc, tb):
        super().__init__(f"remote error in {method}: {exc}\n{tb}")
        self.method = method
        self.cause = exc
        self.remote_traceback = tb

    def __reduce__(self):
        # Exception.__reduce__ would replay __init__ with the joined
        # message as the ONLY argument, so an RpcRemoteError crossing a
        # second process boundary (e.g. inside a task-error reply) failed
        # to unpickle and masked the real error as a TypeError + timeout.
        return (
            RpcRemoteError,
            (self.method, str(self.cause), self.remote_traceback),
        )


class _ChaosInjector:
    """Parses the testing_rpc_failure spec once; rolls dice per call."""

    def __init__(self):
        self._rules: Dict[str, Tuple[float, float]] = {}
        spec = GlobalConfig.testing_rpc_failure
        if spec:
            for entry in spec.split(","):
                parts = entry.strip().split(":")
                if len(parts) == 3:
                    self._rules[parts[0]] = (float(parts[1]), float(parts[2]))

    def enabled(self) -> bool:
        return bool(self._rules)

    def _probs(self, method) -> Tuple[float, float]:
        return self._rules.get(method) or self._rules.get("*") or (0.0, 0.0)

    def fail_request(self, method) -> bool:
        return random.random() < self._probs(method)[0]

    def fail_response(self, method) -> bool:
        return random.random() < self._probs(method)[1]


class _DelayInjector:
    """Network-latency chaos — the transport-level analog of the
    reference's tc-qdisc delay experiments
    (``python/ray/tests/chaos/chaos_network_delay.yaml``): outgoing calls
    sleep delay±jitter before hitting the wire, per the
    ``testing_network_delay`` spec ('method:prob:delay_ms[:jitter_ms]')."""

    def __init__(self):
        self._rules: Dict[str, Tuple[float, float, float]] = {}
        spec = GlobalConfig.testing_network_delay
        if spec:
            for entry in spec.split(","):
                parts = entry.strip().split(":")
                if len(parts) >= 3:
                    self._rules[parts[0]] = (
                        float(parts[1]),
                        float(parts[2]) / 1e3,
                        (float(parts[3]) / 1e3 if len(parts) > 3 else 0.0),
                    )

    def enabled(self) -> bool:
        return bool(self._rules)

    def delay_s(self, method) -> float:
        rule = self._rules.get(method) or self._rules.get("*")
        if rule is None or random.random() >= rule[0]:
            return 0.0
        prob, delay, jitter = rule
        return max(0.0, delay + random.uniform(-jitter, jitter))


def parse_address(addr: Address) -> Tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


def find_free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


_LEN = 8
_MAGIC_FRAME = 0xB2  # v2 single frame with out-of-band buffer table
_MAGIC_BATCH = 0xB3  # v2 batch container of pre-encoded sub-frames
_PICKLE_PROTO = 0x80  # classic pickle body (handshake frames, v1 peers)

# Data-plane frame accounting, published as ray_tpu_* counters by the
# flight recorder's observability flush (this module stays import-leaf).
FRAME_STATS = {
    "oob_frames": 0,     # frames carrying >=1 out-of-band buffer
    "oob_bytes": 0,      # payload bytes that skipped the pickle stream
    "batch_frames": 0,   # batch containers written
    "batched_calls": 0,  # calls multiplexed into batch containers
}

# Counters update from the loop, server lanes, AND direct-submitting user
# threads; dict += is read-modify-write under the GIL, so exactness (pinned
# by tests/test_rpc.py) needs a lock.  Only oob/batch frames pay it — the
# plain-frame hot path never touches FRAME_STATS.
_STATS_LOCK = threading.Lock()

# --------------------------------------------------------------- frame codec
# Two byte-identical codecs: the C fast path (src/native/rtpu_frame.cc via
# ray_tpu.core.native.FrameCodec — meta pack, buf-len table, and body parse
# in one call each) and the pure-Python reference, which is the
# always-available fallback when the toolchain/library is missing or
# ``rpc_native_codec`` is off.  Parity is pinned by tests/test_frame_codec.py.
_codec = None
_codec_resolved = False

# Adaptive dispatch threshold: the C codec costs one ctypes FFI round-trip
# (~1.5µs), which LOSES to CPython's C-backed bytes ops on small frames
# (measured ~1.4–1.8x slower for header-only frames) and only wins once the
# out-of-band buffer table is big enough that the Python codec loops in the
# interpreter (measured crossover ≈4 buffers; +9–10% at 8).  Frames with
# fewer oob buffers than this take the Python codec even when the native
# library is loaded.  Tests pin C-path parity for ALL shapes by setting it
# to 0; batch containers route to C only at 0 too (per-sub FFI never pays
# for the small call frames batching selects for).
_C_MIN_BUFS = 4


def _resolve_codec():
    global _codec, _codec_resolved
    if not _codec_resolved:
        _codec_resolved = True
        if GlobalConfig.rpc_native_codec:
            try:
                from . import native as _native

                _codec = _native.frame_codec()
            except Exception:  # noqa: BLE001 — any native failure ⇒ Python codec
                _codec = None
    return _codec


def _reset_codec_for_tests():
    """Force re-resolution (tests flip rpc_native_codec / RAY_TPU_NATIVE_LIB)."""
    global _codec, _codec_resolved
    _codec = None
    _codec_resolved = False


def _encode_frame(frame) -> Tuple[list, int]:
    """Encode one frame as ``(segments, nbytes)``.

    ``segments[0]`` is one bytearray holding the outer length prefix, the
    fixed meta, the buffer-length table, and the pickle header; each
    out-of-band buffer follows as its own memoryview segment, referencing
    the caller's memory — flushed via ``writelines`` without ever being
    copied into an intermediate frame buffer.  ``nbytes`` is the total
    wire size including the 8-byte length prefix (exact, not estimated —
    the batch flusher budgets with it)."""
    codec = _codec if _codec_resolved else _resolve_codec()
    if codec is None:
        return _encode_frame_py(frame)
    bufs: list = []
    header = pickle.dumps(frame, protocol=5, buffer_callback=bufs.append)
    if not bufs:
        if _C_MIN_BUFS > 0:
            return _encode_plain_py(header)
        meta = codec.pack(header, ())
        return [meta], len(meta)
    views = [b.raw().cast("B") for b in bufs]
    if len(views) < _C_MIN_BUFS or len(views) > codec.MAX_BUFS:
        return _encode_oob_py(header, views)
    lens = [v.nbytes for v in views]
    meta = codec.pack(header, lens)
    total = sum(lens)
    with _STATS_LOCK:
        FRAME_STATS["oob_frames"] += 1
        FRAME_STATS["oob_bytes"] += total
    segments = [meta]
    segments.extend(views)
    return segments, len(meta) + total


def _encode_frame_py(frame) -> Tuple[list, int]:
    """Pure-Python codec; same contract (and bytes) as ``_encode_frame``."""
    bufs: list = []
    header = pickle.dumps(frame, protocol=5, buffer_callback=bufs.append)
    if not bufs:
        return _encode_plain_py(header)
    views = [b.raw().cast("B") for b in bufs]
    return _encode_oob_py(header, views)


def _encode_plain_py(header) -> Tuple[list, int]:
    meta = bytearray(_LEN + 9)
    body_len = 9 + len(header)
    meta[0:_LEN] = body_len.to_bytes(_LEN, "little")
    meta[_LEN] = _MAGIC_FRAME
    meta[_LEN + 1 : _LEN + 5] = len(header).to_bytes(4, "little")
    meta += header
    return [meta], _LEN + body_len


def _encode_oob_py(header, views) -> Tuple[list, int]:
    nbufs = len(views)
    meta = bytearray(_LEN + 9 + 8 * nbufs)
    total = 0
    off = _LEN + 9
    for v in views:
        n = v.nbytes
        meta[off : off + 8] = n.to_bytes(8, "little")
        off += 8
        total += n
    body_len = 9 + 8 * nbufs + len(header) + total
    meta[0:_LEN] = body_len.to_bytes(_LEN, "little")
    meta[_LEN] = _MAGIC_FRAME
    meta[_LEN + 1 : _LEN + 5] = len(header).to_bytes(4, "little")
    meta[_LEN + 5 : _LEN + 9] = nbufs.to_bytes(4, "little")
    meta += header
    with _STATS_LOCK:
        FRAME_STATS["oob_frames"] += 1
        FRAME_STATS["oob_bytes"] += total
    segments = [meta]
    segments.extend(views)
    return segments, _LEN + body_len


def _encode_frame_v1(frame) -> bytes:
    """Classic body: [8B len][pickle(frame)].  Used ONLY for the
    version handshake — any peer version can parse it."""
    data = pickle.dumps(frame, protocol=5)
    return len(data).to_bytes(_LEN, "little") + data


def _decode_frame_v2(mv: memoryview):
    hlen = int.from_bytes(mv[1:5], "little")
    nbufs = int.from_bytes(mv[5:9], "little")
    off = 9 + 8 * nbufs
    header = mv[off : off + hlen]
    off += hlen
    buffers = []
    for i in range(nbufs):
        n = int.from_bytes(mv[9 + 8 * i : 17 + 8 * i], "little")
        buffers.append(mv[off : off + n])
        off += n
    # Out-of-band buffers load as memoryview slices of the read buffer —
    # zero receive-side copies; consumers deserialize straight from them.
    return pickle.loads(header, buffers=buffers)


def _decode_body(data: bytes):
    codec = _codec if _codec_resolved else _resolve_codec()
    # The C parser indexes raw bytes; anything exotic goes the Python way.
    if codec is None or type(data) is not bytes:
        return _decode_body_py(data)
    tag = data[0]
    if tag == _MAGIC_FRAME:
        # Adaptive: small buffer tables parse faster in Python (the FFI
        # round-trip costs more than the loop it saves) — peek nbufs.
        if int.from_bytes(data[5:9], "little") < _C_MIN_BUFS:
            return _decode_frame_v2(memoryview(data))
        return _decode_frame_c(data, 0, len(data), codec)
    if tag == _MAGIC_BATCH:
        if _C_MIN_BUFS > 0:
            # Batches multiplex small call frames; per-sub FFI never pays.
            return _decode_body_py(data)
        n, table = codec.unpack_batch(data)
        if n < 0:
            if n == -2:  # more sub-frames than the scratch table holds
                return _decode_body_py(data)
            raise RpcError("corrupt batch frame")
        # Copy offsets out BEFORE recursing: _decode_frame_c reuses the
        # same thread-local scratch table.
        subs = [(table[2 * i], table[2 * i + 1]) for i in range(n)]
        frames = [_decode_frame_c(data, off, ln, codec) for off, ln in subs]
        return (0, "__batch__", frames)
    if tag == _PICKLE_PROTO:
        return pickle.loads(data)
    raise RpcError(f"corrupt frame: unknown body tag {tag:#04x}")


def _decode_frame_c(data: bytes, off: int, length: int, codec):
    n, table = codec.unpack(data, off, length)
    if n < 0:
        if n == -2:  # more oob buffers than the scratch table holds
            return _decode_frame_v2(memoryview(data)[off : off + length])
        raise RpcError("corrupt v2 frame")
    mv = memoryview(data)
    header = mv[table[0] : table[0] + table[1]]
    buffers = []
    for i in range(n):
        o = table[2 + 2 * i]
        ln = table[3 + 2 * i]
        buffers.append(mv[o : o + ln])
    # Same zero-copy property as _decode_frame_v2: oob buffers are
    # memoryview slices of the read buffer.
    return pickle.loads(header, buffers=buffers)


def _decode_body_py(data):
    tag = data[0]
    if tag == _MAGIC_FRAME:
        return _decode_frame_v2(memoryview(data))
    if tag == _MAGIC_BATCH:
        mv = memoryview(data)
        count = int.from_bytes(mv[1:5], "little")
        frames = []
        off = 5
        for _ in range(count):
            sublen = int.from_bytes(mv[off : off + _LEN], "little")
            off += _LEN
            frames.append(_decode_frame_v2(mv[off : off + sublen]))
            off += sublen
        return (0, "__batch__", frames)
    if tag == _PICKLE_PROTO:
        # Handshake frames and v1 peers: a plain pickled tuple.
        return pickle.loads(data)
    raise RpcError(f"corrupt frame: unknown body tag {tag:#04x}")


async def _read_frame(reader: asyncio.StreamReader):
    hdr = await reader.readexactly(_LEN)
    length = int.from_bytes(hdr, "little")
    data = await reader.readexactly(length)
    return _decode_body(data)


class ForwardToPrimary:
    """Sentinel a *lane-safe* sync handler returns to punt one call to the
    server's primary event loop.

    Lane-safe handlers (named in the handler object's ``LANE_SAFE_METHODS``
    frozenset) run directly on whichever lane owns the connection.  When a
    particular call needs loop-affine state (an unresolved object, a
    reconstruction, a mutation of primary-loop structures), the handler
    returns ``ForwardToPrimary(coro_factory)``: the lane schedules
    ``coro_factory()`` on the primary loop, awaits the result without
    blocking the lane, and sends the reply from the lane (the connection's
    transport never leaves its owning loop)."""

    __slots__ = ("factory",)

    def __init__(self, factory: Callable):
        self.factory = factory


class _LaneStats:
    """Per-lane dispatch accounting.  Written by the owning lane thread
    (plain int/float ops — no locks on the per-frame path); read by the
    metrics flush on another thread, which tolerates torn windows."""

    __slots__ = (
        "index", "connections", "frames_total", "forwarded_total",
        "inflight", "wait_sum", "wait_count", "wait_max",
    )

    def __init__(self, index: int):
        self.index = index
        self.connections = 0
        self.frames_total = 0
        self.forwarded_total = 0
        self.inflight = 0      # frames read whose handler hasn't finished
        self.wait_sum = 0.0    # read-complete -> handler-start latency
        self.wait_count = 0
        self.wait_max = 0.0

    def note_wait(self, wait_s: float):
        self.wait_sum += wait_s
        self.wait_count += 1
        if wait_s > self.wait_max:
            self.wait_max = wait_s

    def snapshot(self) -> dict:
        return {
            "lane": self.index,
            "connections": self.connections,
            "frames_total": self.frames_total,
            "forwarded_total": self.forwarded_total,
            "inflight": self.inflight,
            "dispatch_wait_sum_s": self.wait_sum,
            "dispatch_wait_count": self.wait_count,
            "dispatch_wait_max_s": self.wait_max,
        }


class _RpcLane:
    """One extra service lane: a daemon thread running its own event loop.
    Connections are pinned to a lane at accept time, so per-connection
    frame ordering is exactly the single-loop ordering."""

    def __init__(self, index: int):
        self.index = index
        self.stats = _LaneStats(index)
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self._run, daemon=True, name=f"rpc-lane-{index}"
        )

    def _run(self):
        asyncio.set_event_loop(self.loop)
        lanes_on = debug_lanes.debug_lanes_enabled()
        if lanes_on:
            # Lane-affinity checker scope: only registered lane threads
            # are held to the shard-lock contract (RTL007's dynamic twin).
            debug_lanes.register_lane_thread()
        try:
            self.loop.run_forever()
        finally:
            if lanes_on:
                debug_lanes.deregister_lane_thread()
            try:
                self.loop.close()
            except Exception as e:
                logger.debug("lane %d loop close failed: %s", self.index, e)

    def start(self):
        self.thread.start()

    def stop(self, timeout: float = 2.0):
        def _halt():
            # Cancel in-flight dispatches, then stop on the NEXT pass so
            # the cancellations get one loop iteration to unwind.
            for task in asyncio.all_tasks(self.loop):
                task.cancel()
            self.loop.call_soon(self.loop.stop)

        try:
            self.loop.call_soon_threadsafe(_halt)
        except RuntimeError:
            pass  # loop already stopped/closed
        self.thread.join(timeout)


def resolve_service_lanes(role: str = "") -> int:
    """Lane count for an RPC service.  ``rpc_service_lanes`` > 0 wins;
    0 = auto: min(4, cpu count) for the many-client servers (control
    plane, node agent, driver owner service), 1 for worker executors —
    a worker's hot path is ordered task pushes from one or two peers,
    which gain nothing from cross-lane forwarding."""
    n = GlobalConfig.rpc_service_lanes
    if n > 0:
        return int(n)
    if role == "worker":
        return 1
    return max(1, min(4, os.cpu_count() or 1))


class RpcServer:
    """Serves a handler object: each RPC method ``m`` dispatches to
    ``handler.handle_m(payload, ctx)`` (async or sync).  ``ctx`` exposes the
    peer connection for server-push (pubsub).

    Lanes (``lanes > 1``): the service runs N event loops — the primary
    (lane 0, the loop ``start()`` ran on) plus N-1 ``_RpcLane`` threads.
    Each accepted connection is pinned to the least-loaded lane for its
    lifetime, so per-connection ordering is preserved.  Handler methods
    named in ``handler.LANE_SAFE_METHODS`` execute directly on the lane
    (they must be sync and touch only thread-safe state, returning
    ``ForwardToPrimary`` for calls they can't serve); every other method
    transparently forwards to the primary loop, preserving the
    single-loop threading model for stateful handlers."""

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0,
                 lanes: int = 1):
        self._handler = handler
        self._host = host
        self._port = port
        self.lanes = max(1, int(lanes))
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        self._primary_loop: Optional[asyncio.AbstractEventLoop] = None
        self._lane_workers: List[_RpcLane] = []
        self._lane0_stats = _LaneStats(0)
        self._accept_task: Optional[asyncio.Task] = None
        self._lsock: Optional[socket.socket] = None
        self._lane_safe: frozenset = frozenset(
            getattr(handler, "LANE_SAFE_METHODS", ())
        )
        # Per-handler latency stats (analog of event_stats.h).
        self.stats: Dict[str, list] = {}

    @property
    def address(self) -> Address:
        return f"{self._host}:{self._port}"

    def lane_stats(self) -> List[dict]:
        """Per-lane dispatch/queue accounting (lane 0 = primary loop)."""
        out = [self._lane0_stats.snapshot()]
        out.extend(lane.stats.snapshot() for lane in self._lane_workers)
        return out

    async def start(self):
        self._primary_loop = asyncio.get_running_loop()
        if self.lanes <= 1:
            self._server = await asyncio.start_server(
                self._on_connection, self._host, self._port
            )
            self._port = self._server.sockets[0].getsockname()[1]
            return self.address
        lsock = socket.socket()
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((self._host, self._port))
        lsock.listen(512)
        lsock.setblocking(False)
        self._lsock = lsock
        self._port = lsock.getsockname()[1]
        for i in range(1, self.lanes):
            lane = _RpcLane(i)
            lane.start()
            self._lane_workers.append(lane)
        self._accept_task = self._primary_loop.create_task(self._accept_loop())
        return self.address

    async def stop(self):
        # Close live connections first: in py3.12 Server.wait_closed() blocks
        # until every connection handler returns.
        if self._accept_task is not None:
            self._accept_task.cancel()
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError as e:
                logger.debug("listen socket close failed: %s", e)
        for conn in list(self._conns):
            conn.close()
        if self._server:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=1.0)
            except Exception as e:
                logger.debug("server wait_closed failed: %s", e)
        for lane in self._lane_workers:
            lane.stop()

    # ------------------------------------------------------------ lane accept
    def _pick_lane(self) -> Optional[_RpcLane]:
        """Least-connections pin, primary loop (lane 0) included; ties go
        to the lowest lane so light load stays on the primary."""
        best = None  # None = primary
        best_count = self._lane0_stats.connections
        for lane in self._lane_workers:
            if lane.stats.connections < best_count:
                best = lane
                best_count = lane.stats.connections
        return best

    async def _accept_loop(self):
        loop = asyncio.get_running_loop()
        while True:
            try:
                sock, _addr = await loop.sock_accept(self._lsock)
            except asyncio.CancelledError:
                raise
            except OSError:
                break  # listen socket closed (stop())
            sock.setblocking(False)
            lane = self._pick_lane()
            if lane is None:
                loop.create_task(self._adopt(sock, None))
            else:
                asyncio.run_coroutine_threadsafe(
                    self._adopt(sock, lane), lane.loop
                )

    async def _adopt(self, sock, lane: Optional[_RpcLane]):
        """Wrap an accepted socket in streams ON THE OWNING LANE's loop and
        run the standard connection handler there."""
        loop = asyncio.get_running_loop()
        reader = asyncio.StreamReader(loop=loop)
        proto = asyncio.StreamReaderProtocol(reader, loop=loop)
        try:
            transport, _ = await loop.connect_accepted_socket(
                lambda: proto, sock
            )
        except Exception as e:  # noqa: BLE001 — peer may already be gone
            logger.debug("accepted-socket adoption failed: %s", e)
            try:
                sock.close()
            except OSError:
                pass
            return
        writer = asyncio.StreamWriter(transport, proto, reader, loop)
        await self._on_connection(reader, writer, lane)

    async def _on_connection(self, reader, writer, lane: Optional[_RpcLane] = None):
        try:
            writer.transport.set_write_buffer_limits(high=4 << 20)
        except Exception:  # raylint: waive[RTL003] write-buffer limit is a transport nicety
            pass
        conn = ServerConnection(reader, writer, cross_thread=self.lanes > 1)
        self._conns.add(conn)
        loop = asyncio.get_running_loop()
        stats = lane.stats if lane is not None else self._lane0_stats
        stats.connections += 1
        # Per-connection handler cache: (fn, is_coroutine_fn).  Sync handlers
        # dispatch inline — no task allocation, reply coalesced into the
        # connection's write buffer.
        hcache: Dict[str, tuple] = {}
        try:
            while True:
                await conn.wait_writable()
                try:
                    frame = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                msg_id, method, payload = frame
                if method == "__batch__" and msg_id == 0:
                    # Multiplexed frame: each sub-call dispatches and
                    # replies independently, exactly as if sent alone.
                    if lane is None:
                        for sub in payload:
                            self._process_frame(conn, loop, hcache, *sub)
                    else:
                        for sub in payload:
                            self._process_frame_lane(conn, lane, hcache, *sub)
                    continue
                if lane is None:
                    self._process_frame(conn, loop, hcache, msg_id, method, payload)
                else:
                    self._process_frame_lane(
                        conn, lane, hcache, msg_id, method, payload
                    )
        finally:
            self._conns.discard(conn)
            stats.connections -= 1
            conn.close()
            if hasattr(self._handler, "on_connection_closed"):
                try:
                    if lane is None:
                        res = self._handler.on_connection_closed(conn)
                        if asyncio.iscoroutine(res):
                            await res
                    else:
                        # Teardown hooks touch primary-loop state (pubsub
                        # tables, lease sweeps): run them there.
                        await asyncio.wrap_future(
                            asyncio.run_coroutine_threadsafe(
                                self._closed_on_primary(conn),
                                self._primary_loop,
                            )
                        )
                except Exception:
                    logger.exception("on_connection_closed failed")

    async def _closed_on_primary(self, conn):
        res = self._handler.on_connection_closed(conn)
        if asyncio.iscoroutine(res):
            await res

    def _handshake(self, conn, loop, payload):
        try:
            # Positional prefix only: future hellos may APPEND fields
            # (the evolution rule applies to the handshake too), and a
            # frame we can't parse at all is treated as incompatible —
            # fail fast with a versioned goodbye, not a torn socket.
            ver, peer_min = payload[0], payload[1]
        except Exception:  # noqa: BLE001
            ver, peer_min = -1, PROTOCOL_VERSION + 1
        if ver < MIN_COMPAT_VERSION or peer_min > PROTOCOL_VERSION:
            # Legacy body: the refused peer may predate v2 framing and
            # must still be able to parse the goodbye.
            conn.send_nowait(
                (0, "__goodbye__",
                 (PROTOCOL_VERSION, MIN_COMPAT_VERSION)),
                legacy=True,
            )
            # Close AFTER the goodbye flushes (both are call_soon'd on
            # this loop, in order).
            loop.call_soon(conn.close)
        else:
            conn.peer_version = ver

    def _process_frame(self, conn, loop, hcache, msg_id, method, payload):
        if method == "__hello__" and msg_id == 0:
            self._handshake(conn, loop, payload)
            return
        self._lane0_stats.frames_total += 1
        entry = hcache.get(method)
        if entry is None:
            fn = getattr(self._handler, "handle_" + method, None)
            entry = (fn, fn is None or asyncio.iscoroutinefunction(fn))
            hcache[method] = entry
        fn, needs_task = entry
        if needs_task:
            # Coroutine handlers run as independent tasks so one slow
            # call never blocks the connection (actor ordering is
            # enforced above this layer by sequence numbers).
            loop.create_task(
                self._dispatch(conn, msg_id, method, payload, fn)
            )
            return
        start = time.perf_counter()
        try:
            result = fn(payload, conn)
            if type(result) is ForwardToPrimary:
                # On the primary already: just run the slow-path coroutine.
                loop.create_task(
                    self._finish_async(conn, msg_id, method, result.factory())
                )
            elif asyncio.iscoroutine(result):
                # Sync wrapper returning a coroutine: await in a task.
                loop.create_task(
                    self._finish_async(conn, msg_id, method, result)
                )
            elif msg_id > 0:
                conn.send_nowait((-msg_id, "R", result))
        except Exception as e:  # noqa: BLE001
            if msg_id > 0:
                try:
                    conn.send_nowait(
                        (-msg_id, "E", (e, traceback.format_exc()))
                    )
                except Exception:
                    # e.g. unpicklable exception: report, keep the
                    # connection (only this call errors out).
                    logger.exception(
                        "failed to send error reply for %s", method
                    )
            else:
                logger.exception("oneway handler %s failed", method)
        s = self.stats.get(method)
        if s is None:
            s = self.stats[method] = [0, 0.0]
        s[0] += 1
        s[1] += time.perf_counter() - start

    # -------------------------------------------------------- lane dispatch
    def _process_frame_lane(self, conn, lane, hcache, msg_id, method, payload):
        """Frame dispatch on a lane thread.  Lane-safe sync handlers run
        inline (reply coalesced into the lane connection's write buffer);
        everything else forwards to the primary loop, with the reply sent
        from the lane so the transport never crosses threads."""
        loop = lane.loop
        if method == "__hello__" and msg_id == 0:
            self._handshake(conn, loop, payload)
            return
        stats = lane.stats
        stats.frames_total += 1
        entry = hcache.get(method)
        if entry is None:
            fn = getattr(self._handler, "handle_" + method, None)
            lane_ok = (
                method in self._lane_safe
                and fn is not None
                and not asyncio.iscoroutinefunction(fn)
            )
            entry = (fn, lane_ok)
            hcache[method] = entry
        fn, lane_ok = entry
        if not lane_ok:
            stats.forwarded_total += 1
            loop.create_task(
                self._forward_call(conn, msg_id, method, payload, fn, lane,
                                   time.perf_counter())
            )
            return
        stats.note_wait(0.0)
        try:
            result = fn(payload, conn)
            if type(result) is ForwardToPrimary:
                stats.forwarded_total += 1
                loop.create_task(
                    self._forward_factory(conn, msg_id, method,
                                          result.factory, lane)
                )
            elif asyncio.iscoroutine(result):
                # A lane-safe handler opting into lane-local async work.
                loop.create_task(
                    self._finish_async(conn, msg_id, method, result)
                )
            elif msg_id > 0:
                conn.send_nowait((-msg_id, "R", result))
        except Exception as e:  # noqa: BLE001
            if msg_id > 0:
                try:
                    conn.send_nowait((-msg_id, "E", (e, traceback.format_exc())))
                except Exception:
                    logger.exception("failed to send error reply for %s", method)
            else:
                logger.exception("oneway lane handler %s failed", method)

    async def _forward_call(self, conn, msg_id, method, payload, fn, lane, t0):
        """Run a non-lane-safe handler on the primary loop; reply from the
        lane.  ``wrap_future`` bridges the cross-loop completion without
        blocking the lane's read loop."""
        stats = lane.stats
        stats.inflight += 1
        try:
            cfut = asyncio.run_coroutine_threadsafe(
                self._run_on_primary(method, payload, conn, fn, stats, t0),
                self._primary_loop,
            )
            result = await asyncio.wrap_future(cfut)
            if msg_id > 0:
                await conn.send((-msg_id, "R", result))
        except Exception as e:  # noqa: BLE001 — serialize any handler error
            if msg_id > 0:
                try:
                    await conn.send((-msg_id, "E", (e, traceback.format_exc())))
                except Exception:
                    logger.exception("failed to send error reply for %s", method)
            else:
                logger.exception("oneway handler %s failed", method)
        finally:
            stats.inflight -= 1

    async def _run_on_primary(self, method, payload, conn, fn, stats, t0):
        stats.note_wait(time.perf_counter() - t0)
        if fn is None:
            raise RpcError(f"no handler for method {method!r}")
        result = fn(payload, conn)
        if asyncio.iscoroutine(result):
            result = await result
        return result

    async def _forward_factory(self, conn, msg_id, method, factory, lane):
        """A lane-safe handler punted this call: run its slow-path
        coroutine on the primary loop, reply from the lane."""
        stats = lane.stats
        stats.inflight += 1
        try:
            cfut = asyncio.run_coroutine_threadsafe(
                factory(), self._primary_loop
            )
            result = await asyncio.wrap_future(cfut)
            if msg_id > 0:
                await conn.send((-msg_id, "R", result))
        except Exception as e:  # noqa: BLE001
            if msg_id > 0:
                try:
                    await conn.send((-msg_id, "E", (e, traceback.format_exc())))
                except Exception:
                    logger.exception("failed to send error reply for %s", method)
            else:
                logger.exception("oneway handler %s failed", method)
        finally:
            stats.inflight -= 1

    async def _finish_async(self, conn, msg_id, method, coro):
        try:
            result = await coro
            if msg_id > 0:
                await conn.send((-msg_id, "R", result))
        except Exception as e:  # noqa: BLE001
            if msg_id > 0:
                try:
                    await conn.send((-msg_id, "E", (e, traceback.format_exc())))
                except Exception:
                    logger.exception("failed to send error reply for %s", method)
            else:
                logger.exception("oneway handler %s failed", method)

    async def _dispatch(self, conn, msg_id, method, payload, fn=None):
        start = time.perf_counter()
        try:
            if fn is None:
                fn = getattr(self._handler, "handle_" + method, None)
            if fn is None:
                raise RpcError(f"no handler for method {method!r}")
            result = fn(payload, conn)
            if asyncio.iscoroutine(result):
                result = await result
            if msg_id > 0:
                await conn.send((-msg_id, "R", result))
        except Exception as e:  # noqa: BLE001 - serialize any handler error
            if msg_id > 0:
                try:
                    await conn.send((-msg_id, "E", (e, traceback.format_exc())))
                except Exception:
                    logger.exception("failed to send error reply for %s", method)
            else:
                logger.exception("oneway handler %s failed", method)
        finally:
            self.stats.setdefault(method, [0, 0.0])
            s = self.stats[method]
            s[0] += 1
            s[1] += time.perf_counter() - start


class ServerConnection:
    """Server-side view of a client connection; supports server-push.

    Writes coalesce: frames append to a per-connection buffer flushed once
    per event-loop pass (one syscall for a burst of replies instead of one
    per reply).  Single-threaded event loop ⇒ no lock needed; each frame is
    appended atomically so frames never interleave."""

    def __init__(self, reader, writer, cross_thread: bool = False):
        self._reader = reader
        self._writer = writer
        # Owning event loop: with a multi-lane server the connection's
        # transport lives on ITS lane's loop, while pubsub publishes and
        # forwarded-handler teardown run on the primary — cross-thread
        # sends route through call_soon_threadsafe under a small lock.
        self._loop = asyncio.get_running_loop()
        self._xlock = threading.Lock() if cross_thread else None
        # RAY_TPU_DEBUG_LANES=1: the connection adopts its owning lane
        # thread at construction (we're on its loop right here); _flush
        # asserts it only ever runs there — cross-thread senders must
        # route through call_soon_threadsafe, never call it directly.
        if debug_lanes.debug_lanes_enabled():
            self._lane_tag = debug_lanes.LaneTag(
                "rpc.server_conn", adopt=True
            )
        else:
            self._lane_tag = None
        # Write queue is a SEGMENT LIST (bytes/memoryviews), not a flat
        # bytearray: out-of-band payload buffers ride to writelines
        # untouched instead of being copied into a coalescing buffer.
        self._wsegs: list = []
        self._wbytes = 0
        self._flush_scheduled = False
        self._drain_task: Optional[asyncio.Task] = None
        self.closed = False  # set on teardown; grant paths check liveness
        self.metadata: Dict[str, Any] = {}  # handlers can stash identity here
        self.peer_version = PROTOCOL_VERSION  # pre-handshake default

    def _on_owner_loop(self) -> bool:
        try:
            return asyncio.get_running_loop() is self._loop
        except RuntimeError:
            return False

    def send_nowait(self, frame, legacy: bool = False):
        """Queue a frame; flushed on the next loop pass.  ``legacy`` sends
        the v1 body format — required for ``__goodbye__``, which must be
        parseable by the incompatible peer being refused.  Thread-safe on
        multi-lane servers (callers off the owning loop schedule the flush
        with call_soon_threadsafe)."""
        if legacy:
            segs = [_encode_frame_v1(frame)]
            n = len(segs[0])
        else:
            segs, n = _encode_frame(frame)
        if self._xlock is None:
            self._wsegs.extend(segs)
            self._wbytes += n
            if not self._flush_scheduled:
                self._flush_scheduled = True
                asyncio.get_running_loop().call_soon(self._flush)
            return
        with self._xlock:
            self._wsegs.extend(segs)
            self._wbytes += n
            schedule = not self._flush_scheduled
            if schedule:
                self._flush_scheduled = True
        if schedule:
            if self._on_owner_loop():
                self._loop.call_soon(self._flush)
            else:
                try:
                    self._loop.call_soon_threadsafe(self._flush)
                except RuntimeError:
                    pass  # owning lane already stopped at teardown

    def _flush(self):
        if self._lane_tag is not None:
            debug_lanes.check_mutation(self._lane_tag, "_flush")
        if self._xlock is None:
            self._flush_scheduled = False
            if not self._wsegs:
                return
            if self._drain_task is not None and not self._drain_task.done():
                # Transport backed up by a slow peer: keep frames queued
                # (bounded because the server stops reading this connection —
                # see wait_writable) until the drain completes.
                return
            segs, self._wsegs = self._wsegs, []
            self._wbytes = 0
        else:
            with self._xlock:
                self._flush_scheduled = False
                if not self._wsegs:
                    return
                if self._drain_task is not None and not self._drain_task.done():
                    return
                segs, self._wsegs = self._wsegs, []
                self._wbytes = 0
        try:
            self._writer.writelines(segs)
            if self._writer.transport.get_write_buffer_size() > (4 << 20):
                self._drain_task = asyncio.get_running_loop().create_task(
                    self._await_drain()
                )
        except Exception:  # raylint: waive[RTL003] connection torn down mid-flush
            pass

    async def _await_drain(self):
        try:
            await self._writer.drain()
        except Exception:  # raylint: waive[RTL003] peer gone; read side closes us
            pass
        if self._wsegs and not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush)

    async def wait_writable(self):
        """Backpressure hook for the server's read loop: while this
        connection's write side is overloaded, stop dispatching more of
        its requests (sync-handler replies via send_nowait never await, so
        only pausing intake bounds a slow reader's buffer)."""
        task = self._drain_task
        if task is not None and not task.done():
            try:
                await asyncio.shield(task)
            except Exception:  # raylint: waive[RTL003] drain outcome irrelevant once pausing ends
                pass

    async def send(self, frame):
        self.send_nowait(frame)
        # Flow control: only await the transport when it has a real backlog
        # (large replies / slow peer), not on every small frame.  Count both
        # the not-yet-flushed coalescing buffer and the transport's own.
        try:
            if (
                self._wbytes
                + self._writer.transport.get_write_buffer_size()
            ) > (4 << 20):
                self._flush()
                await self._writer.drain()
        except Exception as e:
            logger.debug("backpressure drain failed: %s", e)

    async def push(self, method: str, payload):
        """One-way server→client message (pubsub delivery).  From off the
        owning loop (primary-loop publish to a lane-pinned subscriber) the
        frame is queued thread-safely without awaiting transport drain."""
        if self._on_owner_loop():
            await self.send((0, method, payload))
        else:
            self.send_nowait((0, method, payload))

    def close(self):
        self.closed = True
        if self._on_owner_loop():
            try:
                self._writer.close()
            except Exception as e:
                logger.debug("server conn close failed: %s", e)
        else:
            # Lane-owned transport: close must run on its loop.
            def _do_close():
                try:
                    self._writer.close()
                except Exception as e:
                    logger.debug("server conn close failed: %s", e)

            try:
                self._loop.call_soon_threadsafe(_do_close)
            except RuntimeError:
                pass  # lane loop already stopped

    @property
    def peername(self):
        try:
            return self._writer.get_extra_info("peername")
        except Exception:
            return None


class _WheelEntry:
    __slots__ = ("cb", "args", "cancelled")


class TimeoutWheel:
    """Coarse shared deadline timer: one asyncio timer services every
    in-flight RPC deadline on a loop.

    Each ``call()`` used to cost two timer-heap operations
    (``asyncio.wait_for`` arms a ``call_later`` and cancels it on reply).
    The wheel replaces them with a dict append and a flag flip: deadlines
    round up into ``granularity_s`` buckets (default 50 ms via
    ``rpc_timeout_wheel_ms``) and a single ``call_at`` timer — re-armed to
    the earliest live bucket — sweeps expired entries.  A deadline
    registered at delay ``d`` fires in ``(d, d + granularity]``: never
    early, at most one bucket late.  RPC timeouts are liveness bounds
    measured in seconds, so 50 ms of slack is free; cancellation is lazy
    (a flag flip under the lock — no heap surgery), and ``add`` is safe
    from any thread (direct-submit arms deadlines off-loop)."""

    def __init__(self, loop, granularity_s: float):
        self._loop = loop
        self._g = granularity_s
        self._lock = threading.Lock()
        self._buckets: Dict[int, list] = {}
        self._timer = None          # loop-thread only
        self._armed_idx = None      # under _lock: bucket the timer targets
        self.live = 0               # under _lock: non-cancelled entries

    def add(self, delay_s: float, cb, *args) -> _WheelEntry:
        e = _WheelEntry()
        e.cb = cb
        e.args = args
        e.cancelled = False
        # +1 rounds UP: the entry's bucket boundary is never before its
        # nominal deadline.
        idx = int((self._loop.time() + delay_s) / self._g) + 1
        with self._lock:
            b = self._buckets.get(idx)
            if b is None:
                self._buckets[idx] = [e]
            else:
                b.append(e)
            self.live += 1
            rearm = self._armed_idx is None or idx < self._armed_idx
            if rearm:
                self._armed_idx = idx
        if rearm:
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is self._loop:
                self._arm()
            else:
                try:
                    self._loop.call_soon_threadsafe(self._arm)
                except RuntimeError:
                    pass  # loop closed; entries die with it
        return e

    def cancel(self, e: _WheelEntry):
        """Lazy cancel — the bucket entry stays until its sweep, the
        callback never fires.  Safe from any thread."""
        with self._lock:
            if not e.cancelled:
                e.cancelled = True
                self.live -= 1

    def _arm(self):
        # Loop thread only.  Recomputes the earliest bucket under the lock,
        # so racing add()s converge: whichever _arm runs last wins with the
        # true minimum.
        with self._lock:
            idx = min(self._buckets, default=None)
            self._armed_idx = idx
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if idx is not None:
            self._timer = self._loop.call_at(idx * self._g, self._service)

    def _service(self):
        self._timer = None
        now = self._loop.time()
        fire = []
        with self._lock:
            due = [i for i in self._buckets if i * self._g <= now]
            for i in due:
                for e in self._buckets.pop(i):
                    if not e.cancelled:
                        e.cancelled = True
                        self.live -= 1
                        fire.append(e)
        for e in fire:
            try:
                e.cb(*e.args)
            except Exception:
                logger.exception("timeout-wheel callback failed")
        self._arm()

    def bucket_count(self) -> int:
        """Total entries still held in buckets (incl. lazily-cancelled)."""
        with self._lock:
            return sum(len(b) for b in self._buckets.values())


# One wheel per event loop, shared by every RpcClient on it.  WeakKey so a
# dead loop releases its wheel (tests spin up many loops).
_WHEELS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_WHEELS_LOCK = threading.Lock()


def _loop_wheel(loop) -> TimeoutWheel:
    w = _WHEELS.get(loop)
    if w is None:
        with _WHEELS_LOCK:
            w = _WHEELS.get(loop)
            if w is None:
                w = TimeoutWheel(loop, GlobalConfig.rpc_timeout_wheel_ms / 1000.0)
                _WHEELS[loop] = w
    return w


class DirectCall:
    """Completion sink for ``RpcClient.submit_direct``.

    Exactly one of ``on_reply`` / ``on_error`` fires, once.  ``on_reply``
    runs on the client's read loop; ``on_error`` runs on the read loop or
    — in narrow teardown races — on the submitting thread.  Implementations
    must therefore be thread-agnostic and non-blocking (post to a loop if
    they need loop-affine state)."""

    __slots__ = ("entry",)

    def __init__(self):
        self.entry = None  # armed TimeoutWheel entry, owned by the client

    def on_reply(self, payload):
        raise NotImplementedError

    def on_error(self, exc: BaseException):
        raise NotImplementedError


class RpcClient:
    """A connection to one RpcServer.  Safe for concurrent calls from one
    event loop.  Push messages from the server are delivered to
    ``push_handler(method, payload)`` if set."""

    def __init__(self, address: Address, push_handler: Optional[Callable] = None,
                 on_disconnect: Optional[Callable] = None):
        self.address = address
        self._push_handler = push_handler
        self._on_disconnect = on_disconnect
        self._reader = None
        self._writer = None
        # Values are asyncio.Futures (loop-path calls, odd msg ids) or
        # DirectCall sinks (direct submits, even msg ids) — the read loop
        # branches on id parity, never isinstance.
        self._pending: Dict[int, Any] = {}
        self._next_id = 1         # loop-path ids: odd, loop-thread only
        self._direct_next_id = 2  # direct-submit ids: even, lock below
        self._direct_id_lock = threading.Lock()
        # Every byte written to the socket goes under _send_lock — the loop
        # flusher and user-thread direct submits serialize here.
        self._send_lock = threading.Lock()
        self._sock = None
        self._wheel: Optional[TimeoutWheel] = None
        self._wsegs: list = []
        self._wbytes = 0
        self._flush_scheduled = False
        self._batch_buf: list = []  # [(segments, nbytes)] — pre-encoded
        self._batch_bytes = 0
        self._batch_scheduled = False
        self._loop = None
        self._read_task = None
        self._closed = False
        self._chaos = _ChaosInjector()
        self._delay = _DelayInjector()

    async def connect(self):
        host, port = parse_address(self.address)
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(host, port),
            timeout=GlobalConfig.rpc_connect_timeout_s,
        )
        self._loop = asyncio.get_running_loop()
        try:
            self._writer.transport.set_write_buffer_limits(high=4 << 20)
        except Exception:  # raylint: waive[RTL003] write-buffer limit is a transport nicety
            pass
        # get_extra_info hands back a TransportSocket facade whose send()
        # deprecation-warns; direct submit needs the real non-blocking
        # socket underneath it.
        tsock = self._writer.get_extra_info("socket")
        self._sock = getattr(tsock, "_sock", tsock)
        if GlobalConfig.rpc_timeout_wheel_ms > 0:
            self._wheel = _loop_wheel(self._loop)
        self._read_task = self._loop.create_task(self._read_loop())
        # Version announcement: pipelined ahead of the first real call, so
        # negotiation costs zero round-trips.  ALWAYS the v1 body format —
        # a pre-v2 server must be able to parse it and answer goodbye
        # instead of choking on a buffer-table body.
        data = _encode_frame_v1(
            (0, "__hello__", (PROTOCOL_VERSION, MIN_COMPAT_VERSION))
        )
        self._wsegs.append(data)
        self._wbytes += len(data)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush_wbuf)
        return self

    # Outgoing frames coalesce into one segment list flushed once per loop
    # pass — a burst of calls (pipelined tasks, batched submissions) costs
    # one writelines, not one write per call, and out-of-band payload
    # buffers ride to the transport without an intermediate copy.
    def _write_frame(self, frame):
        segs, n = _encode_frame(frame)
        self._wsegs.extend(segs)
        self._wbytes += n
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush_wbuf)

    # Transport-level call multiplexing: calls made with batch=True within
    # one loop pass ride a single batch container (one frame parse on the
    # server) while keeping fully independent per-call replies — semantics
    # identical to individual calls.  Sub-frames are encoded ONCE at queue
    # time, so the byte budget below is exact encoded size, not an
    # estimate (a burst of near-cap frames can no longer overshoot it).
    _BATCH_MAX_FRAMES = 256  # bound un-flushed batch memory before the
    # 4 MB transport backpressure check in call() can see the bytes
    _BATCH_MAX_BYTES = 4 << 20  # same threshold as the transport check

    def _queue_batched(self, frame):
        encoded = _encode_frame(frame)
        self._batch_buf.append(encoded)
        self._batch_bytes += encoded[1]
        if (
            len(self._batch_buf) >= self._BATCH_MAX_FRAMES
            or self._batch_bytes >= self._BATCH_MAX_BYTES
        ):
            self._flush_batch()
        elif not self._batch_scheduled:
            self._batch_scheduled = True
            self._loop.call_soon(self._flush_batch)

    def _flush_batch(self):
        self._batch_scheduled = False
        items, self._batch_buf = self._batch_buf, []
        nbytes, self._batch_bytes = self._batch_bytes, 0
        if not items:
            return
        if len(items) == 1:
            segs, n = items[0]
            self._wsegs.extend(segs)
            self._wbytes += n
        else:
            # Each pre-encoded sub-frame already starts with its own 8-byte
            # length — exactly the batch container's sub-entry format, so
            # flushing is pure concatenation with zero re-pickling.
            body_len = 5 + nbytes
            codec = _codec if _codec_resolved else _resolve_codec()
            if codec is not None:
                head = codec.pack_batch_head(nbytes, len(items))
            else:
                head = bytearray(_LEN + 5)
                head[0:_LEN] = body_len.to_bytes(_LEN, "little")
                head[_LEN] = _MAGIC_BATCH
                head[_LEN + 1 : _LEN + 5] = len(items).to_bytes(4, "little")
            self._wsegs.append(head)
            for segs, _n in items:
                self._wsegs.extend(segs)
            self._wbytes += _LEN + body_len
            with _STATS_LOCK:
                FRAME_STATS["batch_frames"] += 1
                FRAME_STATS["batched_calls"] += len(items)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush_wbuf)

    def _flush_wbuf(self):
        self._flush_scheduled = False
        # _send_lock orders this flush against user-thread direct submits:
        # a direct sender either beats the swap (its segments ride this
        # writelines) or sees the transport buffer before this loop pass
        # drains it (and queues instead of writing raw).
        with self._send_lock:
            if not self._wsegs:
                return
            segs, self._wsegs = self._wsegs, []
            self._wbytes = 0
            try:
                self._writer.writelines(segs)
            except Exception:  # raylint: waive[RTL003] torn down mid-flush; read loop surfaces the failure
                pass

    async def _read_loop(self):
        try:
            while True:
                frame = await _read_frame(self._reader)
                msg_id, kind, payload = frame
                if msg_id == 0:
                    if kind == "__goodbye__":
                        sv, smin = payload
                        self._closed = True
                        self._fail_all_pending(RpcVersionError(
                            f"server {self.address} speaks protocol "
                            f"{sv} (min compat {smin}); this client is "
                            f"{PROTOCOL_VERSION} (min {MIN_COMPAT_VERSION})"
                        ))
                        break
                    if self._push_handler:
                        try:
                            res = self._push_handler(kind, payload)
                            if asyncio.iscoroutine(res):
                                asyncio.get_running_loop().create_task(res)
                        except Exception:
                            logger.exception("push handler failed for %s", kind)
                    continue
                mid = -msg_id
                handler = self._pending.pop(mid, None)
                if handler is None:
                    continue
                if mid & 1:
                    # Odd id: loop-path call() awaiting an asyncio future.
                    if not handler.done():
                        if kind == "R":
                            handler.set_result(payload)
                        else:
                            exc, tb = payload
                            handler.set_exception(RpcRemoteError("?", exc, tb))
                else:
                    # Even id: direct submit — complete the DirectCall sink
                    # inline (no future, no task wake).
                    entry = handler.entry
                    if entry is not None and self._wheel is not None:
                        self._wheel.cancel(entry)
                    try:
                        if kind == "R":
                            handler.on_reply(payload)
                        else:
                            exc, tb = payload
                            handler.on_error(RpcRemoteError("?", exc, tb))
                    except Exception:
                        logger.exception("direct reply handler failed")
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        except Exception:
            logger.exception("rpc client read loop error (%s)", self.address)
        finally:
            # Distinguish peer-initiated loss from our own close(): close()
            # flips _closed BEFORE cancelling this task, so observing it
            # still False here means the PEER went away — the signal
            # liveness watchers key on (a worker must exit when its agent's
            # socket closes, reference: raylet IPC-socket death).
            peer_lost = not self._closed
            self._closed = True  # peer gone: force reconnect on next use
            self._fail_all_pending(RpcConnectionError(f"connection to {self.address} lost"))
            if peer_lost and self._on_disconnect is not None:
                try:
                    self._on_disconnect()
                except Exception:  # noqa: BLE001 — watcher must not kill the loop
                    logger.exception("on_disconnect callback failed")

    def _fail_all_pending(self, exc):
        # Swap first: submit_direct re-checks _closed after registering and
        # pops its own entry if it lost the race, so ownership of every
        # entry here is unambiguous.
        pending, self._pending = self._pending, {}
        for mid, handler in pending.items():
            if mid & 1:
                if not handler.done():
                    handler.set_exception(exc)
            else:
                entry = handler.entry
                if entry is not None and self._wheel is not None:
                    self._wheel.cancel(entry)
                try:
                    handler.on_error(exc)
                except Exception:
                    logger.exception("direct error handler failed")

    @property
    def connected(self) -> bool:
        return (
            self._writer is not None
            and not self._closed
            and not self._writer.is_closing()
        )

    async def call(
        self, method: str, payload=None, timeout: Optional[float] = None,
        batch: bool = False,
    ):
        if not self.connected:
            raise RpcConnectionError(f"not connected to {self.address}")
        if self._chaos.enabled() and self._chaos.fail_request(method):
            raise RpcConnectionError(f"[chaos] dropped request {method}")
        if self._delay.enabled():
            d = self._delay.delay_s(method)
            if d > 0:
                await asyncio.sleep(d)
        # Single-threaded loop: id allocation + buffer append are atomic.
        # Loop-path ids stay odd; direct-submit ids are even (allocated
        # under their own lock) — parity tells the read loop which
        # completion style a reply belongs to without a type check.
        msg_id = self._next_id
        self._next_id += 2
        fut = self._loop.create_future()
        self._pending[msg_id] = fut
        if batch:
            self._queue_batched((msg_id, method, payload))
        else:
            self._write_frame((msg_id, method, payload))
        if (
            self._wbytes + self._batch_bytes
            + self._writer.transport.get_write_buffer_size()
        ) > (4 << 20):
            try:
                self._flush_batch()
                self._flush_wbuf()
                await self._writer.drain()
            except (ConnectionError, RuntimeError) as e:
                self._pending.pop(msg_id, None)
                raise RpcConnectionError(str(e)) from e
        timeout = timeout if timeout is not None else GlobalConfig.rpc_call_timeout_s
        try:
            if timeout == UNBOUNDED:
                # Explicitly-unbounded calls (task pushes, owner gets) skip
                # the per-call timer; connection loss still fails the future.
                result = await fut
            elif self._wheel is not None:
                # Shared wheel: a dict append + lazy cancel instead of the
                # two timer-heap ops asyncio.wait_for costs per call.  The
                # expiry callback sets the SAME RpcTimeoutError the wait_for
                # path raised, so retry policies above see no difference.
                entry = self._wheel.add(
                    timeout, self._expire_call, msg_id, method, timeout
                )
                try:
                    result = await fut
                finally:
                    self._wheel.cancel(entry)
            else:
                result = await asyncio.wait_for(fut, timeout=timeout)
        except asyncio.TimeoutError:
            self._pending.pop(msg_id, None)
            raise RpcTimeoutError(
                f"rpc {method} to {self.address} timed out after {timeout}s"
            )
        if self._chaos.enabled() and self._chaos.fail_response(method):
            raise RpcConnectionError(f"[chaos] dropped response {method}")
        return result

    # Wheel expiry callbacks (loop thread, via TimeoutWheel._service).
    def _expire_call(self, msg_id, method, timeout):
        fut = self._pending.pop(msg_id, None)
        if fut is not None and not fut.done():
            fut.set_exception(RpcTimeoutError(
                f"rpc {method} to {self.address} timed out after {timeout}s"
            ))

    def _expire_direct(self, msg_id, method, timeout):
        handler = self._pending.pop(msg_id, None)
        if handler is not None:
            try:
                handler.on_error(RpcTimeoutError(
                    f"rpc {method} to {self.address} timed out after {timeout}s"
                ))
            except Exception:
                logger.exception("direct timeout handler failed")

    def submit_direct(self, method: str, payload, handler: DirectCall,
                      timeout: Optional[float] = None) -> bool:
        """Serialize and send one request from the CALLING thread.

        The sync-path fast lane: no ``call_soon_threadsafe`` self-pipe
        wake, no submission task, no per-call timer — the user thread
        pickles the frame and, when the transport is idle, writes it to
        the socket itself.  Returns ``False`` (with NO side effects) when
        the connection isn't usable — the caller falls back to the
        loop path.  Once it returns ``True``, exactly one of
        ``handler.on_reply`` / ``handler.on_error`` will fire.

        Ownership rules (see docs/performance.md):

        * Every socket write happens under ``_send_lock`` — here and in
          the loop's ``_flush_wbuf``; the transport never sees interleaved
          partial frames.
        * The raw ``sock.send`` is attempted only when the transport's
          write buffer is empty AND ``_wsegs`` is empty, so it can never
          overtake queued bytes (the pipelined hello included — ordering
          with the handshake is preserved).
        * On a partial send the remainder is queued at the FRONT of
          ``_wsegs`` (still under the lock) and the loop flusher takes
          over; ownership of the bytes passes to the loop exactly once.
        * After the handler is registered, failures are delivered through
          it (never a ``False`` return): the frame counters have already
          ticked and the caller must not re-encode."""
        if self._sock is None or not self.connected:
            return False
        with self._direct_id_lock:
            msg_id = self._direct_next_id
            self._direct_next_id += 2
        timeout = timeout if timeout is not None else GlobalConfig.rpc_call_timeout_s
        if self._wheel is not None and timeout and timeout != UNBOUNDED:
            handler.entry = self._wheel.add(
                timeout, self._expire_direct, msg_id, method, timeout
            )
        self._pending[msg_id] = handler
        if self._closed:
            # Lost the race with _fail_all_pending's swap: our entry may
            # sit in the new dict nobody will fail.  We still own it —
            # deliver the error ourselves.
            if self._pending.pop(msg_id, None) is not None:
                if handler.entry is not None and self._wheel is not None:
                    self._wheel.cancel(handler.entry)
                try:
                    handler.on_error(
                        RpcConnectionError(f"connection to {self.address} lost")
                    )
                except Exception:
                    logger.exception("direct error handler failed")
            return True
        segs, n = _encode_frame((msg_id, method, payload))
        flush = False
        try:
            with self._send_lock:
                if (
                    not self._wsegs
                    and self._writer.transport.get_write_buffer_size() == 0
                ):
                    data = segs[0] if len(segs) == 1 else b"".join(segs)
                    try:
                        sent = self._sock.send(data)
                    except BlockingIOError:
                        sent = 0
                    if sent < len(data):
                        # Hand the tail to the loop flusher — front of the
                        # queue, so frame bytes stay contiguous.
                        self._wsegs.insert(0, memoryview(data)[sent:])
                        self._wbytes += len(data) - sent
                        flush = True
                else:
                    self._wsegs.extend(segs)
                    self._wbytes += n
                    flush = True
        except OSError:
            # Socket died mid-send: the read loop observes the same death
            # and fails every pending entry, ours included.
            pass
        if flush:
            try:
                self._loop.call_soon_threadsafe(self._flush_wbuf)
            except RuntimeError:
                pass  # loop closed; read-loop teardown owns the failure
        return True

    async def notify(self, method: str, payload=None):
        if not self.connected:
            raise RpcConnectionError(f"not connected to {self.address}")
        self._write_frame((0, method, payload))
        if (
            self._wbytes + self._writer.transport.get_write_buffer_size()
        ) > (4 << 20):
            try:
                self._flush_wbuf()
                await self._writer.drain()
            except (ConnectionError, RuntimeError) as e:
                raise RpcConnectionError(str(e)) from e

    async def close(self):
        self._closed = True
        if self._read_task:
            self._read_task.cancel()
        if self._writer:
            try:
                self._writer.close()
            except Exception as e:
                logger.debug("client writer close failed: %s", e)


def next_backoff_delay(prev: float, base: Optional[float] = None,
                       cap: Optional[float] = None) -> float:
    """Next retry sleep after a failed attempt that waited ``prev``.

    With ``rpc_retry_jitter`` (default): decorrelated jitter —
    ``min(cap, uniform(base, prev * 3))`` — so two clients that failed at
    the same instant (every client in the cluster, after a control-plane
    restart) diverge instead of reconnecting in lockstep.  Without it:
    the classic deterministic doubling, ``min(cap, prev * 2)``.

    ``base``/``cap`` default to the rpc retry knobs; other backoff users
    (the autoscaler's per-type launch gate) pass their own bounds."""
    if cap is None:
        cap = GlobalConfig.rpc_retry_max_delay_s
    if not GlobalConfig.rpc_retry_jitter:
        return min(prev * 2, cap)
    if base is None:
        base = GlobalConfig.rpc_retry_base_delay_s
    return min(cap, random.uniform(base, max(base, prev * 3)))


class RetryableRpcClient:
    """Reconnecting client with exponential backoff — the analog of
    ``RetryableGrpcClient``.  Only retries on transport failures, never on
    remote exceptions; callers must ensure retried methods are idempotent."""

    def __init__(self, address: Address, push_handler=None, on_disconnect=None,
                 address_resolver=None):
        self.address = address
        self._push_handler = push_handler
        self._on_disconnect = on_disconnect
        # Optional leader discovery (cp_ha.make_cp_resolver): re-invoked
        # before every (re)connect, so after a control-plane failover the
        # normal reconnect loop transparently re-anchors the client to
        # the new leader's published endpoint.
        self._address_resolver = address_resolver
        self._client: Optional[RpcClient] = None
        self._connect_lock = asyncio.Lock()

    async def _ensure(self) -> RpcClient:
        client = self._client
        if client and client.connected:
            return client
        async with self._connect_lock:
            client = self._client
            if client and client.connected:
                return client
            if self._address_resolver is not None:
                try:
                    resolved = self._address_resolver()
                    if resolved:
                        self.address = resolved
                except Exception as e:  # noqa: BLE001 — discovery is advisory
                    logger.debug("address resolver failed: %s", e)
            # Work on a LOCAL and publish only after connect succeeds: a
            # concurrent call's failure path nulls self._client, and
            # returning the attribute (not the local) could hand back
            # None mid-connect.
            client = RpcClient(
                self.address, self._push_handler,
                on_disconnect=self._on_disconnect,
            )
            await client.connect()
            self._client = client
            return client

    async def call(
        self, method: str, payload=None, timeout=None, retries=None,
        batch: bool = False,
    ):
        retries = retries if retries is not None else GlobalConfig.rpc_max_retries
        delay = GlobalConfig.rpc_retry_base_delay_s
        last_exc = None
        # With leader discovery attached (HA mode), the attempt budget
        # alone can drain INSIDE a leaderless failover window (old leader
        # dead, standby still replaying the journal tail) — so retrying
        # also continues until a grace window sized from the election
        # parameters has elapsed.  Plain clients keep pure attempt counts.
        ha_grace = 0.0
        if self._address_resolver is not None:
            ha_grace = max(
                5.0,
                3.0 * (GlobalConfig.cp_lease_ttl_s
                       + GlobalConfig.cp_lease_poll_s),
            )
        started = time.monotonic()
        attempts = 0
        # False only when EVERY attempt died inside connect(): the request
        # frame was never written to any socket, so the peer provably never
        # saw it.  Callers use this to tell "request may have executed"
        # from "request never left this process" (e.g. a task push is
        # exactly-once safe to re-lease in the latter case).
        maybe_delivered = False

        def exhausted() -> bool:
            if attempts < max(1, retries):
                return False
            return time.monotonic() - started >= ha_grace

        while True:
            attempts += 1
            try:
                client = await self._ensure()
                maybe_delivered = True
                return await client.call(method, payload, timeout, batch=batch)
            except RpcRemoteError as e:
                # A standby (or freshly fenced stale leader) answered:
                # the request did NOT execute — drop the connection and
                # retry, letting _ensure()'s resolver find the leader.
                if not isinstance(e.cause, NotLeaderError):
                    raise
                last_exc = e
                dropped, self._client = self._client, None
                if dropped is not None:
                    try:
                        await dropped.close()
                    except Exception:  # raylint: waive[RTL003] stale-leader socket; reconnect follows
                        pass
                if exhausted():
                    break
                await asyncio.sleep(delay)
                delay = next_backoff_delay(delay)
            except (
                RpcConnectionError, ConnectionError, OSError,
                asyncio.TimeoutError,
            ) as e:
                # NOTE: only transport-level failures land here —
                # asyncio.TimeoutError can come solely from connect()
                # (per-call deadlines surface as RpcError, which
                # deliberately propagates without dropping the client, so
                # a busy server never costs the shared connection its
                # connection-owned server state, e.g. leases).
                last_exc = e
                # Transport actually failed: CLOSE the old client (never
                # abandon it — its half-dead socket would leak an FD and
                # linger as a stale liveness signal) and reconnect.
                dropped, self._client = self._client, None
                if dropped is not None:
                    try:
                        await dropped.close()
                    except Exception:  # raylint: waive[RTL003] half-dead socket; reconnect follows
                        pass
                if exhausted():
                    break
                await asyncio.sleep(delay)
                delay = next_backoff_delay(delay)
        exc = RpcConnectionError(
            f"rpc {method} to {self.address} failed after {attempts} attempts: {last_exc}"
        )
        exc.maybe_delivered = maybe_delivered
        raise exc

    async def notify(self, method: str, payload=None):
        client = await self._ensure()
        await client.notify(method, payload)

    async def close(self):
        if self._client:
            await self._client.close()
            self._client = None


class ClientPool:
    """Cached clients keyed by address (analog of CoreWorkerClientPool /
    RayletClientPool)."""

    def __init__(self, retryable: bool = True):
        self._retryable = retryable
        self._clients: Dict[Address, Any] = {}

    def get(self, address: Address, push_handler=None):
        client = self._clients.get(address)
        if client is None:
            client = (
                RetryableRpcClient(address, push_handler)
                if self._retryable
                else RpcClient(address, push_handler)
            )
            self._clients[address] = client
        return client

    def peek(self, address: Address):
        """Read-only lookup — no insertion, so safe from any thread (the
        direct-submit fast lane probes for an already-connected client)."""
        return self._clients.get(address)

    def invalidate(self, address: Address):
        """Drop the cached client WITHOUT closing it (caller knows the
        connection is already being torn down elsewhere).  Prefer
        ``close()`` when the peer is simply gone — transports keep their
        FD open after peer EOF until transport.close()."""
        self._clients.pop(address, None)

    async def close(self, address: Address):
        client = self._clients.pop(address, None)
        if client is not None:
            try:
                await client.close()
            except Exception as e:
                logger.debug("client close failed: %s", e)

    async def close_all(self):
        for c in self._clients.values():
            try:
                await c.close()
            except Exception as e:
                logger.debug("client close failed in close_all: %s", e)
        self._clients.clear()
