"""Runtime environments — per-task/actor working_dir, py_modules, env_vars.

Role-equivalent of the reference's runtime-env subsystem
(``python/ray/_private/runtime_env/``: plugins for working_dir/py_modules/
env_vars, URI-cached packages).  TPU-native simplification: no per-node HTTP
agent process — packaging happens in the driver (content-addressed staging
into a shared cache directory) and application happens in the worker process
at startup.  The staged-package path rides the worker's env (the analog of
the reference shipping runtime-env URIs in the task spec and resolving them
through the agent), so it participates in the worker pool's env-key and
workers are cached per runtime env exactly like the reference's
per-(language, runtime-env-hash) worker pool (``raylet/worker_pool.h:281``).

Supported keys (the reference's most-used subset):
  - ``env_vars``: dict of str → str set in the worker process.
  - ``working_dir``: local directory, staged by content hash; worker chdirs
    into the staged copy and prepends it to ``sys.path``.
  - ``py_modules``: list of local dirs/files staged the same way and
    prepended to ``sys.path``.

conda/pip/uv/container envs are intentionally out of scope (they imply
package installation, which this image forbids); requesting them raises.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import sys
from typing import Any, Dict, List, Optional

# Env vars used to ship the resolved env to the worker process.
WORKING_DIR_ENV = "RAY_TPU_RT_WORKING_DIR"
PY_MODULES_ENV = "RAY_TPU_RT_PY_MODULES"

_UNSUPPORTED = ("conda", "pip", "uv", "container", "image_uri")


def _cache_root() -> str:
    root = os.environ.get("RAY_TPU_LOG_DIR", "/tmp/ray_tpu")
    return os.path.join(root, "runtime_env_cache")


def _hash_path(path: str) -> str:
    """Content hash of a file or directory tree (names + bytes)."""
    h = hashlib.sha1()
    if os.path.isfile(path):
        h.update(os.path.basename(path).encode())
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()
    for dirpath, dirnames, filenames in os.walk(path):
        # Prune before descent (must mutate in place, pre-sort) and never
        # hash/stage caches — the reference excludes these too.
        dirnames[:] = sorted(
            d for d in dirnames if d not in ("__pycache__", ".git")
        )
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, path)
            h.update(rel.encode())
            try:
                with open(full, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
            except OSError:
                continue
    return h.hexdigest()


def package_path(path: str) -> str:
    """Stage ``path`` into the content-addressed cache; returns staged path.

    Idempotent: same content → same cache entry (the analog of the
    reference's GCS-KV URI cache for working_dir/py_modules packages).
    """
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.exists(path):
        raise FileNotFoundError(f"runtime_env path does not exist: {path}")
    digest = _hash_path(path)
    base = os.path.basename(path.rstrip("/")) or "pkg"
    # Stage under a digest directory, keeping the original basename — imports
    # of a staged package need the module's own name on disk.
    staged = os.path.join(_cache_root(), digest[:16], base)
    if os.path.exists(staged):
        return staged
    tmp = f"{staged}.tmp.{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(os.path.dirname(tmp), exist_ok=True)
    if os.path.isdir(path):
        shutil.copytree(
            path, tmp,
            ignore=shutil.ignore_patterns("__pycache__", ".git"),
        )
    else:
        shutil.copy2(path, tmp)
    try:
        os.rename(tmp, staged)
    except OSError:
        # Lost a concurrent staging race; the winner's copy is equivalent.
        shutil.rmtree(tmp, ignore_errors=True)
    return staged


def resolve_runtime_env(runtime_env: Optional[Dict[str, Any]]) -> Dict[str, str]:
    """Driver side: normalize a runtime_env dict into worker env vars.

    Returns the env-var dict that fully describes the environment (and hence
    keys the worker pool's idle cache).
    """
    if not runtime_env:
        return {}
    for key in _UNSUPPORTED:
        if runtime_env.get(key):
            raise ValueError(
                f"runtime_env[{key!r}] is not supported: package installation "
                "is unavailable; pre-bake dependencies into the image"
            )
    unknown = set(runtime_env) - {"env_vars", "working_dir", "py_modules"}
    if unknown:
        raise ValueError(f"unknown runtime_env keys: {sorted(unknown)}")
    env: Dict[str, str] = dict(runtime_env.get("env_vars") or {})
    for k, v in env.items():
        if not isinstance(k, str) or not isinstance(v, str):
            raise TypeError("runtime_env['env_vars'] must be Dict[str, str]")
    wd = runtime_env.get("working_dir")
    if wd:
        env[WORKING_DIR_ENV] = package_path(wd)
    mods: List[str] = []
    for m in runtime_env.get("py_modules") or []:
        mods.append(package_path(m))
    if mods:
        env[PY_MODULES_ENV] = json.dumps(mods)
    return env


def apply_runtime_env_in_worker() -> None:
    """Worker side: chdir into the staged working_dir, extend sys.path."""
    wd = os.environ.get(WORKING_DIR_ENV)
    if wd and os.path.isdir(wd):
        os.chdir(wd)
        if wd not in sys.path:
            sys.path.insert(0, wd)
    mods = os.environ.get(PY_MODULES_ENV)
    if mods:
        for m in json.loads(mods):
            # m is <cache>/<digest>/<module-name>; importing needs the parent.
            parent = os.path.dirname(m)
            if parent not in sys.path:
                sys.path.insert(0, parent)
