"""Runtime environments — per-task/actor working_dir, py_modules, env_vars.

Role-equivalent of the reference's runtime-env subsystem
(``python/ray/_private/runtime_env/``: plugins for working_dir/py_modules/
env_vars, URI-cached packages).  TPU-native simplification: no per-node HTTP
agent process — packaging happens in the driver (content-addressed staging
into a shared cache directory) and application happens in the worker process
at startup.  The staged-package path rides the worker's env (the analog of
the reference shipping runtime-env URIs in the task spec and resolving them
through the agent), so it participates in the worker pool's env-key and
workers are cached per runtime env exactly like the reference's
per-(language, runtime-env-hash) worker pool (``raylet/worker_pool.h:281``).

Supported keys (the reference's most-used subset):
  - ``env_vars``: dict of str → str set in the worker process.
  - ``working_dir``: local directory, staged by content hash; worker chdirs
    into the staged copy and prepends it to ``sys.path``.
  - ``py_modules``: list of local dirs/files staged the same way and
    prepended to ``sys.path``.

``pip`` envs build a real virtualenv per requirements set, keyed by the
hash of the requirement list, cached and reused across tasks/actors/jobs
(the reference's most-used isolation mode after working_dir; ray
``_private/runtime_env/pip.py``).  Workers for a pip env run under the
venv's interpreter; ``--system-site-packages`` keeps the image's baked-in
stack (jax et al.) visible, exactly like the reference's virtualenv
inheritance.

``conda`` envs resolve an existing named env, an ``environment.yml``
path, or an inline spec dict to that env's interpreter (hash-cached like
pip; ray ``_private/runtime_env/conda.py``).  ``container``/``image_uri``
wrap the worker command in ``podman run``/``docker run`` with host
network/pid/ipc and the session + shm dirs mounted (ray
``_private/runtime_env/image_uri.py``); both are gated on the container
binary being present on PATH.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import subprocess
import sys
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

# Env vars used to ship the resolved env to the worker process.
WORKING_DIR_ENV = "RAY_TPU_RT_WORKING_DIR"
PY_MODULES_ENV = "RAY_TPU_RT_PY_MODULES"
VENV_PY_ENV = "RAY_TPU_RT_VENV_PY"
CONTAINER_ENV = "RAY_TPU_RT_CONTAINER"


def _cache_root() -> str:
    root = os.environ.get("RAY_TPU_LOG_DIR", "/tmp/ray_tpu")
    return os.path.join(root, "runtime_env_cache")


def _hash_path(path: str) -> str:
    """Content hash of a file or directory tree (names + bytes)."""
    h = hashlib.sha1()
    if os.path.isfile(path):
        h.update(os.path.basename(path).encode())
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()
    for dirpath, dirnames, filenames in os.walk(path):
        # Prune before descent (must mutate in place, pre-sort) and never
        # hash/stage caches — the reference excludes these too.
        dirnames[:] = sorted(
            d for d in dirnames if d not in ("__pycache__", ".git")
        )
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, path)
            h.update(rel.encode())
            try:
                with open(full, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
            except OSError:
                continue
    return h.hexdigest()


def package_path(path: str) -> str:
    """Stage ``path`` into the content-addressed cache; returns staged path.

    Idempotent: same content → same cache entry (the analog of the
    reference's GCS-KV URI cache for working_dir/py_modules packages).
    """
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.exists(path):
        raise FileNotFoundError(f"runtime_env path does not exist: {path}")
    digest = _hash_path(path)
    base = os.path.basename(path.rstrip("/")) or "pkg"
    # Stage under a digest directory, keeping the original basename — imports
    # of a staged package need the module's own name on disk.
    staged = os.path.join(_cache_root(), digest[:16], base)
    if os.path.exists(staged):
        return staged
    tmp = f"{staged}.tmp.{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(os.path.dirname(tmp), exist_ok=True)
    if os.path.isdir(path):
        shutil.copytree(
            path, tmp,
            ignore=shutil.ignore_patterns("__pycache__", ".git"),
        )
    else:
        shutil.copy2(path, tmp)
    try:
        os.rename(tmp, staged)
    except OSError:
        # Lost a concurrent staging race; the winner's copy is equivalent.
        shutil.rmtree(tmp, ignore_errors=True)
    return staged


def _normalize_pip(spec) -> Dict[str, Any]:
    """``pip`` accepts a list of requirements or
    ``{"packages": [...], "pip_install_options": [...]}``."""
    if isinstance(spec, (list, tuple)):
        return {"packages": [str(p) for p in spec], "pip_install_options": []}
    if isinstance(spec, dict):
        return {
            "packages": [str(p) for p in spec.get("packages", [])],
            "pip_install_options": [
                str(o) for o in spec.get("pip_install_options", [])
            ],
        }
    raise TypeError("runtime_env['pip'] must be a list or a dict")


def build_pip_env(spec) -> str:
    """Build (or reuse) the virtualenv for a pip spec; returns the venv's
    python path.  Keyed by the hash of (sorted packages, options); builds
    are serialized per key with an flock so concurrent drivers/agents
    never interleave writes into one venv."""
    norm = _normalize_pip(spec)
    if not norm["packages"]:
        return sys.executable
    digest = hashlib.sha1(
        json.dumps(
            [sorted(norm["packages"]), norm["pip_install_options"]]
        ).encode()
    ).hexdigest()[:16]
    venv_dir = os.path.join(_cache_root(), "venvs", digest)
    py = os.path.join(venv_dir, "bin", "python")
    ready = os.path.join(venv_dir, ".ready")
    if os.path.exists(ready):
        return py
    import fcntl

    os.makedirs(os.path.dirname(venv_dir), exist_ok=True)
    with open(venv_dir + ".lock", "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        if os.path.exists(ready):
            return py
        shutil.rmtree(venv_dir, ignore_errors=True)
        # --system-site-packages: the image's baked-in stack stays visible;
        # the venv only ADDS the requested packages (reference semantics).
        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages", venv_dir],
            check=True, capture_output=True, timeout=300,
        )
        # When the driver itself runs inside a venv, --system-site-packages
        # inherits the BASE interpreter's site, not the driver venv's —
        # bridge the driver's actual site-packages with a .pth so the
        # image's stack (jax, cloudpickle, ...) stays importable.
        import site
        import sysconfig

        new_site = sysconfig.get_path(
            "purelib", vars={"base": venv_dir, "platbase": venv_dir}
        )
        parent_paths = [
            p for p in site.getsitepackages() if os.path.isdir(p)
        ]
        if parent_paths and os.path.isdir(new_site):
            with open(os.path.join(new_site, "_rtpu_parent_site.pth"), "w") as f:
                f.write("\n".join(parent_paths) + "\n")
        cmd = (
            [py, "-m", "pip", "install", "--disable-pip-version-check"]
            + norm["pip_install_options"]
            + norm["packages"]
        )
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=1800
        )
        if proc.returncode != 0:
            shutil.rmtree(venv_dir, ignore_errors=True)
            raise RuntimeError(
                f"pip runtime_env build failed: {proc.stderr[-2000:]}"
            )
        with open(ready, "w") as f:
            f.write(digest)
    return py


def _conda_binary() -> Optional[str]:
    for name in ("conda", "mamba", "micromamba"):
        path = shutil.which(name)
        if path:
            return path
    return None


def build_conda_env(spec) -> str:
    """Resolve a conda runtime env to its python interpreter.

    Reference: ``python/ray/_private/runtime_env/conda.py`` — three spec
    shapes: an existing env NAME, a path to an ``environment.yml``, or an
    inline dict (written to a yml).  Created envs are cached per content
    hash like pip venvs.  Gated: raises a clear error when no conda-like
    binary (conda/mamba/micromamba) is on PATH.
    """
    conda = _conda_binary()
    if conda is None:
        raise RuntimeError(
            "runtime_env['conda'] requires a conda/mamba/micromamba binary "
            "on PATH; none found on this host"
        )

    def env_python(prefix: str) -> str:
        return os.path.join(prefix, "bin", "python")

    if isinstance(spec, str) and not spec.endswith((".yml", ".yaml")):
        # Existing named env: ask conda where it lives.
        proc = subprocess.run(
            [conda, "env", "list", "--json"],
            capture_output=True, text=True, timeout=120,
        )
        if proc.returncode == 0:
            for prefix in json.loads(proc.stdout).get("envs", []):
                if os.path.basename(prefix) == spec:
                    return env_python(prefix)
        raise RuntimeError(f"conda env {spec!r} not found")

    if isinstance(spec, str):
        with open(spec, "rb") as f:
            content = f.read()
    else:
        # Inline dict -> minimal YAML (dependencies / channels lists).
        lines = []
        for key in ("name", "channels", "dependencies"):
            val = spec.get(key)
            if val is None:
                continue
            if isinstance(val, list):
                lines.append(f"{key}:")
                lines.extend(f"  - {v}" for v in val)
            else:
                lines.append(f"{key}: {val}")
        content = ("\n".join(lines) + "\n").encode()

    digest = hashlib.sha1(content).hexdigest()[:16]
    prefix = os.path.join(_cache_root(), "conda", digest)
    ready = os.path.join(prefix, ".ready")
    if os.path.exists(ready):
        return env_python(prefix)
    import fcntl

    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    with open(prefix + ".lock", "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        if os.path.exists(ready):
            return env_python(prefix)
        shutil.rmtree(prefix, ignore_errors=True)
        yml = prefix + ".yml"
        with open(yml, "wb") as f:
            f.write(content)
        proc = subprocess.run(
            [conda, "env", "create", "-p", prefix, "-f", yml],
            capture_output=True, text=True, timeout=1800,
        )
        if proc.returncode != 0:
            shutil.rmtree(prefix, ignore_errors=True)
            raise RuntimeError(
                f"conda runtime_env build failed: {proc.stderr[-2000:]}"
            )
        with open(ready, "w") as f:
            f.write(digest)
    return env_python(prefix)


def _container_binary() -> Optional[str]:
    for name in ("podman", "docker"):
        path = shutil.which(name)
        if path:
            return path
    return None


def resolve_container_spec(spec) -> str:
    """Normalize a container runtime env to the JSON shipped to the agent.

    Reference: ``python/ray/_private/runtime_env/image_uri.py`` — the
    worker command is wrapped in ``podman run`` with host network/pid/ipc
    so the container shares the node's data plane (shm arena, TCP
    control plane).  Accepts ``"image:tag"`` or ``{"image": ...,
    "run_options": [...]}``.  A driver host without podman/docker only
    WARNS — containers run on worker nodes, whose agents re-resolve the
    runtime authoritatively (``container_argv``).
    """
    if isinstance(spec, str):
        spec = {"image": spec}
    image = spec.get("image")
    if not image or not isinstance(image, str):
        raise ValueError(
            "runtime_env['container'] needs an 'image' (or use "
            "runtime_env['image_uri'])"
        )
    unknown = set(spec) - {"image", "run_options"}
    if unknown:
        raise ValueError(
            f"unknown runtime_env['container'] keys: {sorted(unknown)}"
        )
    # Probe the DRIVER's PATH for an early heads-up — but ship only the
    # binary NAME: agents on other nodes re-resolve against their own
    # PATH in container_argv (a driver's /usr/bin/podman may be
    # /usr/local/bin/docker on an autoscaled worker host).  A missing
    # driver-side runtime is a WARNING, not an error: containers only
    # run on worker nodes, so a head node without podman/docker must not
    # false-fail a runtime_env that every worker host can satisfy — the
    # agent-side re-resolution stays the authoritative gate.
    binary = _container_binary()
    if binary is None:
        logger.warning(
            "runtime_env['container']: no podman or docker on this "
            "driver's PATH; deferring to each worker node's agent "
            "(a worker host without a container runtime will fail the "
            "lease there)"
        )
        binary = "podman"
    run_options = list(spec.get("run_options") or [])
    if not all(isinstance(o, str) for o in run_options):
        raise ValueError("container run_options must be a list of strings")
    return json.dumps(
        {
            "binary": os.path.basename(binary),
            "image": image,
            "run_options": run_options,
        }
    )


def container_argv(container_json: str, worker_env: Dict[str, str],
                   base_argv: List[str]) -> List[str]:
    """Agent side: wrap a worker command in its container runtime.

    Host network/pid/ipc keep the worker on the node's control plane and
    shm arena; the session dir, /dev/shm, and the framework source are
    mounted so the image needs python but not a baked-in ray_tpu.
    RAY_TPU_* identity vars are forwarded explicitly (podman run strips
    the inherited environment).
    """
    spec = json.loads(container_json)
    # Re-resolve the runtime on THIS host (the spec carries the driver's
    # preferred name; this agent may have it elsewhere on PATH, or only
    # the other runtime).
    binary = (
        shutil.which(spec["binary"])
        or _container_binary()
    )
    if binary is None:
        raise RuntimeError(
            f"container runtime {spec['binary']!r} not found on this "
            "node's PATH (and no podman/docker fallback)"
        )
    log_dir = os.environ.get("RAY_TPU_LOG_DIR", "/tmp/ray_tpu")
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    argv = [
        binary, "run", "--rm",
        "--network=host", "--pid=host", "--ipc=host",
        "-v", f"{log_dir}:{log_dir}",
        "-v", "/dev/shm:/dev/shm",
        "-v", f"{pkg_root}:{pkg_root}:ro",
    ]
    fwd = {
        k: v for k, v in worker_env.items()
        if k.startswith(("RAY_TPU", "PYTHON", "JAX_", "XLA_", "TPU"))
    }
    fwd["PYTHONPATH"] = (
        pkg_root + os.pathsep + worker_env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    for k, v in sorted(fwd.items()):
        argv += ["--env", f"{k}={v}"]
    argv += spec["run_options"]
    argv.append(spec["image"])
    # Inside the image: plain `python` (the venv-interpreter override is a
    # host path and does not exist in the container).
    argv += ["python"] + base_argv[1:]
    return argv


def resolve_runtime_env(runtime_env: Optional[Dict[str, Any]]) -> Dict[str, str]:
    """Driver side: normalize a runtime_env dict into worker env vars.

    Returns the env-var dict that fully describes the environment (and hence
    keys the worker pool's idle cache).
    """
    if not runtime_env:
        return {}
    unknown = set(runtime_env) - {
        "env_vars", "working_dir", "py_modules", "pip", "uv", "conda",
        "container", "image_uri",
    }
    if unknown:
        raise ValueError(f"unknown runtime_env keys: {sorted(unknown)}")
    env: Dict[str, str] = dict(runtime_env.get("env_vars") or {})
    for k, v in env.items():
        if not isinstance(k, str) or not isinstance(v, str):
            raise TypeError("runtime_env['env_vars'] must be Dict[str, str]")
    wd = runtime_env.get("working_dir")
    if wd:
        env[WORKING_DIR_ENV] = package_path(wd)
    mods: List[str] = []
    for m in runtime_env.get("py_modules") or []:
        mods.append(package_path(m))
    if mods:
        env[PY_MODULES_ENV] = json.dumps(mods)
    # "uv" shares the venv path (the reference's uv plugin mirrors pip's
    # contract; the installer binary differs, which we don't ship).
    pip_spec = runtime_env.get("pip") or runtime_env.get("uv")
    if pip_spec:
        env[VENV_PY_ENV] = build_pip_env(pip_spec)
    conda_spec = runtime_env.get("conda")
    if conda_spec:
        if pip_spec:
            raise ValueError(
                "runtime_env cannot combine 'conda' with 'pip'/'uv' — the "
                "conda env owns the interpreter (put pip deps in the conda "
                "spec's dependencies)"
            )
        env[VENV_PY_ENV] = build_conda_env(conda_spec)
    container_spec = runtime_env.get("container")
    if runtime_env.get("image_uri"):
        if container_spec:
            raise ValueError(
                "runtime_env cannot combine 'container' with 'image_uri' "
                "(image_uri is shorthand for container={'image': ...})"
            )
        container_spec = {"image": runtime_env["image_uri"]}
    if container_spec:
        if pip_spec or conda_spec:
            raise ValueError(
                "runtime_env cannot combine 'container' with 'pip'/'uv'/"
                "'conda' — the image owns the interpreter (bake deps into "
                "the image)"
            )
        env[CONTAINER_ENV] = resolve_container_spec(container_spec)
    return env


def apply_runtime_env_in_worker() -> None:
    """Worker side: chdir into the staged working_dir, extend sys.path."""
    wd = os.environ.get(WORKING_DIR_ENV)
    if wd and os.path.isdir(wd):
        os.chdir(wd)
        if wd not in sys.path:
            sys.path.insert(0, wd)
    mods = os.environ.get(PY_MODULES_ENV)
    if mods:
        for m in json.loads(mods):
            # m is <cache>/<digest>/<module-name>; importing needs the parent.
            parent = os.path.dirname(m)
            if parent not in sys.path:
                sys.path.insert(0, parent)
