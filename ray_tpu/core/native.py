"""ctypes binding to the C++ native data plane (src/native/rtpu_store.cc).

The native library provides the node-local shared-memory arena object store
(plasma analog, ray ``src/ray/object_manager/plasma/``) and mutable-object
channels (ray ``src/ray/core_worker/experimental_mutable_object_manager.h``).
It is built on first use via the Makefile; if no toolchain is present the
callers fall back to the pure-Python shm path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# Overridable so the stress harness can load sanitizer builds
# (librtpu_native_{asan,tsan}.so; see src/native/Makefile).
_LIB_PATH = os.environ.get("RAY_TPU_NATIVE_LIB") or os.path.join(
    _REPO_ROOT, "build", "librtpu_native.so"
)
_SRC_DIR = os.path.join(_REPO_ROOT, "src", "native")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False


def _build() -> bool:
    """Build the library under an flock: concurrent first-use from several
    processes (driver, agent, workers) must not interleave writes to the
    same .so."""
    try:
        os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
        import fcntl

        with open(os.path.join(os.path.dirname(_LIB_PATH), ".build.lock"), "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            if _stale():
                # Build to a temp path and rename atomically: overwriting
                # the .so in place would truncate a library other live
                # processes have dlopen'd (SIGBUS on their next page fault).
                tmp = _LIB_PATH + f".tmp.{os.getpid()}"
                subprocess.run(
                    ["make", "-C", _SRC_DIR, f"TARGET={tmp}"],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp, _LIB_PATH)
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def _stale() -> bool:
    """True if the .so is missing or older than any native source file."""
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    try:
        for name in os.listdir(_SRC_DIR):
            if name.endswith((".cc", ".h")):
                if os.path.getmtime(os.path.join(_SRC_DIR, name)) > lib_mtime:
                    return True
    except OSError:
        pass
    return False


def _declare(lib):
    u64, i64, u32 = ctypes.c_uint64, ctypes.c_int64, ctypes.c_uint32
    p = ctypes.c_void_p
    cp = ctypes.c_char_p
    u8p = ctypes.POINTER(ctypes.c_uint8)
    sigs = {
        "rtpu_arena_create": (p, [cp, u64, u64]),
        "rtpu_arena_create2": (p, [cp, u64, u64, ctypes.c_int]),
        "rtpu_arena_create3": (p, [cp, u64, u64, ctypes.c_int, ctypes.c_int]),
        "rtpu_arena_attach": (p, [cp]),
        "rtpu_arena_close": (None, [p]),
        "rtpu_arena_base": (ctypes.c_void_p, [p]),
        "rtpu_arena_capacity": (u64, [p]),
        "rtpu_arena_used": (u64, [p]),
        "rtpu_arena_live": (u64, [p]),
        "rtpu_memcpy_nt": (None, [p, p, u64]),
        "rtpu_arena_lock": (None, [p]),
        "rtpu_arena_unlock": (None, [p]),
        "rtpu_alloc": (u64, [p, cp, u64]),
        "rtpu_seal": (ctypes.c_int, [p, cp]),
        "rtpu_lookup": (ctypes.c_int, [p, cp, ctypes.POINTER(u64), ctypes.POINTER(u64)]),
        "rtpu_acquire": (ctypes.c_int, [p, cp, ctypes.POINTER(u64), ctypes.POINTER(u64)]),
        "rtpu_release_ref": (ctypes.c_int, [p, cp]),
        "rtpu_delete": (ctypes.c_int, [p, cp]),
        "rtpu_evict_lru": (u64, [p, u64, cp, u64, u8p, u64]),
        "rtpu_chan_create": (p, [cp, u64, u64]),
        "rtpu_chan_attach": (p, [cp]),
        "rtpu_chan_close": (None, [p]),
        "rtpu_chan_buf": (ctypes.c_void_p, [p]),
        "rtpu_chan_capacity": (u64, [p]),
        "rtpu_chan_write_begin": (ctypes.c_int, [p, i64]),
        "rtpu_chan_write_end": (ctypes.c_int, [p, u64, u32]),
        "rtpu_chan_read_begin": (i64, [p, u64, ctypes.POINTER(u64), ctypes.POINTER(u32), i64]),
        "rtpu_chan_read_end": (ctypes.c_int, [p]),
        "rtpu_chan_set_closed": (None, [p]),
        "rtpu_chan_is_closed": (ctypes.c_int, [p]),
        "rtpu_frame_pack": (u64, [p, cp, u64, ctypes.POINTER(u64), u32]),
        "rtpu_frame_unpack": (i64, [cp, u64, u64, ctypes.POINTER(u64), u32]),
        "rtpu_frame_pack_batch_head": (None, [p, u64, u32]),
        "rtpu_frame_unpack_batch": (i64, [cp, u64, ctypes.POINTER(u64), u32]),
        "rtpu_sched_create": (p, []),
        "rtpu_sched_destroy": (None, [p]),
        "rtpu_sched_update_node": (
            None,
            [p, u8p, ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(i64),
             ctypes.POINTER(i64), ctypes.c_int32],
        ),
        "rtpu_sched_remove_node": (None, [p, u8p]),
        "rtpu_sched_num_nodes": (ctypes.c_int32, [p]),
        "rtpu_sched_pick_node": (
            ctypes.c_int32,
            [p, ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(i64),
             ctypes.c_int32, i64, i64, u8p, u64, u8p],
        ),
    }
    for name, (res, args) in sigs.items():
        fn = getattr(lib, name)
        fn.restype = res
        fn.argtypes = args


def get_lib():
    """Load (building if needed) the native library, or None."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("RAY_TPU_NATIVE_LIB"):
            # Explicit override (e.g. a sanitizer build): load it verbatim —
            # auto-rebuilding would silently replace it with a default-flags
            # build.
            try:
                lib = ctypes.CDLL(_LIB_PATH)
                _declare(lib)
                _lib = lib
            except (OSError, AttributeError):
                _load_failed = True
            return _lib
        if _stale() and not _build() and not os.path.exists(_LIB_PATH):
            # Rebuild failed AND there is nothing to load.  (A stale .so
            # with a missing toolchain still loads — better old symbols
            # than silently disabling the native plane.)
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            _declare(lib)
            _lib = lib
        except (OSError, AttributeError):
            # AttributeError: a stale .so lacking newly added symbols (and
            # no toolchain to rebuild) — degrade to the Python fallbacks
            # rather than crash every native caller.
            _load_failed = True
    return _lib


def available() -> bool:
    return get_lib() is not None


def memcpy_nt(dst_mv: memoryview, src_mv: memoryview) -> bool:
    """Non-temporal copy of ``src_mv`` into ``dst_mv`` (equal sizes, both
    C-contiguous).  Returns False when the native library is unavailable —
    caller falls back to a plain slice copy."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "rtpu_memcpy_nt"):
        return False
    import numpy as np

    d = np.frombuffer(dst_mv, np.uint8)
    s = np.frombuffer(src_mv, np.uint8)
    lib.rtpu_memcpy_nt(
        ctypes.c_void_p(d.ctypes.data), ctypes.c_void_p(s.ctypes.data),
        s.nbytes,
    )
    return True


def _default_n_slots(capacity: int) -> int:
    # ~1 slot per 4KiB of capacity, but never let the table eat more than
    # 1/8 of the arena (48B/slot).
    return max(64, min(capacity // 4096, capacity // (8 * 48)))


_pin_cls_cache: dict = {}


def _pinned_view(arena: "NativeArena", oid: bytes, address: int, size: int) -> memoryview:
    """A memoryview over the object's payload that owns one reader pin.

    Zero-copy consumers (numpy views reconstructed by pickle5) hold the
    exporting ctypes buffer alive through the buffer protocol; when the last
    view is collected the buffer's finalizer releases the pin — the
    PlasmaBuffer-destructor analog in the reference."""

    cls = _pin_cls_cache.get(size)
    if cls is None:

        def _del(self):
            rel = self.__dict__.get("_release")
            if rel is not None:
                rel()

        cls = type("_PinArr", (ctypes.c_uint8 * size,), {"__del__": _del})
        if len(_pin_cls_cache) < 1024:
            _pin_cls_cache[size] = cls
    arr = cls.from_address(address)
    # Closure also keeps the arena handle alive while views exist.
    arr._release = lambda: arena._release_pin(oid)
    return memoryview(arr).cast("B")


class NativeArena:
    """A node-wide shared-memory arena: object table + allocator, shared by
    every process that attaches.  Payload views are zero-copy memoryviews of
    the single mmap."""

    def __init__(self, handle, lib):
        self._h = handle
        self._lib = lib
        self._local_pins = 0
        base = lib.rtpu_arena_base(handle)
        cap = lib.rtpu_arena_capacity(handle)
        self._buf = (ctypes.c_uint8 * cap).from_address(base)
        self._mv = memoryview(self._buf).cast("B")

    @classmethod
    def create(cls, path: str, capacity: int, n_slots: int = 0) -> "NativeArena":
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        if n_slots <= 0:
            n_slots = _default_n_slots(capacity)
        h = lib.rtpu_arena_create(path.encode(), capacity, n_slots)
        if not h:
            raise OSError(f"failed to create arena at {path}")
        return cls(h, lib)

    @classmethod
    def attach(cls, path: str) -> "NativeArena":
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        h = lib.rtpu_arena_attach(path.encode())
        if not h:
            raise FileNotFoundError(f"no arena at {path}")
        return cls(h, lib)

    @classmethod
    def open_shared(cls, path: str, capacity: int) -> "NativeArena":
        """Attach to the arena at ``path``, creating it exclusively if absent.
        Safe under concurrent callers: exactly one creates; attachers spin
        briefly until the creator publishes the header."""
        import time as _time

        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        deadline = _time.monotonic() + 10.0
        while True:
            if os.path.exists(path):
                h = lib.rtpu_arena_attach(path.encode())
                if h:
                    return cls(h, lib)
            else:
                from .config import GlobalConfig

                h = lib.rtpu_arena_create3(
                    path.encode(), capacity, _default_n_slots(capacity), 1,
                    1 if GlobalConfig.object_store_prefault else 0,
                )
                if h:
                    return cls(h, lib)
            if _time.monotonic() > deadline:
                raise OSError(f"could not open shared arena at {path}")
            _time.sleep(0.01)

    # -- object lifecycle ---------------------------------------------------
    def alloc(self, object_id: bytes, size: int) -> Optional[memoryview]:
        off = self._lib.rtpu_alloc(self._h, object_id, size)
        if off == 0:
            return None
        return self._mv[off : off + size]

    def seal(self, object_id: bytes) -> bool:
        return bool(self._lib.rtpu_seal(self._h, object_id))

    def lookup(self, object_id: bytes) -> Optional[memoryview]:
        """Unpinned peek — only safe for short-lived reads under the caller's
        own lifetime guarantees.  Prefer :meth:`acquire` for anything that
        escapes the current call."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        if not self._lib.rtpu_lookup(self._h, object_id, ctypes.byref(off), ctypes.byref(size)):
            return None
        return self._mv[off.value : off.value + size.value]

    def acquire(self, object_id: bytes) -> Optional[memoryview]:
        """Pinned zero-copy view: the payload cannot be freed or evicted
        until every view (and any numpy array built over it) is collected."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        if not self._lib.rtpu_acquire(self._h, object_id, ctypes.byref(off), ctypes.byref(size)):
            return None
        base = self._lib.rtpu_arena_base(self._h)
        self._local_pins += 1
        return _pinned_view(self, object_id, base + off.value, size.value)

    def _release_pin(self, object_id: bytes):
        if self._h:
            self._lib.rtpu_release_ref(self._h, object_id)
            self._local_pins -= 1

    def contains(self, object_id: bytes) -> bool:
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        return bool(
            self._lib.rtpu_lookup(self._h, object_id, ctypes.byref(off), ctypes.byref(size))
        )

    def delete(self, object_id: bytes) -> bool:
        return bool(self._lib.rtpu_delete(self._h, object_id))

    def evict_lru(self, need_bytes: int, pinned: List[bytes], max_evict: int = 256) -> List[bytes]:
        skip = b"".join(pinned)
        out = (ctypes.c_uint8 * (max_evict * 16))()
        n = self._lib.rtpu_evict_lru(
            self._h, need_bytes, skip, len(pinned), out, max_evict
        )
        raw = bytes(out)
        return [raw[i * 16 : (i + 1) * 16] for i in range(n)]

    # -- stats --------------------------------------------------------------
    @property
    def used(self) -> int:
        return self._lib.rtpu_arena_used(self._h)

    @property
    def capacity(self) -> int:
        return self._lib.rtpu_arena_capacity(self._h)

    @property
    def n_live(self) -> int:
        return self._lib.rtpu_arena_live(self._h)

    def close(self):
        if self._h:
            if self._local_pins > 0:
                # Zero-copy views still alive in this process: leave the
                # mapping in place (reclaimed at process exit) rather than
                # unmapping memory under live readers.
                return
            try:
                self._mv.release()
            except BufferError:
                return
            self._lib.rtpu_arena_close(self._h)
            self._h = None


class NativeChannel:
    """Single-writer N-reader mutable object in shared memory (the substrate
    for compiled-graph channels).  Blocking reads/writes with timeouts; the
    writer overwrites in place once all readers consumed the prior value."""

    CLOSED = -2
    TIMEOUT = -1

    def __init__(self, handle, lib, path: str):
        self._h = handle
        self._lib = lib
        self.path = path
        base = lib.rtpu_chan_buf(handle)
        cap = lib.rtpu_chan_capacity(handle)
        self._buf = (ctypes.c_uint8 * cap).from_address(base)
        self._mv = memoryview(self._buf).cast("B")
        self.capacity = cap
        self._last_version = 0

    @classmethod
    def create(cls, path: str, capacity: int, n_readers: int) -> "NativeChannel":
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        h = lib.rtpu_chan_create(path.encode(), capacity, n_readers)
        if not h:
            raise OSError(f"failed to create channel at {path}")
        return cls(h, lib, path)

    @classmethod
    def attach(cls, path: str) -> "NativeChannel":
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        h = lib.rtpu_chan_attach(path.encode())
        if not h:
            raise FileNotFoundError(f"no channel at {path}")
        return cls(h, lib, path)

    def write(self, payload: bytes, timeout: Optional[float] = None, error: int = 0):
        """Block until readers drained the previous value, then publish."""
        if len(payload) > self.capacity:
            raise ValueError(
                f"payload {len(payload)} exceeds channel capacity {self.capacity}"
            )
        tmo = -1 if timeout is None else int(timeout * 1000)
        rc = self._lib.rtpu_chan_write_begin(self._h, tmo)
        if rc == self.CLOSED:
            raise ChannelClosedError(self.path)
        if rc == self.TIMEOUT:
            raise TimeoutError(f"channel write timed out: {self.path}")
        self._mv[: len(payload)] = payload
        self._lib.rtpu_chan_write_end(self._h, len(payload), error)

    def read(self, timeout: Optional[float] = None) -> Tuple[bytes, int]:
        """Block for the next version; returns (payload, error_flag)."""
        size = ctypes.c_uint64()
        err = ctypes.c_uint32()
        tmo = -1 if timeout is None else int(timeout * 1000)
        v = self._lib.rtpu_chan_read_begin(
            self._h, self._last_version, ctypes.byref(size), ctypes.byref(err), tmo
        )
        if v == self.CLOSED:
            raise ChannelClosedError(self.path)
        if v == self.TIMEOUT:
            raise TimeoutError(f"channel read timed out: {self.path}")
        payload = bytes(self._mv[: size.value])
        self._last_version = v
        self._lib.rtpu_chan_read_end(self._h)
        return payload, err.value

    def close_channel(self):
        """Mark closed, waking all blocked parties (they raise)."""
        self._lib.rtpu_chan_set_closed(self._h)

    @property
    def closed(self) -> bool:
        return bool(self._lib.rtpu_chan_is_closed(self._h))

    def detach(self):
        if self._h:
            self._mv.release()
            self._lib.rtpu_chan_close(self._h)
            self._h = None

    def unlink(self):
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


class ChannelClosedError(RuntimeError):
    pass


class NativeScheduler:
    """ctypes wrapper over the native scheduling core (src/native/
    rtpu_sched.cc — fixed-point resource table + hybrid policy).  Resource
    kind names are interned to int32 ids here (the analog of the
    reference's ResourceID interning)."""

    def __init__(self, lib):
        from .resources import PRECISION

        # rtpu_sched.cc's kPrecision is compiled to 10000; the Python side
        # must agree or the two resource views silently diverge.
        assert PRECISION == 10000, "resources.PRECISION changed; update rtpu_sched.cc"
        self.PRECISION = PRECISION
        self._lib = lib
        self._handle = lib.rtpu_sched_create()
        self._kind_ids = {}

    def _kind(self, name: str) -> int:
        kid = self._kind_ids.get(name)
        if kid is None:
            kid = len(self._kind_ids)
            self._kind_ids[name] = kid
        return kid

    def _vectors(self, amounts: dict):
        n = len(amounts)
        kinds = (ctypes.c_int32 * n)()
        vals = (ctypes.c_int64 * n)()
        for i, (k, v) in enumerate(amounts.items()):
            kinds[i] = self._kind(k)
            vals[i] = int(round(v * self.PRECISION))
        return kinds, vals, n

    def update_node(self, node_id_bytes: bytes, total: dict, available: dict):
        keys = set(total) | set(available)
        n = len(keys)
        kinds = (ctypes.c_int32 * n)()
        totals = (ctypes.c_int64 * n)()
        avails = (ctypes.c_int64 * n)()
        for i, k in enumerate(keys):
            kinds[i] = self._kind(k)
            totals[i] = int(round(total.get(k, 0.0) * self.PRECISION))
            avails[i] = int(round(available.get(k, 0.0) * self.PRECISION))
        buf = (ctypes.c_uint8 * 16).from_buffer_copy(node_id_bytes)
        self._lib.rtpu_sched_update_node(
            self._handle, buf, kinds, totals, avails, n
        )

    def remove_node(self, node_id_bytes: bytes):
        buf = (ctypes.c_uint8 * 16).from_buffer_copy(node_id_bytes)
        self._lib.rtpu_sched_remove_node(self._handle, buf)

    def num_nodes(self) -> int:
        return self._lib.rtpu_sched_num_nodes(self._handle)

    def pick_node(
        self,
        request: dict,
        spread_threshold: float,
        top_k_fraction: float,
        preferred: bytes = None,
        seed: int = 0,
    ):
        """Returns (status, node_id_bytes): status 1 picked, 0 retry later,
        -1 infeasible forever, -2 empty cluster."""
        kinds, vals, n = self._vectors(request)
        out = (ctypes.c_uint8 * 16)()
        pref = (
            (ctypes.c_uint8 * 16).from_buffer_copy(preferred)
            if preferred is not None
            else None
        )
        status = self._lib.rtpu_sched_pick_node(
            self._handle,
            kinds,
            vals,
            n,
            int(spread_threshold * self.PRECISION),
            int(top_k_fraction * self.PRECISION),
            pref,
            seed,
            out,
        )
        return status, bytes(out) if status == 1 else None

    def __del__(self):
        try:
            self._lib.rtpu_sched_destroy(self._handle)
        except Exception:  # raylint: waive[RTL003] GC-time destroy; interpreter may be tearing down
            pass


def make_scheduler():
    """NativeScheduler if the library is available, else None."""
    lib = get_lib()
    return NativeScheduler(lib) if lib is not None else None


# A 1-element char array is enough to hand ctypes the base address of a
# writable bytearray (the C side writes past it into caller-sized space);
# one cached type avoids growing ctypes' per-size array-type cache with
# every distinct frame length.
_CHAR1 = ctypes.c_char * 1


class FrameCodec:
    """ctypes wrapper over the C v2-frame codec (src/native/rtpu_frame.cc).

    Byte-identical to the pure-Python codec in ``core.rpc`` — the C side
    only does the framing arithmetic (meta prefix, buf-len table, offset
    parse); pickling and out-of-band buffer segments stay in Python.
    Scratch tables are thread-local: encode/decode run concurrently on the
    protocol loop, server lanes, and direct-submitting user threads."""

    # Frames with more out-of-band buffers than this (or batches with more
    # sub-frames) fall back to the Python codec — the tables are scratch,
    # not a protocol limit.
    MAX_BUFS = 64
    MAX_SUBS = 2048

    def __init__(self, lib):
        self._lib = lib
        self._tls = threading.local()

    def _scratch(self):
        scr = getattr(self._tls, "scr", None)
        if scr is None:
            scr = self._tls.scr = (
                (ctypes.c_uint64 * (2 + 2 * self.MAX_BUFS))(),  # unpack table
                (ctypes.c_uint64 * (2 * self.MAX_SUBS))(),      # batch table
                (ctypes.c_uint64 * self.MAX_BUFS)(),            # pack buf lens
            )
        return scr

    def pack(self, header: bytes, buf_lens) -> bytearray:
        """The meta segment of a v2 frame: [8B len][tag][hlen][nbufs]
        [buf-len table][header].  Caller appends the buffers as their own
        wire segments.  ``len(buf_lens)`` must be <= MAX_BUFS."""
        nbufs = len(buf_lens)
        meta = bytearray(8 + 9 + 8 * nbufs + len(header))
        if nbufs:
            lens = self._scratch()[2]
            for i, n in enumerate(buf_lens):
                lens[i] = n
        else:
            lens = None
        self._lib.rtpu_frame_pack(
            _CHAR1.from_buffer(meta), header, len(header), lens, nbufs
        )
        return meta

    def pack_batch_head(self, payload_bytes: int, count: int) -> bytearray:
        head = bytearray(13)
        self._lib.rtpu_frame_pack_batch_head(
            _CHAR1.from_buffer(head), payload_bytes, count
        )
        return head

    def unpack(self, body: bytes, off: int, length: int):
        """Parse the frame at ``body[off : off+length]``.  Returns
        ``(nbufs, table)`` — table[0]/[1] = header off/len, then per-buffer
        off/len pairs, all absolute into ``body``; nbufs < 0 means fall
        back to the Python parser (-2) or corrupt framing (-1)."""
        table = self._scratch()[0]
        n = self._lib.rtpu_frame_unpack(body, off, length, table, self.MAX_BUFS)
        return n, table

    def unpack_batch(self, body: bytes):
        """Parse a batch container body.  Returns ``(count, table)`` with
        per-sub-frame off/len pairs (absolute into ``body``); count < 0
        means fall back (-2) or corrupt framing (-1)."""
        table = self._scratch()[1]
        n = self._lib.rtpu_frame_unpack_batch(body, len(body), table, self.MAX_SUBS)
        return n, table


_frame_codec: Optional[FrameCodec] = None


def frame_codec() -> Optional[FrameCodec]:
    """Process-wide FrameCodec over the native library, or None when the
    toolchain/library is unavailable (callers use the Python codec)."""
    global _frame_codec
    if _frame_codec is None:
        lib = get_lib()
        if lib is None:
            return None
        _frame_codec = FrameCodec(lib)
    return _frame_codec
