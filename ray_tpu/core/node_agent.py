"""Per-node agent — the raylet equivalent.

One process per node (Ray ``src/ray/raylet/node_manager.h``).  Owns:
  - the worker pool (spawn/cache/kill worker processes; Ray ``worker_pool.h``)
  - the lease protocol: queue + grant worker leases against local resources,
    spillback to other nodes via the control plane's view
    (Ray ``cluster_lease_manager.h`` / ``local_lease_manager.h``)
  - instance-granular TPU chip accounting → ``TPU_VISIBLE_CHIPS`` isolation
    for leased workers (reference precedent:
    ray ``python/ray/_private/accelerators/tpu.py``)
  - placement-group bundle reservations (2-phase prepare/commit; Ray
    ``node_manager.h:589``)
  - the node object directory for the shm tier + chunked node-to-node object
    pulls (Ray ``object_manager/``)
  - worker lifecycle monitoring; actor-death reporting to the control plane.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from .config import GlobalConfig
from .ids import ActorID, NodeID, ObjectID, PlacementGroupID, WorkerID
from .object_store import NodeObjectDirectory, ShmObjectStore
from .resources import NodeResources, ResourceInstanceSet, ResourceSet
from .rpc import ClientPool, RetryableRpcClient, RpcServer, resolve_service_lanes
from .task_spec import ActorSpec
from ..util.metric_registry import (
    LEASE_GRANT_WAIT_HIST,
    LEASE_QUEUE_DEPTH,
    LEASES_HELD,
)

logger = logging.getLogger(__name__)


def _ignore_usr1():
    """preexec_fn: SIGUSR1 → SIG_IGN before exec.  Ignored dispositions
    survive exec (handlers don't), so a `ray-tpu stack` signal landing
    during the child's import phase — before the loop installs the real
    dump handler — is dropped instead of killing the starting worker."""
    import signal as _signal

    _signal.signal(_signal.SIGUSR1, _signal.SIG_IGN)


def _sched_idle():
    """preexec_fn: run the child under SCHED_IDLE (falls back to nice 19
    where unavailable) so prestart imports only use otherwise-idle CPU."""
    _ignore_usr1()
    try:
        os.sched_setscheduler(0, os.SCHED_IDLE, os.sched_param(0))
    except Exception:  # noqa: BLE001
        try:
            os.nice(19)
        except Exception:  # raylint: waive[RTL003] no further fallback below nice(19)
            pass


class WorkerHandle:
    def __init__(self, worker_id: WorkerID, proc: subprocess.Popen, env_key: tuple):
        self.worker_id = worker_id
        self.proc = proc
        self.env_key = env_key  # pool key: (tpu_chips_tuple, extra_env_items)
        self.address: Optional[str] = None
        self.ready = asyncio.Event()
        self.leased = False
        self.is_actor = False
        self.actor_id: Optional[ActorID] = None
        self.last_idle = time.monotonic()


class Lease:
    def __init__(self, lease_id: int, worker: WorkerHandle, resources: ResourceSet,
                 instances: Dict[str, List[int]], pg_id: Optional[PlacementGroupID],
                 bundle_index: int, is_actor: bool = False):
        self.lease_id = lease_id
        self.worker = worker
        self.resources = resources
        self.instances = instances
        self.pg_id = pg_id
        self.bundle_index = bundle_index
        self.is_actor = is_actor
        self.retriable = not is_actor  # refined from the lease request
        self.start_ts = time.monotonic()


class BundlePool:
    """Resources reserved for one placement-group bundle on this node."""

    def __init__(self, spec: Dict[str, float]):
        self.total = ResourceSet(spec)
        self.available = ResourceSet(spec)
        self.committed = False


class NodeAgent:
    # Read-only probes the multi-lane RPC server may run on a lane thread
    # (see rpc.RpcServer).  The agent's stateful paths — leases, bundle
    # pools, worker lifecycle, pulls — keep their single-loop semantics by
    # forwarding; lanes still isolate per-connection framing/serialization.
    LANE_SAFE_METHODS = frozenset({"ping", "object_info"})

    def __init__(
        self,
        host: str,
        port: int,
        cp_address: str,
        session_id: str,
        resources: Dict[str, float],
        labels: Dict[str, str],
        node_id: Optional[NodeID] = None,
        cp_ha_dir: Optional[str] = None,
    ):
        self.node_id = node_id or NodeID.from_random()
        self.session_id = session_id
        self.cp_address = cp_address
        self.cp_ha_dir = cp_ha_dir
        self.server = RpcServer(self, host, port, lanes=resolve_service_lanes())
        # With HA, every reconnect re-resolves the published leader
        # endpoint — failover re-anchoring IS the plain reconnect path
        # (heartbeat's "reregister" reply then replays node state).
        resolver = None
        if cp_ha_dir:
            from .cp_ha import make_cp_resolver

            resolver = make_cp_resolver(cp_ha_dir, cp_address)
        self.cp_client = RetryableRpcClient(
            cp_address, address_resolver=resolver
        )
        self.agent_clients = ClientPool()  # peers, for remote pulls
        self.worker_clients = ClientPool()  # local workers (actor_init etc.)
        self.resources = NodeResources(resources, labels)
        self.instances = ResourceInstanceSet(resources)
        self.directory = NodeObjectDirectory(
            session_id, GlobalConfig.object_store_memory_bytes
        )
        # The agent is the session arena's creator; every other process
        # (workers, drivers) attaches only — see get_arena's leak note.
        self.shm_store = ShmObjectStore(session_id, create_arena=True)
        self.workers: Dict[WorkerID, WorkerHandle] = {}
        self.idle_pool: Dict[tuple, List[WorkerHandle]] = {}
        # cgroup-v2 isolation of application workers (no-op unless
        # enable_resource_isolation and a writable cgroup mount).
        from .cgroup import WorkerIsolation

        self.isolation = WorkerIsolation(
            session_id,
            memory_limit_bytes=(
                GlobalConfig.worker_cgroup_memory_limit_bytes or None
            ),
        )
        self.leases: Dict[int, Lease] = {}
        self._next_lease_id = 1
        self.bundles: Dict[Tuple[PlacementGroupID, int], BundlePool] = {}
        self._lease_queue: List[tuple] = []  # (payload, future)
        # Stable lease ownership: owner_id -> latest live connection, and
        # pending grace-reap timers for owners whose conn dropped.
        self._owner_conns: Dict[str, Any] = {}
        self._owner_reap_timers: Dict[str, Any] = {}
        self._idle_since = None  # monotonic ts when node went fully idle
        self._pull_futures: Dict[ObjectID, asyncio.Future] = {}
        # Frees observed while a pull of the same oid is in flight: the
        # pull's post-await seal would otherwise re-register a dead oid
        # (same hazard handle_seal_object guards against) and leak its
        # directory accounting + storage forever.
        self._freed_during_pull: set = set()
        self._prestart_task: Optional[asyncio.Task] = None
        self._last_pop = 0.0  # monotonic ts of last default-pool pop
        self._pool_miss_at = 0.0  # monotonic ts of last EMPTY-pool pop
        self._prestart_inflight: set = set()  # spawning prestart handles
        self._prestart_first = True  # initial fill runs hot (see loop)
        self._prestart_hot_until = 0.0  # forced-hot deadline (prestart_pool)
        # Pool key of a plain CPU-only lease (chip isolation applied to an
        # empty chip set) — constant per process; prestarted workers carry
        # exactly this env so they match ordinary task/actor leases.
        env: Dict[str, str] = {}
        self._apply_chip_isolation(env, {})
        self._default_env = env
        self._default_env_key = tuple(sorted(env.items()))
        self._bg: List[asyncio.Task] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # Observability aggregator counters (pull rides the heartbeat —
        # by design there is NO separate periodic loop for it; a test
        # pins that via the _bg task list in debug_state).
        self._obs_rounds = 0
        self._obs_events_forwarded = 0
        self._obs_workers_pulled = 0
        # batch-id acks per worker: sent with the next pull only AFTER a
        # successful obs_report, so workers re-deliver un-forwarded
        # batches instead of losing them (at-least-once).
        self._obs_acks: Dict[str, int] = {}

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> str:
        addr = await self.server.start()
        reply = await self.cp_client.call(
            "register_node",
            {
                "node_id": self.node_id,
                "agent_address": addr,
                "snapshot": self._snapshot(),
                "held_pgs": self._held_pg_ids(),
            },
        )
        assert reply["ok"]
        self._drop_stale_pgs(reply.get("drop_pgs"))
        loop = asyncio.get_running_loop()
        # The agent has no CoreWorker, so its flight-recorder metrics
        # (object directory, lease waits) reach the cluster registry via a
        # custom flush hook; the heartbeat loop forces a push each period.
        self._loop = loop
        from ..util import metrics as _metrics

        _metrics.set_flush_hook(self._push_metrics_payload)
        self._bg.append(loop.create_task(self._heartbeat_loop()))
        self._bg.append(loop.create_task(self._monitor_workers_loop()))
        if GlobalConfig.memory_monitor_period_s > 0:
            self._bg.append(loop.create_task(self._memory_monitor_loop()))
        self._replenish_pool()
        logger.info("node agent %s on %s", self.node_id.hex()[:8], addr)
        return addr

    async def _memory_monitor_loop(self):
        """OOM defense (reference: MemoryMonitor + WorkerKillingPolicy):
        when node memory crosses the threshold, kill the newest retriable
        lease's worker — the submitter's retry machinery resubmits it."""
        from .memory_monitor import MemoryMonitor, system_memory_fraction

        fake_file = GlobalConfig.memory_monitor_fake_usage_file

        def usage_reader() -> float:
            if fake_file:  # chaos/testing hook
                try:
                    with open(fake_file) as f:
                        return float(f.read().strip())
                except (OSError, ValueError):
                    return 0.0
            return system_memory_fraction()

        monitor = MemoryMonitor(
            GlobalConfig.memory_monitor_threshold, usage_reader
        )
        self.memory_monitor = monitor
        period = GlobalConfig.memory_monitor_period_s
        while True:
            await asyncio.sleep(period)
            try:
                victims = [
                    {
                        "lease_id": lid,
                        "start_ts": lease.start_ts,
                        "retriable": lease.retriable and not lease.is_actor,
                        "is_actor": lease.is_actor,
                    }
                    for lid, lease in self.leases.items()
                ]
                picked = monitor.check(victims)
                if picked is not None:
                    lease = self.leases.get(picked[0])
                    if lease is not None:
                        self._kill_worker_proc(lease.worker)
            except Exception as e:  # noqa: BLE001
                logger.warning("memory monitor round failed: %s", e)

    def _push_metrics_payload(self, payload: dict):
        """metrics flush hook: ship this agent process's registry to the
        control-plane KV.  Must be callable from any thread (the directory's
        spill thread records counters) and never raise."""
        async def push():
            try:
                await self.cp_client.call(
                    "kv_put",
                    {"namespace": "metrics",
                     "key": f"agent:{self.node_id.hex()}",
                     "value": payload, "overwrite": True},
                    retries=1,
                )
            except Exception:  # raylint: waive[RTL003] metrics are best-effort
                pass

        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        try:
            if running is self._loop:
                running.create_task(push())
            elif self._loop is not None:
                asyncio.run_coroutine_threadsafe(push(), self._loop)
        except RuntimeError:
            pass  # loop tearing down

    async def stop(self):
        from ..util import metrics as _metrics

        _metrics.clear_flush_hook(self._push_metrics_payload)
        if self._prestart_task is not None:
            self._prestart_task.cancel()
        for t in self._bg:
            t.cancel()
        for w in self.workers.values():
            self._kill_worker_proc(w)
        self.isolation.cleanup()
        self.directory.cleanup()
        await self.server.stop()
        await self.cp_client.close()
        await self.agent_clients.close_all()
        await self.worker_clients.close_all()

    def _snapshot(self) -> dict:
        # Idle tracking + queued lease demands feed the autoscaler's load
        # state (reference: resource-demand fields in the raylet's resource
        # report consumed by GcsAutoscalerStateManager).
        pending = [
            dict(payload.get("resources") or {})
            for payload, fut, _conn in self._lease_queue
            if not fut.done()
        ]
        busy = bool(pending) or (
            self.resources.available.to_dict() != self.resources.total.to_dict()
        )
        if busy:
            self._idle_since = None
        elif self._idle_since is None:
            self._idle_since = time.monotonic()
        return {
            "total": self.resources.total.to_dict(),
            "available": self.resources.available.to_dict(),
            "labels": dict(self.resources.labels),
            "pending_demands": pending,
            "idle_s": (
                time.monotonic() - self._idle_since
                if self._idle_since is not None
                else 0.0
            ),
        }

    async def _heartbeat_loop(self):
        from ..util import flight_recorder as fr
        from ..util import metrics as _metrics

        period = GlobalConfig.health_check_period_s
        while True:
            try:
                # Flight-recorder gauges ride the heartbeat cadence (off
                # every hot path), then the registry is force-pushed
                # through the agent's flush hook.
                if fr.enabled():
                    self.directory.record_telemetry()
                    fr.gauge(LEASE_QUEUE_DEPTH, len(self._lease_queue))
                    fr.gauge(LEASES_HELD, len(self.leases))
                    fr.record_rpc_lanes(self.server, role="node_agent")
                    _metrics.flush()
            except Exception:  # raylint: waive[RTL003] telemetry must not kill heartbeat
                pass
            try:
                # Observability aggregation rides the SAME cadence: pull
                # every local worker's span/task-event/metric deltas and
                # forward one merged obs_report — no extra periodic RPC.
                if GlobalConfig.enable_obs_aggregator:
                    await self._obs_pull_round()
            except Exception:  # raylint: waive[RTL003] telemetry must not kill heartbeat
                pass
            try:
                reply = await self.cp_client.call(
                    "heartbeat",
                    {"node_id": self.node_id, "snapshot": self._snapshot()},
                    retries=1,
                )
                if reply.get("reregister"):
                    rereg = await self.cp_client.call(
                        "register_node",
                        {
                            "node_id": self.node_id,
                            "agent_address": self.server.address,
                            "snapshot": self._snapshot(),
                            "held_pgs": self._held_pg_ids(),
                        },
                    )
                    self._drop_stale_pgs(rereg.get("drop_pgs"))
            except Exception as e:
                logger.debug("heartbeat send failed: %s", e)
            await asyncio.sleep(period)

    async def _obs_pull_round(self):
        """One aggregator round: drain each ready local worker's
        observability buffers (obs_pull) and ship the merged batches to
        the control plane as one obs_report.  Per-worker failures are
        isolated — a dying worker must not cost the node its telemetry."""
        self._obs_rounds += 1
        timeout = max(1.0, GlobalConfig.health_check_period_s)

        async def pull_one(handle):
            if handle.address is None or handle.proc.poll() is not None:
                return None
            wid = handle.worker_id.hex()
            try:
                return await self.worker_clients.get(handle.address).call(
                    "obs_pull", {"ack": self._obs_acks.get(wid)},
                    timeout=timeout,
                )
            except Exception:  # noqa: BLE001 — worker may be mid-exit
                # Nothing is lost: the worker staged the reply and will
                # re-deliver it on the next (un-acked) pull.
                from ..util import flight_recorder as fr

                fr.count_suppressed("obs_pull")
                return None

        handles = list(self.workers.values())
        replies = await asyncio.gather(*(pull_one(h) for h in handles))
        live = {h.worker_id.hex() for h in handles}
        for wid in [w for w in self._obs_acks if w not in live]:
            del self._obs_acks[wid]
        batches = [
            b for b in replies
            if b and (b.get("events") or b.get("profile_events")
                      or b.get("metrics") or b.get("span_drops"))
        ]
        self._obs_workers_pulled += sum(1 for b in replies if b)
        if not batches:
            return
        n_events = sum(
            len(b.get("events") or ()) + len(b.get("profile_events") or ())
            for b in batches
        )
        try:
            await self.cp_client.call(
                "obs_report",
                {"node_id": self.node_id.hex(), "batches": batches},
                retries=1,
            )
        except Exception as e:  # noqa: BLE001 — workers re-deliver un-acked batches
            logger.debug("obs_report failed (will re-pull): %s", e)
            return
        self._obs_events_forwarded += n_events
        for b in batches:
            if b.get("batch_id") is not None and b.get("worker_id"):
                self._obs_acks[b["worker_id"]] = b["batch_id"]

    # --------------------------------------------------------------- workers
    def _spawn_worker(
        self, env_extra: Dict[str, str], env_key: tuple, nice: bool = False
    ) -> WorkerHandle:
        worker_id = WorkerID.from_random()
        env = dict(os.environ)
        env.update(env_extra)
        env.update(
            RAY_TPU_WORKER_ID=worker_id.hex(),
            RAY_TPU_AGENT_ADDRESS=self.server.address,
            # The leader may have moved since this agent started: point
            # new workers at the client's CURRENT resolved address.
            RAY_TPU_CP_ADDRESS=self.cp_client.address,
            RAY_TPU_SESSION_ID=self.session_id,
            RAY_TPU_NODE_ID=self.node_id.hex(),
            # Log lines (and crash dumps) must reach the file when they
            # happen, not when a block-buffered stdio flushes — a killed
            # worker would otherwise leave an empty log.
            PYTHONUNBUFFERED="1",
        )
        if self.cp_ha_dir:
            env["RAY_TPU_CP_HA_DIR"] = self.cp_ha_dir
        log_dir = os.environ.get("RAY_TPU_LOG_DIR", "/tmp/ray_tpu")
        os.makedirs(log_dir, exist_ok=True)
        out = open(os.path.join(log_dir, f"worker-{worker_id.hex()[:12]}.log"), "ab")
        # A pip runtime env runs the worker under its venv's interpreter
        # (reference: per-env virtualenv workers, _private/runtime_env/pip.py).
        python = env.get("RAY_TPU_RT_VENV_PY") or sys.executable
        argv = [python, "-m", "ray_tpu.core.worker_main"]
        container = env.get("RAY_TPU_RT_CONTAINER")
        if container:
            # Container runtime env: the worker command runs inside
            # podman/docker with host network/pid/ipc (reference:
            # _private/runtime_env/image_uri.py).
            from .runtime_env import container_argv

            argv = container_argv(container, env, argv)
        proc = subprocess.Popen(
            argv,
            env=env,
            stdout=out,
            stderr=subprocess.STDOUT,
            start_new_session=True,
            # Prestarted workers import under SCHED_IDLE so pool refill
            # only uses CPU nothing else wants; _prestart_loop restores
            # SCHED_OTHER once the worker registers (before pooling).
            # Both paths ignore SIGUSR1 until the real dump handler is
            # installed (see _ignore_usr1).
            preexec_fn=_sched_idle if nice else _ignore_usr1,
        )
        handle = WorkerHandle(worker_id, proc, env_key)
        self.isolation.attach_worker(proc.pid)
        self.workers[worker_id] = handle
        return handle

    def handle_register_worker(self, payload, conn):
        worker_id = payload["worker_id"]
        handle = self.workers.get(worker_id)
        if handle is None:
            return {"ok": False}
        handle.address = payload["address"]
        handle.ready.set()
        conn.metadata["worker_id"] = worker_id
        return {"ok": True}

    def _pool_floor(self) -> int:
        """Target number of idle default-env workers kept warm.

        Reference: ``WorkerPool::PrestartWorkers`` keeps pre-started
        workers around so tasks AND actor creations skip the interpreter
        cold start (ray ``src/ray/raylet/worker_pool.h:281``).
        ``prestart_workers``: 0 disables, N>0 is an explicit floor, -1
        auto-sizes to the node's CPU count.
        """
        n = GlobalConfig.prestart_workers
        if n < 0:
            n = int(self.resources.total.get("CPU"))
        return n

    def _replenish_pool(self):
        """Kick the background prestart loop toward the pool floor.

        Fired at agent start and whenever a pooled worker is consumed or
        dies.  Actual spawning is debounced and serialized in
        ``_prestart_loop`` so replenishment never competes with a live
        creation burst for CPU (interpreter startup is ~0.4s of pure
        import work per worker)."""
        if self._pool_floor() <= 0:
            return
        if self._prestart_task is None or self._prestart_task.done():
            self._prestart_task = asyncio.get_running_loop().create_task(
                self._prestart_loop()
            )

    # Hot-demand window: a pop that found the pool EMPTY within this many
    # seconds means demand is outrunning supply — refills must run at
    # normal priority (SCHED_IDLE imports starve completely on a busy
    # core) and in parallel, or a creation burst cold-starts every worker.
    _PRESTART_HOT_WINDOW_S = 5.0
    _PRESTART_HOT_BATCH = 4

    async def _prestart_loop(self):
        key = self._default_env_key
        while True:
            # Task-leased default-env workers count toward the floor: on a
            # saturated node every slot is busy doing real work, spawning
            # "replacements" would only steal CPU from it, and task leases
            # RETURN their workers to the pool.  Actor-held workers do not
            # count — an actor keeps its process until death, so its pool
            # slot is genuinely consumed and must be refilled.
            have = len(self.idle_pool.get(key, [])) + sum(
                1 for h in self.workers.values()
                if h.leased and not h.is_actor and h.env_key == key
            ) + len(self._prestart_inflight)
            deficit = self._pool_floor() - have
            if deficit <= 0:
                # Fill complete: close any forced-hot window so post-fill
                # refills (e.g. during a measured creation burst) drop
                # back to polite SCHED_IDLE mode.
                self._prestart_hot_until = 0.0
                return
            now = time.monotonic()
            hot = (
                self._prestart_first
                or now < self._prestart_hot_until
                or now - self._pool_miss_at < self._PRESTART_HOT_WINDOW_S
            )
            if not hot:
                quiet = time.monotonic() - self._last_pop
                if quiet < 0.5:
                    await asyncio.sleep(0.5 - quiet)
                    continue
            if GlobalConfig.memory_monitor_period_s > 0:
                # Don't refill the pool while the OOM defense is shedding
                # memory — fresh interpreters would re-consume what the
                # kill policy just freed.
                from .memory_monitor import system_memory_fraction

                if system_memory_fraction() > GlobalConfig.memory_monitor_threshold:
                    await asyncio.sleep(1.0)
                    continue
            batch = min(deficit, self._PRESTART_HOT_BATCH if hot else 1)
            handles = []
            spawn_failed = False
            for _ in range(batch):
                # A mid-batch spawn failure (EMFILE, fork failure) must
                # not strand the already-spawned handles in
                # _prestart_inflight — finish() below is what discards
                # them — or the inflated `have` count would disable
                # refill permanently.
                try:
                    h = self._spawn_worker(
                        dict(self._default_env), key, nice=not hot
                    )
                except Exception:  # noqa: BLE001 — spawn is best-effort
                    spawn_failed = True
                    break
                self._prestart_inflight.add(h)
                handles.append(h)

            async def finish(handle):
                try:
                    await self._wait_worker_ready(handle)
                    # Only the interpreter-import phase may ride
                    # SCHED_IDLE; a registered idle worker must run at
                    # normal priority or a busy box starves its
                    # agent-liveness pings and the watchdog kills it.
                    try:
                        os.sched_setscheduler(
                            handle.proc.pid, os.SCHED_OTHER,
                            os.sched_param(0),
                        )
                    except Exception:  # raylint: waive[RTL003] sched boost is a nicety; proc may have exited
                        pass
                    if handle.proc.poll() is None and not handle.leased:
                        self.idle_pool.setdefault(key, []).append(handle)
                except Exception:  # noqa: BLE001 — prestart is best-effort
                    self._kill_worker_proc(handle)
                    await asyncio.sleep(1.0)
                finally:
                    self._prestart_inflight.discard(handle)

            await asyncio.gather(*(finish(h) for h in handles))
            if spawn_failed:
                await asyncio.sleep(1.0)  # back off before retrying spawns
            self._prestart_first = False

    async def _wait_worker_ready(self, handle: WorkerHandle):
        """Wait until the worker registers; fail fast if its process dies
        first (an import-time crash must not cost the full startup
        timeout)."""
        deadline = time.monotonic() + GlobalConfig.worker_startup_timeout_s
        while True:
            try:
                await asyncio.wait_for(handle.ready.wait(), timeout=0.2)
                return
            except asyncio.TimeoutError:
                code = handle.proc.poll()
                if code is not None:
                    raise RuntimeError(
                        f"worker exited with code {code} before registering"
                    )
                if time.monotonic() > deadline:
                    raise asyncio.TimeoutError(
                        "worker did not register within "
                        f"{GlobalConfig.worker_startup_timeout_s}s"
                    )

    async def _pop_worker(self, env_extra: Dict[str, str]) -> WorkerHandle:
        env_key = tuple(sorted(env_extra.items()))
        pool = self.idle_pool.get(env_key)
        handle = None
        while pool:
            h = pool.pop()
            if h.proc.poll() is None:
                handle = h
                break
        if env_key == self._default_env_key:
            self._last_pop = time.monotonic()
            if handle is None:
                # Demand outran supply: flip the prestart loop into hot
                # mode and promote any SCHED_IDLE spawns already in
                # flight (a niced import never finishes on a busy core).
                self._pool_miss_at = self._last_pop
                for h in self._prestart_inflight:
                    try:
                        os.sched_setscheduler(
                            h.proc.pid, os.SCHED_OTHER, os.sched_param(0)
                        )
                    except Exception:  # raylint: waive[RTL003] sched boost is a nicety; proc may have exited
                        pass
            self._replenish_pool()
        if handle is None:
            handle = self._spawn_worker(env_extra, env_key)
            handle.leased = True
            try:
                await self._wait_worker_ready(handle)
            except Exception:
                # Kill the half-started interpreter — nothing else tracks
                # it (the monitor only reaps procs that already exited).
                self._kill_worker_proc(handle)
                raise
            return handle
        handle.leased = True
        return handle

    def _return_worker(self, handle: WorkerHandle):
        handle.leased = False
        handle.last_idle = time.monotonic()
        if handle.proc.poll() is None and not handle.is_actor:
            self.idle_pool.setdefault(handle.env_key, []).append(handle)

    def _kill_worker_proc(self, handle: WorkerHandle):
        try:
            if handle.proc.poll() is None:
                handle.proc.terminate()
        except Exception as e:
            logger.debug("worker terminate failed: %s", e)

    async def _monitor_workers_loop(self):
        while True:
            await asyncio.sleep(0.5)
            for worker_id, handle in list(self.workers.items()):
                if handle.proc.poll() is not None:
                    del self.workers[worker_id]
                    if handle.address is not None:
                        await self.worker_clients.close(handle.address)
                    pool = self.idle_pool.get(handle.env_key)
                    if pool and handle in pool:
                        pool.remove(handle)
                    if handle.env_key == self._default_env_key:
                        self._replenish_pool()
                    # Release any lease held by this worker.
                    for lease_id, lease in list(self.leases.items()):
                        if lease.worker is handle:
                            self._release_lease(lease_id)
                    if handle.is_actor and handle.actor_id is not None:
                        try:
                            await self.cp_client.call(
                                "actor_worker_died",
                                {
                                    "actor_id": handle.actor_id,
                                    "cause": f"worker exited with code "
                                    f"{handle.proc.returncode}",
                                },
                                retries=2,
                            )
                        except Exception as e:
                            logger.warning("actor-death notify failed: %s", e)

    async def handle_kill_worker(self, payload, conn):
        for handle in self.workers.values():
            if handle.address == payload["worker_address"]:
                handle.is_actor = False  # suppress death report: intentional
                handle.actor_id = None
                self._kill_worker_proc(handle)
                return True
        return False

    # ---------------------------------------------------------------- leases
    def _resource_pool(self, pg_id, bundle_index, resources: Optional[ResourceSet] = None):
        """Resolve the PG bundle pool a lease draws from (None = node pool).
        For the wildcard index (-1), picks the lowest-indexed bundle of the
        group that can actually fit ``resources`` right now."""
        if pg_id is None:
            return None
        pool = self.bundles.get((pg_id, bundle_index))
        if pool is None and bundle_index == -1:
            fallback = None
            for (pid, _bi), p in sorted(
                self.bundles.items(), key=lambda kv: kv[0][1]
            ):
                if pid != pg_id:
                    continue
                if fallback is None:
                    fallback = p
                if resources is None or resources.is_subset_of(p.available):
                    return p
            return fallback  # all full: caller re-queues against this one
        return pool

    async def handle_request_lease(self, payload, conn):
        """Grant a worker lease, queue it, or reply with a spillback target."""
        t0 = time.monotonic()
        fut = asyncio.get_running_loop().create_future()
        self._lease_queue.append((payload, fut, conn))
        self._drain_lease_queue()
        reply = await fut
        from ..util import flight_recorder as fr

        if reply.get("granted"):
            result = "granted"
        elif reply.get("spillback"):
            result = "spillback"
        else:
            result = "retry"  # infeasible right now; requester re-asks
        fr.histogram(
            LEASE_GRANT_WAIT_HIST, time.monotonic() - t0,
            {"result": result},
        )
        return reply

    def _drain_lease_queue(self):
        still_waiting = []
        for payload, fut, conn in self._lease_queue:
            if fut.done():
                continue
            granted = self._try_grant(payload, fut, conn)
            if not granted:
                still_waiting.append((payload, fut, conn))
        self._lease_queue = still_waiting

    def _try_grant(self, payload, fut, conn=None) -> bool:
        resources = ResourceSet(payload.get("resources") or {})
        pg_id = payload.get("placement_group_id")
        bundle_index = payload.get("bundle_index", -1)
        bundle = self._resource_pool(pg_id, bundle_index, resources)
        if pg_id is not None:
            if bundle is None:
                # The bundle lives on another node (or the PG is still
                # pending): ask the control plane for the bundle's node and
                # spill the lease there instead of failing the task.
                asyncio.get_running_loop().create_task(
                    self._spillback(payload, fut, resources)
                )
                return True
            if not resources.is_subset_of(bundle.available):
                return False
            bundle.available = bundle.available - resources
        else:
            if not self.resources.could_ever_fit(resources):
                asyncio.get_running_loop().create_task(
                    self._spillback(payload, fut, resources)
                )
                return True
            if not self.resources.acquire(resources):
                return False
        instances = self._acquire_instances(resources)
        if instances is None:
            # Accounting says the amount fits but chip instances are too
            # fragmented right now — undo and stay queued.
            if bundle is not None:
                bundle.available = bundle.available + resources
            else:
                self.resources.release(resources)
            return False
        asyncio.get_running_loop().create_task(
            self._finish_grant(
                payload, fut, resources, instances, pg_id, bundle_index, conn
            )
        )
        return True

    def _acquire_instances(self, resources: ResourceSet) -> Optional[Dict[str, List[int]]]:
        """Returns granted instance ids per unit resource, or None if any
        requested unit resource can't be instance-assigned (never grant a
        TPU lease without chip isolation)."""
        instances: Dict[str, List[int]] = {}
        acquired: List[tuple] = []
        for name in ResourceInstanceSet.UNIT_RESOURCES:
            amount = resources.get(name)
            if amount > 0 and name in self.instances.instances:
                got = self.instances.acquire(name, amount)
                if got is None:
                    for n, a, ids in acquired:
                        self.instances.release(n, a, ids)
                    return None
                instances[name] = got
                acquired.append((name, amount, got))
        return instances

    def _release_instances(self, resources: ResourceSet, instances: Dict[str, List[int]]):
        for name, ids in instances.items():
            self.instances.release(name, resources.get(name), ids)

    @staticmethod
    def _apply_chip_isolation(env_extra: Dict[str, str], instances):
        """TPU leases expose exactly their chips; non-TPU leases must not
        touch the accelerator at all — workers that import jax fall back to
        CPU (reference precedent: empty TPU_VISIBLE_CHIPS; here we also
        neutralize the axon-tunnel sitecustomize, which force-registers the
        TPU backend in every child process)."""
        if "TPU" in instances:
            chips = ",".join(str(i) for i in instances["TPU"])
            env_extra[GlobalConfig.tpu_visible_chips_env] = chips
            env_extra["TPU_VISIBLE_DEVICES"] = chips
        else:
            env_extra.setdefault(GlobalConfig.tpu_visible_chips_env, "")
            env_extra.setdefault("TPU_VISIBLE_DEVICES", "")
            if "axon" in os.environ.get("JAX_PLATFORMS", ""):
                env_extra.setdefault("JAX_PLATFORMS", "cpu")
                env_extra.setdefault("PALLAS_AXON_POOL_IPS", "")

    async def _finish_grant(self, payload, fut, resources, instances, pg_id,
                            bundle_index, conn=None):
        env_extra = dict(payload.get("env_vars") or {})
        self._apply_chip_isolation(env_extra, instances)
        try:
            worker = await self._pop_worker(env_extra)
        except Exception as e:  # noqa: BLE001
            self._release_pool_resources(resources, instances, pg_id, bundle_index)
            self._drain_lease_queue()
            if not fut.done():
                fut.set_exception(e)
            return
        lease_id = self._next_lease_id
        self._next_lease_id += 1
        lease = Lease(
            lease_id, worker, resources, instances, pg_id, bundle_index
        )
        lease.retriable = payload.get("retriable", True)
        # The lease belongs to the requesting DRIVER, identified two ways:
        # by connection (fast death signal) and by stable owner_id (the
        # driver's RPC address) — a retrying client that reconnects after
        # a transient transport failure re-associates its leases via
        # owner_ping/request_lease instead of losing them (ADVICE r3: a
        # healthy driver's leases must not die with one socket).
        lease.owner_conn = conn
        lease.owner_id = payload.get("owner_id")
        self.leases[lease_id] = lease
        if conn is not None and getattr(conn, "closed", False):
            # Owner died while we were starting its worker: reap now —
            # on_connection_closed already ran and cannot see this lease.
            # MUST precede the re-association below: binding the owner to
            # this dead conn (and cancelling its grace timer) would orphan
            # the owner's OTHER leases forever (no further disconnect
            # event will fire for an already-closed connection).
            self._reap_lease(lease_id)
            if not fut.done():
                fut.set_exception(
                    ConnectionError("lease requester disconnected")
                )
            return
        if lease.owner_id:
            self._owner_conns[lease.owner_id] = conn
            timer = self._owner_reap_timers.pop(lease.owner_id, None)
            if timer:
                timer.cancel()
        if not fut.done():
            fut.set_result(
                {
                    "granted": True,
                    "lease_id": lease_id,
                    "worker_address": worker.address,
                    "worker_id": worker.worker_id,
                    "instances": instances,
                }
            )

    async def _spillback(self, payload, fut, resources: ResourceSet):
        try:
            reply = await self.cp_client.call(
                "pick_node_for_lease",
                {
                    "resources": resources.to_dict(),
                    "strategy": payload.get("strategy"),
                    "preferred": None,
                    "placement_group_id": payload.get("placement_group_id"),
                    "bundle_index": payload.get("bundle_index", -1),
                    "job_id": payload.get("job_id"),
                    # Stable requester identity: the control plane dedupes
                    # its autoscaler demand windows by it, so one lease
                    # pool retrying does not read as N pending tasks.
                    "owner_id": payload.get("owner_id"),
                },
            )
        except Exception as e:  # noqa: BLE001
            if not fut.done():
                fut.set_exception(e)
            return
        if not fut.done():
            if reply.get("infeasible"):
                if reply.get("fatal"):
                    # No amount of scaling fixes this (e.g. removed PG,
                    # bad bundle index): surface the error now.
                    fut.set_exception(ValueError(reply["error"]))
                    return
                # Infeasible *now* — stay queued and retry (the reference
                # queues infeasible work indefinitely; the autoscaler sees
                # the demand via the control plane's unplaceable window and
                # may add a node that fits).
                fut.set_result({"granted": False, "retry": True})
            elif reply.get("node_id") is None:
                fut.set_result({"granted": False, "retry": True})
            elif reply["agent_address"] == self.server.address:
                # The control plane pointed back at THIS node (e.g. a PG
                # bundle recorded here that _try_grant couldn't find) —
                # spilling to ourselves would loop forever.
                fut.set_exception(
                    ValueError(
                        "lease unroutable: target node is this node but "
                        "the local grant failed"
                    )
                )
            else:
                fut.set_result(
                    {"granted": False, "spillback": reply["agent_address"]}
                )

    def _release_pool_resources(self, resources, instances, pg_id, bundle_index):
        self._release_instances(resources, instances)
        if pg_id is not None:
            bundle = self._resource_pool(pg_id, bundle_index)
            if bundle is not None:
                bundle.available = bundle.available + resources
        else:
            self.resources.release(resources)

    def _release_lease(self, lease_id: int):
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return
        self._release_pool_resources(
            lease.resources, lease.instances, lease.pg_id, lease.bundle_index
        )
        self._return_worker(lease.worker)
        self._drain_lease_queue()

    def handle_return_lease(self, payload, conn):
        self._release_lease(payload["lease_id"])
        return True

    def on_connection_closed(self, conn):
        """A peer connection dropped.  If it was a lease-holding driver,
        reap its leases (reference: the raylet reclaims a dead owner's
        leased workers) — a crashed/exited driver must not pin node
        resources forever.  Order matters: purge the dead driver's QUEUED
        requests first, because releasing a lease re-drains the queue and
        would otherwise grant the freed resources straight back to the
        dead driver.  Leased workers are KILLED, not pooled: they may be
        mid-task for the dead driver and must not serve the next lease.
        Worker-registration connections are handled by the process monitor.
        """
        kept = []
        for payload, fut, qconn in self._lease_queue:
            if qconn is conn:
                # Resolve the handler coroutine so it doesn't await forever;
                # the error reply goes nowhere (connection is gone), which
                # the dispatch layer tolerates.
                if not fut.done():
                    fut.set_exception(
                        ConnectionError("lease requester disconnected")
                    )
            else:
                kept.append((payload, fut, qconn))
        self._lease_queue = kept
        affected = [
            (lid, lease) for lid, lease in self.leases.items()
            if getattr(lease, "owner_conn", None) is conn
        ]
        owners_with_id = set()
        for lid, lease in affected:
            owner_id = getattr(lease, "owner_id", None)
            if owner_id:
                owners_with_id.add(owner_id)
            else:
                # Legacy/no-id lease: the connection WAS the identity.
                logger.info("reaping lease %d from disconnected driver", lid)
                self._reap_lease(lid)
        # Owners bound to this conn with NO leases: nothing to grace —
        # drop the mapping now so dead connections don't accumulate.
        for owner_id, oconn in list(self._owner_conns.items()):
            if oconn is conn and owner_id not in owners_with_id:
                self._owner_conns.pop(owner_id, None)
                timer = self._owner_reap_timers.pop(owner_id, None)
                if timer:
                    timer.cancel()
        # Identified owners get a reconnection grace window: a retrying
        # client that lost one socket re-associates via owner_ping /
        # request_lease; only an owner that stays silent is reaped.
        for owner_id in owners_with_id:
            if self._owner_conns.get(owner_id) is not conn:
                continue  # already re-associated to a newer connection
            timer = self._owner_reap_timers.pop(owner_id, None)
            if timer:
                timer.cancel()
            self._owner_reap_timers[owner_id] = (
                asyncio.get_running_loop().call_later(
                    GlobalConfig.lease_owner_grace_s,
                    self._reap_owner_if_silent, owner_id, conn,
                )
            )

    def _reap_owner_if_silent(self, owner_id: str, dead_conn):
        """Grace expired: reap the owner's leases unless it reconnected."""
        self._owner_reap_timers.pop(owner_id, None)
        current = self._owner_conns.get(owner_id)
        if current is not dead_conn and current is not None and not getattr(
            current, "closed", False
        ):
            return  # owner came back on a new connection; leases live on
        for lid, lease in list(self.leases.items()):
            if getattr(lease, "owner_id", None) == owner_id:
                logger.info(
                    "reaping lease %d from silent owner %s", lid, owner_id
                )
                self._reap_lease(lid)
        self._owner_conns.pop(owner_id, None)

    def handle_owner_ping(self, payload, conn):
        """Driver liveness + lease re-association (sent periodically and
        after client reconnects)."""
        owner_id = payload.get("owner_id")
        if not owner_id:
            # oneway handler (clients only .notify): no reply frame ever
            # goes out, so returning a value would just be dead code.
            return
        prev = self._owner_conns.get(owner_id)
        self._owner_conns[owner_id] = conn
        timer = self._owner_reap_timers.pop(owner_id, None)
        if timer:
            timer.cancel()
        if prev is not conn:
            for lease in self.leases.values():
                if getattr(lease, "owner_id", None) == owner_id:
                    lease.owner_conn = conn
        return

    def _reap_lease(self, lease_id: int):
        """Release a dead owner's lease: free resources, KILL the worker
        (it may still be running the dead driver's task)."""
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return
        self._release_pool_resources(
            lease.resources, lease.instances, lease.pg_id, lease.bundle_index
        )
        self._kill_worker_proc(lease.worker)
        self._drain_lease_queue()

    # ---------------------------------------------------------------- actors
    async def handle_create_actor_worker(self, payload, conn):
        spec: ActorSpec = payload["spec"]
        resources = ResourceSet(spec.resources)
        bundle = self._resource_pool(spec.placement_group_id, spec.bundle_index, resources)
        if bundle is not None:
            if not resources.is_subset_of(bundle.available):
                raise ValueError("bundle resources exhausted")
            bundle.available = bundle.available - resources
        else:
            if not self.resources.acquire(resources):
                raise ValueError("insufficient resources for actor")
        instances = self._acquire_instances(resources)
        if instances is None:
            if bundle is not None:
                bundle.available = bundle.available + resources
            else:
                self.resources.release(resources)
            raise ValueError("accelerator instances fragmented; retry")
        env_extra = dict(spec.env_vars)
        self._apply_chip_isolation(env_extra, instances)
        try:
            # Actor creations pop the same idle pool as task leases — a
            # pooled worker (pre-started, or recycled after running task
            # code) hosts the new actor instance, exactly like the
            # reference (``WorkerPool::PopWorker``,
            # src/ray/raylet/worker_pool.h:281, which also reuses workers
            # that executed tasks).  Once the actor is initialized the
            # process belongs to it: on actor death it is killed, never
            # re-pooled (_return_worker).
            worker = await self._pop_worker(env_extra)
            worker.is_actor = True
            worker.actor_id = spec.actor_id
            # Initialize the actor instance in the worker.
            reply = await self.worker_clients.get(worker.address).call(
                "actor_init",
                {"spec": spec, "incarnation": payload.get("incarnation", 0)},
                timeout=GlobalConfig.worker_startup_timeout_s,
            )
            if not reply.get("ok"):
                # Application error (user __init__ raised): kill the worker,
                # report non-retryably so the control plane marks the actor
                # DEAD instead of respawning forever.
                worker.is_actor = False
                worker.actor_id = None
                self._kill_worker_proc(worker)
                self._release_instances(resources, instances)
                if bundle is not None:
                    bundle.available = bundle.available + resources
                else:
                    self.resources.release(resources)
                self._drain_lease_queue()
                return {"init_error": str(reply.get("error"))}
        except Exception:
            worker_handle = locals().get("worker")
            if worker_handle is not None:
                worker_handle.is_actor = False
                worker_handle.actor_id = None
                self._kill_worker_proc(worker_handle)
            self._release_instances(resources, instances)
            if bundle is not None:
                bundle.available = bundle.available + resources
            else:
                self.resources.release(resources)
            self._drain_lease_queue()
            raise
        lease_id = self._next_lease_id
        self._next_lease_id += 1
        self.leases[lease_id] = Lease(
            lease_id,
            worker,
            resources,
            instances,
            spec.placement_group_id,
            spec.bundle_index,
            is_actor=True,
        )
        return {"worker_address": worker.address, "worker_id": worker.worker_id}

    # ---------------------------------------------------- placement bundles
    def _prepare_pg(self, pg_id: PlacementGroupID, bundles: dict) -> bool:
        """Reserve one group's bundles; atomic per group — on any bundle
        not fitting, every bundle already reserved HERE rolls back."""
        reserved = []
        for idx, spec in bundles.items():
            rs = ResourceSet(spec)
            if not self.resources.acquire(rs):
                for i in reserved:
                    pool = self.bundles.pop((pg_id, i))
                    self.resources.release(pool.total)
                return False
            self.bundles[(pg_id, idx)] = BundlePool(spec)
            reserved.append(idx)
        return True

    def handle_prepare_bundles(self, payload, conn):
        return {"ok": self._prepare_pg(payload["pg_id"], payload["bundles"])}

    def handle_prepare_bundles_batch(self, payload, conn):
        """Phase-1 reservation for SEVERAL placement groups in one RPC.
        Per-group atomic: a group that doesn't fit rolls back its own
        bundles and reports ok=False without affecting batch siblings."""
        return {
            "results": {
                g["pg_id"]: self._prepare_pg(g["pg_id"], g["bundles"])
                for g in payload["groups"]
            }
        }

    def handle_commit_bundles(self, payload, conn):
        self._commit_pg(payload["pg_id"])
        return True

    def _commit_pg(self, pg_id):
        for key, pool in self.bundles.items():
            if key[0] == pg_id:
                pool.committed = True

    def handle_commit_bundles_batch(self, payload, conn):
        for pg_id in payload["pg_ids"]:
            self._commit_pg(pg_id)
        return True

    def handle_reserve_bundles_batch(self, payload, conn):
        """Fused prepare+commit for groups placed wholly on this node —
        the control plane's single-node fast path (two-phase commit only
        pays for itself when a group spans agents)."""
        results = {}
        for g in payload["groups"]:
            ok = self._prepare_pg(g["pg_id"], g["bundles"])
            if ok:
                self._commit_pg(g["pg_id"])
            results[g["pg_id"]] = ok
        return {"results": results}

    def handle_cancel_bundles(self, payload, conn):
        return self._drop_bundles(payload["pg_id"])

    def handle_cancel_bundles_batch(self, payload, conn):
        for pg_id in payload["pg_ids"]:
            self._drop_bundles(pg_id, drain=False)
        self._drain_lease_queue()
        return True

    def handle_return_bundles(self, payload, conn):
        return self._drop_bundles(payload["pg_id"])

    def handle_return_bundles_batch(self, payload, conn):
        for pg_id in payload["pg_ids"]:
            self._drop_bundles(pg_id, drain=False)
        self._drain_lease_queue()
        return True

    def _held_pg_ids(self):
        """Distinct placement groups with live reservations on this node —
        shipped with (re-)registration so the control plane can reconcile:
        a group removed (or evicted) while this node was unreachable, or
        while the CP itself was restarting, must not pin resources here
        forever."""
        return list({key[0] for key in self.bundles})

    def _drop_stale_pgs(self, pg_ids) -> None:
        for pg_id in pg_ids or ():
            logger.info(
                "dropping stale bundle reservation for pg %s "
                "(control-plane reconciliation)", pg_id.hex()[:12],
            )
            self._drop_bundles(pg_id, drain=False)
        if pg_ids:
            self._drain_lease_queue()

    def _drop_bundles(self, pg_id, drain: bool = True):
        for key in [k for k in self.bundles if k[0] == pg_id]:
            pool = self.bundles.pop(key)
            self.resources.release(pool.total)
        if drain:
            self._drain_lease_queue()
        return True

    # --------------------------------------------------------------- objects
    def handle_seal_object(self, payload, conn):
        # Guard against seal-after-free: seals are pipelined oneway frames
        # and a fast owner free (different connection for task-return
        # objects) may have already deleted the entry from the tiers.
        # Registering a dead oid would leak directory accounting forever.
        oid = payload["object_id"]
        if payload.get("tier") == "spill":
            # Arena-oversized object written straight to the disk spill
            # tier by its creator: index it as spilled (never shm-LRU'd).
            from .object_store import spill_path

            if os.path.exists(spill_path(self.session_id, oid)):
                self.directory.register_spilled(oid, payload["size"])
        elif self.shm_store.contains(oid):
            self.directory.seal(oid, payload["size"])
        # oneway handler (clients only .notify): the return value of a
        # msg_id-0 frame is silently dropped, so don't fake an ack.
        return

    def handle_free_objects(self, payload, conn):
        for oid in payload["object_ids"]:
            if oid in self._pull_futures:
                self._freed_during_pull.add(oid)
            self.directory.free(oid)
        return True

    def handle_list_objects(self, payload, conn):
        """Local sealed-object inventory for the state API (snapshot taken
        under the directory's tier lock — the spill thread mutates tiers
        concurrently)."""
        out = self.directory.inventory()
        for row in out:
            row["node_id"] = self.node_id.hex()
        return out

    def handle_object_info(self, payload, conn):
        size = self.directory.size_of(payload["object_id"])
        return {"exists": size is not None, "size": size}

    def handle_get_object_chunk(self, payload, conn):
        oid = payload["object_id"]
        if not self.directory.contains(oid) and not self.shm_store.contains(oid):
            return {"exists": False}
        view = self.shm_store.raw_bytes(oid)
        off, length = payload["offset"], payload["length"]
        # Out-of-band chunk: the pinned arena view rides the reply frame
        # as a raw segment — no bytes() copy on the serving agent (the pin
        # holds the block until the transport flushes the frame).
        from .serialization import oob_bytes

        return {
            "exists": True,
            "total": len(view),
            "data": oob_bytes(view[off : off + length]),
        }

    async def _pull_into_local(self, oid: ObjectID, from_agent: str):
        """Dedup'd pull of one object into local shm — the shared body of
        the single and batch pull RPCs.  Joiners of an in-flight pull are
        shielded (one requester's cancellation must not kill the pull for
        the rest) and only the future's owner pops the dedup entry (a
        cancelled joiner must not evict a still-running pull — a third
        requester would start a duplicate)."""
        if self.directory.contains(oid):
            return
        fut = self._pull_futures.get(oid)
        owner_of_fut = fut is None
        if owner_of_fut:
            fut = asyncio.get_running_loop().create_task(
                self._do_pull(oid, from_agent)
            )
            self._pull_futures[oid] = fut
        try:
            if owner_of_fut:
                await fut
            else:
                await asyncio.shield(fut)
        finally:
            if owner_of_fut:
                self._pull_futures.pop(oid, None)
                self._freed_during_pull.discard(oid)

    async def handle_pull_object(self, payload, conn):
        """Pull an object from a remote node into local shm (dedup'd)."""
        await self._pull_into_local(payload["object_id"], payload["from_agent"])
        return {"ok": True}

    async def handle_pull_objects(self, payload, conn):
        """Batch fan-in for the data-plane fast path: pull many objects
        concurrently (dedup'd against in-flight singles) with per-object
        failure isolation — one dead source must not fail the batch.
        Returns ``errors`` aligned with ``items`` (None on success)."""

        async def pull_one(oid: ObjectID, from_agent: str):
            try:
                await self._pull_into_local(oid, from_agent)
                return None
            except Exception as e:  # noqa: BLE001 — reported per-slot
                return f"{type(e).__name__}: {e}"

        errors = await asyncio.gather(
            *(pull_one(oid, src) for oid, src in payload["items"])
        )
        return {"errors": list(errors)}

    async def _do_pull(self, oid: ObjectID, from_agent: str):
        client = self.agent_clients.get(from_agent)
        chunk = GlobalConfig.object_chunk_bytes
        first = await client.call(
            "get_object_chunk", {"object_id": oid, "offset": 0, "length": chunk}
        )
        if not first["exists"]:
            raise KeyError(f"object {oid} not on {from_agent}")
        total = first["total"]
        parts = [first["data"]]
        got = len(first["data"])
        while got < total:
            part = await client.call(
                "get_object_chunk",
                {"object_id": oid, "offset": got, "length": chunk},
            )
            parts.append(part["data"])
            got += len(part["data"])
        payload = b"".join(parts)
        # Executor: the store write is a full-payload copy — for an
        # arena-oversized object, a multi-hundred-MB DISK write — and must
        # not stall the agent loop (heartbeats, lease grants).
        size, tier = await asyncio.get_running_loop().run_in_executor(
            None, self.shm_store.create_from_bytes, oid, payload
        )
        if oid in self._freed_during_pull:
            # Freed while the pull was in flight: sealing now would
            # register a dead oid forever.  Delete the just-written copy
            # instead (free is idempotent across tiers).
            self._freed_during_pull.discard(oid)
            self.directory.free(oid)
            return
        if tier == "spill":
            self.directory.register_spilled(oid, size)
        else:
            self.directory.seal(oid, size)

    async def handle_remediate(self, payload, conn):
        """Remediation directive fan-out: forward the directives to every
        live local worker's ``remediate`` handler.  The remediation
        controller broadcasts through agents (one RPC per node) so
        per-process actuators — the collective tuner, registered
        in-process hooks — are reachable without per-worker addressing.
        Per-worker failures are isolated, mirroring the obs pull."""
        from ..util import flight_recorder as fr

        directives = payload.get("directives", ())
        timeout = max(1.0, GlobalConfig.health_check_period_s)

        async def one(handle):
            if handle.address is None or handle.proc.poll() is not None:
                return None
            try:
                return await self.worker_clients.get(handle.address).call(
                    "remediate", {"directives": directives}, timeout=timeout,
                )
            except Exception:  # noqa: BLE001 — worker may be mid-exit
                fr.count_suppressed("remediate_fanout")
                return None

        replies = await asyncio.gather(
            *(one(h) for h in list(self.workers.values()))
        )
        done = [r for r in replies if r]
        return {"workers": len(done), "results": done}

    async def handle_prepare_evict(self, payload, conn):
        """Checkpoint fan-out ahead of a preemption: every local worker
        holding a lease of the victim placement group gets a
        ``prepare_evict`` call so its workload can checkpoint through its
        existing restart machinery before the bundle is reclaimed.
        Best-effort with per-worker isolation (like ``remediate``): a
        wedged worker forfeits its checkpoint, never the eviction."""
        from ..util import flight_recorder as fr

        pg_id = payload["pg_id"]
        timeout = max(1.0, float(
            payload.get("timeout")
            or GlobalConfig.sched_evict_checkpoint_timeout_s
        ))
        cause = payload.get("cause", "")
        targets = []
        seen = set()
        for lease in list(self.leases.values()):
            if lease.pg_id != pg_id:
                continue
            handle = lease.worker
            if handle.address is None or handle.address in seen:
                continue
            if handle.proc.poll() is not None:
                continue
            seen.add(handle.address)
            targets.append(handle)

        async def one(handle):
            try:
                reply = await self.worker_clients.get(handle.address).call(
                    "prepare_evict", {"cause": cause}, timeout=timeout,
                    retries=1,
                )
                return bool(reply and reply.get("checkpointed"))
            except Exception:  # noqa: BLE001 — evict proceeds regardless
                fr.count_suppressed("prepare_evict_fanout")
                return False

        results = await asyncio.gather(*(one(h) for h in targets))
        return {"acks": sum(1 for r in results if r), "workers": len(targets)}

    def handle_ping(self, payload, conn):
        return "pong"

    def handle_prestart_pool(self, payload, conn):
        """Force the warm pool toward its floor at normal priority NOW.

        Reference analog: ``ray._private.state.prestart_workers`` /
        ``WorkerPool::PrestartWorkers`` (raylet ``worker_pool.h:281``) —
        callers that know a creation burst is coming (benchmarks, batch
        drivers) warm the pool deterministically instead of relying on
        the quiet-time background refill, whose SCHED_IDLE imports can
        starve arbitrarily long on a contended core."""
        # Hold hot mode open until this fill completes (the 5 s pop-miss
        # window is too short for a full 16-worker fill on one core).
        self._prestart_hot_until = time.monotonic() + 120.0
        self._replenish_pool()
        key = self._default_env_key
        return {
            "idle": len(self.idle_pool.get(key, [])),
            "inflight": len(self._prestart_inflight),
            "floor": self._pool_floor(),
        }

    def handle_debug_state(self, payload, conn):
        return {
            "node_id": self.node_id.hex(),
            "resources": self._snapshot(),
            "num_workers": len(self.workers),
            "idle": {str(k): len(v) for k, v in self.idle_pool.items()},
            "idle_pids": sorted(
                h.proc.pid for v in self.idle_pool.values() for h in v
            ),
            "prestart_inflight": len(self._prestart_inflight),
            "pool_floor": self._pool_floor(),
            "leases": len(self.leases),
            "queued_leases": len(self._lease_queue),
            "objects": len(self.directory.object_ids()),
            "object_bytes": self.directory.used,
            "spilled_objects": len(self.directory._spilled),
            "spilled_bytes": self.directory.spilled_bytes,
            "num_spilled_total": self.directory.num_spilled,
            "rpc_stats": dict(self.server.stats),
            "rpc_lanes": self.server.lane_stats(),
            # Aggregator introspection: rounds counts obs pulls (ridden on
            # the heartbeat); background_loops names every periodic task
            # this agent runs so tests can pin "no new periodic RPC loop".
            "obs": {
                "rounds": self._obs_rounds,
                "workers_pulled": self._obs_workers_pulled,
                "events_forwarded": self._obs_events_forwarded,
            },
            "background_loops": sorted(
                t.get_coro().__qualname__ for t in self._bg
            ),
        }



def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--cp-address", required=True)
    parser.add_argument("--session-id", required=True)
    parser.add_argument(
        "--owns-session-shm", default="0",
        help="1 = this agent owns session shm cleanup on parent death "
        "(set for the head node's agent only)",
    )
    parser.add_argument("--resources", required=True, help="JSON dict")
    parser.add_argument("--labels", default="{}", help="JSON dict")
    parser.add_argument(
        "--cp-ha-dir", default=None,
        help="control-plane HA directory; the CP client follows the "
        "published leader endpoint across failovers",
    )
    args = parser.parse_args()

    def _unlink_session_arena(session_id=args.session_id):
        from .object_store import arena_path

        try:
            os.unlink(arena_path(session_id))
        except OSError:
            pass
        try:
            os.unlink(arena_path(session_id) + ".owner")
        except OSError:
            pass

    if args.owns_session_shm == "1":
        # This agent owns its session's arena: stamp ownership (pid +
        # starttime, PID-reuse-proof) and sweep arenas orphaned by
        # SIGKILLed heads of PAST sessions — their reaper never ran, and
        # nothing else ever deletes them (head-owned cleanup).
        from .object_store import arena_path as _ap
        from .reaper import _proc_start_time
        from .shm import SHM_DIR, _PREFIX

        try:
            with open(_ap(args.session_id) + ".owner", "w") as f:
                f.write(f"{os.getpid()} {_proc_start_time(os.getpid())}")
        except OSError:
            pass
        for fname in os.listdir(SHM_DIR):
            if not (fname.startswith(f"{_PREFIX}_") and
                    fname.endswith("_arena")):
                continue
            path = os.path.join(SHM_DIR, fname)
            if path == _ap(args.session_id):
                continue
            try:
                with open(path + ".owner") as f:
                    pid_s, _, start_s = f.read().partition(" ")
                alive = _proc_start_time(int(pid_s)) == start_s
            except (OSError, ValueError):
                # No ownership stamp: NEVER assume dead (mmap writes don't
                # reliably bump mtime, so age is not proof) — leave it.
                continue
            if not alive:
                logger.info("sweeping orphan session arena %s", fname)
                # The whole dead session's shm: arena + per-object
                # segments (rtpu_<sid>_<objhex>) + owner stamp.
                from .shm import cleanup_session

                dead_sid = fname[len(_PREFIX) + 1:-len("_arena")]
                cleanup_session(dead_sid)
                try:
                    os.unlink(path + ".owner")
                except OSError:
                    pass

    from .reaper import watch_parent_process

    watch_parent_process(
        on_exit=(
            _unlink_session_arena
            if args.owns_session_shm == "1"
            else None
        )
    )
    import json

    logging.basicConfig(
        level=GlobalConfig.log_level,
        format="%(asctime)s %(levelname)s node_agent: %(message)s",
    )

    async def run():
        from .stack_dump import install_signal_dumpers

        install_signal_dumpers(asyncio.get_running_loop())
        agent = NodeAgent(
            args.host,
            args.port,
            args.cp_address,
            args.session_id,
            json.loads(args.resources),
            json.loads(args.labels),
            cp_ha_dir=args.cp_ha_dir,
        )
        await agent.start()
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
