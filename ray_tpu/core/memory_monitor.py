"""Node OOM defense: memory monitor + worker-killing policy.

Reference: ``MemoryMonitor`` (ray ``src/ray/common/memory_monitor.h:52``)
polls node memory; ``WorkerKillingPolicy`` (ray
``raylet/worker_killing_policy.h:33``) picks a victim when usage crosses
the threshold — retriable work first, newest first (so long-running work
survives).  The killed task surfaces as a ``WorkerCrashedError`` and the
submitter's ``max_retries`` machinery resubmits it, exactly like any other
worker death.
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger(__name__)


def system_memory_fraction() -> float:
    """Used fraction of node memory from /proc/meminfo (cgroup limits are
    the follow-up; the reference reads both)."""
    total = available = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    available = int(line.split()[1])
                if total is not None and available is not None:
                    break
    except OSError:
        return 0.0
    if not total:
        return 0.0
    return 1.0 - (available or 0) / total


def pick_worker_to_kill(
    leases: List[dict],
) -> Optional[Tuple[int, dict]]:
    """Choose a victim among active leases.

    Each lease dict needs: ``lease_id``, ``start_ts``, ``retriable`` (bool),
    ``is_actor`` (bool).  Policy (reference group-by-owner/retriable-first,
    simplified): retriable tasks before non-retriable before actors; newest
    first within a class — the work closest to its start loses the least.
    """
    if not leases:
        return None

    def rank(lease):
        if lease.get("is_actor"):
            cls = 2
        elif lease.get("retriable", True):
            cls = 0
        else:
            cls = 1
        return (cls, -lease.get("start_ts", 0.0))

    ordered = sorted(leases, key=rank)
    victim = ordered[0]
    return victim["lease_id"], victim


class MemoryMonitor:
    """Periodically invoked by the node agent; kills one victim per breach
    round (gradual back-off beats mass slaughter)."""

    def __init__(
        self,
        threshold: float,
        usage_reader: Callable[[], float] = system_memory_fraction,
    ):
        self.threshold = threshold
        self.usage_reader = usage_reader
        self.num_kills = 0

    def check(self, leases: List[dict]) -> Optional[Tuple[int, dict]]:
        """Returns (lease_id, lease) to kill, or None."""
        usage = self.usage_reader()
        if usage < self.threshold:
            return None
        victim = pick_worker_to_kill(leases)
        if victim is not None:
            self.num_kills += 1
            logger.warning(
                "memory usage %.1f%% >= %.1f%%: killing lease %s",
                usage * 100, self.threshold * 100, victim[0],
            )
        return victim
