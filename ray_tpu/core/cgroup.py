"""cgroup-v2 resource isolation for worker processes.

Reference: ray ``src/ray/common/cgroup2/`` (+ ``enable_resource_isolation``
in ``ray.init``, ``_private/worker.py:1427``): system processes and
application workers are placed in separate cgroup subtrees so a runaway
worker cannot starve the control plane.  Redesign: a small driver ABC with
a real cgroup2 filesystem driver and a fake driver for tests (the
reference ships ``fake_cgroup_driver.h`` for the same reason — cgroup
writes need root + a v2 mount, which CI may not have).

Enabled by the ``enable_resource_isolation`` knob; the node agent then
creates a flat ``<root>/ray_tpu_<session>_workers`` group with memory/cpu
limits and attaches every spawned worker pid.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

CGROUP_ROOT = "/sys/fs/cgroup"


class CgroupDriver:
    """Interface: create a subgroup, apply limits, attach pids."""

    def available(self) -> bool:
        raise NotImplementedError

    def create_group(self, name: str, limits: Dict[str, str]) -> str:
        raise NotImplementedError

    def attach(self, group: str, pid: int) -> None:
        raise NotImplementedError

    def remove_group(self, group: str) -> None:
        raise NotImplementedError


class Cgroup2Driver(CgroupDriver):
    """Real driver over the unified cgroup-v2 hierarchy."""

    def __init__(self, root: str = CGROUP_ROOT):
        self.root = root

    def available(self) -> bool:
        ctrl = os.path.join(self.root, "cgroup.controllers")
        return os.path.exists(ctrl) and os.access(self.root, os.W_OK)

    def create_group(self, name: str, limits: Dict[str, str]) -> str:
        path = os.path.join(self.root, name)
        os.makedirs(path, exist_ok=True)
        for knob, value in limits.items():
            try:
                with open(os.path.join(path, knob), "w") as f:
                    f.write(value)
            except OSError as e:
                logger.warning("cgroup limit %s=%s failed: %s", knob, value, e)
        return path

    def attach(self, group: str, pid: int) -> None:
        try:
            with open(os.path.join(group, "cgroup.procs"), "w") as f:
                f.write(str(pid))
        except OSError as e:
            logger.warning("cgroup attach pid %d failed: %s", pid, e)

    def remove_group(self, group: str) -> None:
        """rmdir with a short retry: the agent kills workers immediately
        before cleanup, and cgroup.procs often still lists the dying pids
        — an immediate rmdir fails with EBUSY and stale
        ``ray_tpu_<session>_workers`` groups would accumulate.  Remaining
        pids are migrated to the root group on the last attempt."""
        import errno
        import time as _time

        for attempt in range(10):
            try:
                os.rmdir(group)
                return
            except OSError as e:
                if e.errno == errno.ENOENT:
                    return  # never created / already removed
                if attempt == 8:
                    # Last resort: move stragglers to the root cgroup so
                    # the rmdir can succeed.  Per-pid — a single dead pid
                    # (ESRCH) must not abort migrating the live ones.
                    try:
                        procs = os.path.join(group, "cgroup.procs")
                        root_procs = os.path.join(self.root, "cgroup.procs")
                        with open(procs) as f:
                            pids = f.read().split()
                    except OSError:
                        pids = []
                    for pid in pids:
                        try:
                            with open(root_procs, "w") as f:
                                f.write(pid)
                        except OSError:
                            pass
                _time.sleep(0.1)
        logger.warning("could not remove cgroup %s (still busy)", group)


class FakeCgroupDriver(CgroupDriver):
    """Records operations instead of touching the filesystem (the
    reference's fake_cgroup_driver.h analog)."""

    def __init__(self):
        self.groups: Dict[str, Dict[str, str]] = {}
        self.attached: Dict[str, List[int]] = {}
        self.removed: List[str] = []

    def available(self) -> bool:
        return True

    def create_group(self, name: str, limits: Dict[str, str]) -> str:
        self.groups[name] = dict(limits)
        self.attached.setdefault(name, [])
        return name

    def attach(self, group: str, pid: int) -> None:
        self.attached.setdefault(group, []).append(pid)

    def remove_group(self, group: str) -> None:
        self.removed.append(group)


class WorkerIsolation:
    """The node agent's view: one workers subgroup per session, every
    spawned worker attached; no-op when isolation is disabled or the
    driver reports unavailable."""

    def __init__(self, session_id: str, driver: Optional[CgroupDriver] = None,
                 memory_limit_bytes: Optional[int] = None,
                 cpu_weight: int = 100):
        from .config import GlobalConfig

        self.enabled = bool(GlobalConfig.enable_resource_isolation)
        self.driver = driver or Cgroup2Driver()
        self.group: Optional[str] = None
        if not self.enabled:
            return
        if not self.driver.available():
            logger.warning(
                "resource isolation requested but cgroup2 is unavailable "
                "(missing mount or permissions); continuing without it"
            )
            self.enabled = False
            return
        limits: Dict[str, str] = {"cpu.weight": str(cpu_weight)}
        if memory_limit_bytes:
            limits["memory.max"] = str(memory_limit_bytes)
        self.group = self.driver.create_group(
            f"ray_tpu_{session_id}_workers", limits
        )

    def attach_worker(self, pid: int) -> None:
        if self.enabled and self.group is not None:
            self.driver.attach(self.group, pid)

    def cleanup(self) -> None:
        if self.enabled and self.group is not None:
            self.driver.remove_group(self.group)
            self.group = None
