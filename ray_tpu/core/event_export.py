"""Structured cluster lifecycle event export.

Reference: ray ``src/ray/observability/ray_event_recorder.h`` + the
``export_*.proto`` schemas — typed definition/lifecycle events for
nodes, actors, jobs, and placement groups, recorded centrally and shipped
to an external aggregator.  Native redesign: the control plane records
events into a bounded ring and appends them as JSON lines to
``events.jsonl`` under the session directory (the external-export file an
operator's collector tails); the state API exposes ``list_cluster_events``.
"""

from __future__ import annotations

import json
import logging
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

# Event types (reference: observability/ray_*_event.h).
NODE_LIFECYCLE = "NODE_LIFECYCLE"
ACTOR_DEFINITION = "ACTOR_DEFINITION"
ACTOR_LIFECYCLE = "ACTOR_LIFECYCLE"
JOB_DEFINITION = "JOB_DEFINITION"
JOB_LIFECYCLE = "JOB_LIFECYCLE"
PG_LIFECYCLE = "PG_LIFECYCLE"


class EventRecorder:
    """Bounded in-memory ring + append-only JSONL export file."""

    def __init__(self, export_path: Optional[str] = None,
                 max_events: int = 10_000):
        self._ring: deque = deque(maxlen=max_events)
        self._export_path = export_path
        self._file = None
        self._seq = 0
        if export_path:
            os.makedirs(os.path.dirname(export_path) or ".", exist_ok=True)
            # Seed the sequence (and the queryable ring) from any existing
            # export: a restarted control plane appends with monotonic seq
            # instead of restarting at 0, and pre-crash events stay
            # servable through list_events.
            try:
                with open(export_path, "r") as f:
                    for line in f:
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            continue
                        self._ring.append(ev)
                        self._seq = max(self._seq, int(ev.get("seq", 0)))
            except OSError:
                pass
            self._file = open(export_path, "a", buffering=1)  # line-buffered

    def record(self, event_type: str, entity_id: str, state: str,
               **attrs: Any) -> None:
        self._seq += 1
        event = {
            "seq": self._seq,
            "timestamp": time.time(),
            "event_type": event_type,
            "entity_id": entity_id,
            "state": state,
            **attrs,
        }
        self._ring.append(event)
        if self._file is not None:
            try:
                self._file.write(json.dumps(event, default=str) + "\n")
            except Exception as e:
                logger.debug("event export write failed: %s", e)

    def list_events(self, event_type: Optional[str] = None,
                    entity_id: Optional[str] = None,
                    limit: int = 1000) -> List[Dict[str, Any]]:
        out = []
        for ev in reversed(self._ring):
            if event_type and ev["event_type"] != event_type:
                continue
            if entity_id and ev["entity_id"] != entity_id:
                continue
            out.append(ev)
            if len(out) >= limit:
                break
        out.reverse()
        return out

    def close(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except Exception as e:
                logger.debug("event export close failed: %s", e)
