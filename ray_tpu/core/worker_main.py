"""Worker process entrypoint (spawned by the node agent).

Registers with the node agent, then serves the core-worker protocol loop
forever (the analog of ``CoreWorker::RunTaskExecutionLoop``, Ray
``src/ray/core_worker/core_worker.h:251`` — except execution here is
push-driven via RPC handlers, so the loop just runs the event loop).
"""

from __future__ import annotations

import asyncio
import faulthandler
import logging
import os

# SIGABRT dumps every thread's stack to the worker log — the one tool
# that turns "a worker is stuck somewhere" into a line number (the
# reference gets this from `ray stack`'s py-spy integration).
faulthandler.enable()


_profile_exit_hook = lambda: None  # replaced when RAY_TPU_PROFILE_DIR is set


def main():
    from .config import GlobalConfig
    from .core_worker import CoreWorker, set_global_worker
    from .ids import NodeID, WorkerID
    from .rpc import RetryableRpcClient
    from .runtime_env import apply_runtime_env_in_worker

    apply_runtime_env_in_worker()

    logging.basicConfig(
        level=GlobalConfig.log_level,
        format="%(asctime)s %(levelname)s worker: %(message)s",
    )
    worker_id = WorkerID.from_hex(os.environ["RAY_TPU_WORKER_ID"])
    agent_address = os.environ["RAY_TPU_AGENT_ADDRESS"]
    cp_address = os.environ["RAY_TPU_CP_ADDRESS"]
    session_id = os.environ["RAY_TPU_SESSION_ID"]
    node_id = NodeID.from_hex(os.environ["RAY_TPU_NODE_ID"])

    async def run():
        from .stack_dump import install_signal_dumpers

        install_signal_dumpers(asyncio.get_running_loop())
        worker = CoreWorker(
            CoreWorker.WORKER,
            cp_address,
            agent_address,
            session_id,
            node_id,
            worker_id=worker_id,
        )
        set_global_worker(worker)
        address = await worker.async_start()
        # Keep a dedicated registration connection open: the agent uses its
        # closure as a liveness signal in addition to process polling.
        reg_lost = asyncio.Event()
        reg = RetryableRpcClient(agent_address, on_disconnect=reg_lost.set)
        reply = await reg.call(
            "register_worker",
            {"worker_id": worker_id, "address": address, "pid": os.getpid()},
        )
        if not reply.get("ok"):
            raise SystemExit("agent rejected worker registration")
        # Freeze the startup heap (same rationale as api.init): executor
        # GC cycles must not re-walk the interpreter's import graph on
        # every collection triggered by per-task garbage.
        import gc

        gc.collect()
        gc.freeze()
        # Liveness watchdog: a worker must not outlive its node agent
        # (reference: workers die the moment the raylet's IPC socket
        # closes).  Primary signal is connection EOF — a SIGKILLed agent
        # takes its workers down in milliseconds, not after 3 missed ping
        # periods (a surviving worker can keep serving cached objects and
        # stale leases from a "dead" node, breaking node-loss semantics).
        # The periodic ping stays as backup for half-open connections.
        failures = 0
        while True:
            eof = False
            try:
                await asyncio.wait_for(reg_lost.wait(), timeout=2.0)
                eof = True
                reg_lost.clear()
            except asyncio.TimeoutError:
                pass
            try:
                # After an EOF this reconnects; connection-refused fails
                # it instantly (agent process is gone).
                await reg.call("ping", timeout=2.0, retries=1)
                failures = 0
            except Exception:
                failures += 1
                if eof or failures >= 3:
                    logging.getLogger(__name__).warning(
                        "node agent unreachable; worker exiting"
                    )
                    _profile_exit_hook()
                    os._exit(1)

    from .core_worker import _maybe_dump_profile, _maybe_start_profile

    global _profile_exit_hook
    prof = _maybe_start_profile()
    if prof is not None:
        # Workers normally die by SIGTERM (agent stop) or the watchdog's
        # os._exit — both skip the finally below, so dump from a signal
        # handler / the watchdog hook instead.
        import signal

        def _dump_and_exit(signum=None, frame=None):
            _maybe_dump_profile(prof, "worker")
            os._exit(0)

        _profile_exit_hook = lambda: _maybe_dump_profile(prof, "worker")
        signal.signal(signal.SIGTERM, _dump_and_exit)
    try:
        asyncio.run(run())
    finally:
        _maybe_dump_profile(prof, "worker")


if __name__ == "__main__":
    main()
