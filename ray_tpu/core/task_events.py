"""Task event recording: per-worker buffer flushed to the control plane.

Role-equivalent of the reference's ``TaskEventBuffer`` (ray
``src/ray/core_worker/task_event_buffer.h:297``) + ``GcsTaskManager`` (ray
``src/ray/gcs/gcs_task_manager.h:97``): every worker batches task
state-transition and user profile events and periodically flushes them to the
control plane, which keeps a bounded per-task store powering the state API
(``list_tasks``), ``summarize_tasks``, and the Chrome-trace timeline dump
(ray ``python/ray/_private/state.py:441,527``).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time
from typing import Any, Dict, List, Optional

from .config import GlobalConfig

logger = logging.getLogger(__name__)

# Task lifecycle states (reference: rpc::TaskStatus).
PENDING_SUBMISSION = "PENDING_SUBMISSION"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"


class TaskEventBuffer:
    """Buffers task events in-process; a background loop flushes them to the
    control plane.  Lossy by design: if the control plane is unreachable the
    batch is dropped after one retry (events are observability, not truth) —
    but every drop is COUNTED (``num_dropped`` +
    ``ray_tpu_task_events_dropped_total``), so lossiness is visible."""

    def __init__(self, cp_client, node_id_hex: str, worker_id_hex: str):
        self._cp = cp_client
        self._node = node_id_hex
        self._worker = worker_id_hex
        # Flat tuples on the hot path (see record()); dicts are built at
        # flush time.
        self._events: List[tuple] = []
        self._profile_events: List[dict] = []
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self.num_dropped = 0  # events lost to shedding or failed flushes
        # Spans ride the profile channel, so buffer shedding silently
        # punches holes in traces — span drops are counted separately
        # (ray_tpu_trace_spans_dropped_total) and the running total ships
        # with every flush so get_trace()/the timeline can flag affected
        # traces as truncated instead of returning them as complete.
        self.num_span_dropped = 0
        self._span_drops_reported = 0  # last total shipped to the CP
        # When the node agent pulls this buffer on the heartbeat
        # (obs_pull), the worker's own flush loop drops to a slow backup
        # cadence instead of racing the agent with per-worker RPCs.
        self.pull_mode = False

    def _count_dropped(self, n: int, spans: int = 0) -> None:
        if n <= 0:
            return
        self.num_dropped += n
        self.num_span_dropped += spans
        try:
            from ray_tpu.util import flight_recorder

            flight_recorder.counter(
                flight_recorder.TASK_EVENTS_DROPPED_TOTAL, n
            )
            flight_recorder.counter(
                flight_recorder.TRACE_SPANS_DROPPED_TOTAL, spans
            )
        except Exception:  # raylint: waive[RTL003] telemetry of the telemetry
            pass

    @staticmethod
    def _count_spans(rows) -> int:
        return sum(
            1 for r in rows if ((r.get("extra") or {}).get("span"))
        )

    # ------------------------------------------------------------- recording
    def record(
        self,
        task_id_hex: str,
        name: str,
        state: str,
        *,
        attempt: int = 0,
        job_id_hex: str = "",
        actor_id_hex: str = "",
        error: Optional[str] = None,
        resources: Optional[Dict[str, float]] = None,
    ) -> None:
        if not GlobalConfig.enable_task_events:
            return
        # Hot path (3 records per task): append a flat tuple; the dict
        # shape the control plane expects is built at flush time.
        self._events.append(
            (task_id_hex, attempt, name, state, time.time(), job_id_hex,
             actor_id_hex, error, resources)
        )
        if len(self._events) > GlobalConfig.task_events_max_buffer:
            # Shed oldest half under backpressure.
            shed = len(self._events) // 2
            del self._events[:shed]
            self._count_dropped(shed)

    def add_profile_row(self, name: str, start: float, end: float,
                        extra: Optional[dict] = None) -> None:
        """Append one profile-channel row (timeline slice) with the shared
        overflow shed + drop accounting.  Safe from user threads under the
        GIL (same contract as record()): an append racing the flush swap
        lands in whichever list it read — delivered either way."""
        self._profile_events.append(
            {
                "name": name,
                "start": start,
                "end": end,
                "worker_id": self._worker,
                "node_id": self._node,
                "extra": extra,
            }
        )
        if len(self._profile_events) > GlobalConfig.task_events_max_buffer:
            shed = len(self._profile_events) // 2
            shed_rows = self._profile_events[:shed]
            del self._profile_events[:shed]
            self._count_dropped(shed, spans=self._count_spans(shed_rows))

    @contextlib.contextmanager
    def profile(self, event_name: str, extra: Optional[dict] = None):
        """User profile span (``ray.timeline`` profile-event analog); shows up
        as its own row in the timeline dump."""
        start = time.time()
        try:
            yield
        finally:
            if GlobalConfig.enable_task_events:
                self.add_profile_row(event_name, start, time.time(), extra)

    # --------------------------------------------------------------- flushing
    def start(self) -> None:
        if self._task is None and GlobalConfig.enable_task_events:
            self._task = asyncio.get_running_loop().create_task(self._flush_loop())

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        await self.flush()

    def drain(self) -> tuple:
        """Atomically take every buffered event, shaped for the control
        plane's ``task_events``/``obs_report`` handlers.  Shared by the
        worker's own flush and the node agent's heartbeat pull — each
        event leaves through exactly one of the two paths."""
        raw, self._events = self._events, []
        profiles, self._profile_events = self._profile_events, []
        events = [
            {
                "task_id": t[0],
                "attempt": t[1],
                "name": t[2],
                "state": t[3],
                "ts": t[4],
                "job_id": t[5],
                "actor_id": t[6],
                "node_id": self._node,
                "worker_id": self._worker,
                "error": t[7],
                "resources": t[8],
            }
            for t in raw
        ]
        return events, profiles

    async def flush(self) -> None:
        if (
            not self._events
            and not self._profile_events
            # An empty buffer still flushes when sheds happened since the
            # last report — truncation visibility must not wait for the
            # next event to ride along.
            and self.num_span_dropped == self._span_drops_reported
        ):
            return
        events, profiles = self.drain()
        span_drops = self.num_span_dropped
        try:
            await self._cp.call(
                "task_events",
                {"events": events, "profile_events": profiles,
                 "worker_id": self._worker,
                 "span_drops": span_drops},
                retries=2,
            )
            self._span_drops_reported = span_drops
        except Exception as e:  # noqa: BLE001 — observability is best-effort
            # Lossy by design — but visibly so: the counter flushes with
            # the metrics registry once the control plane is reachable
            # again, so operators can see how much history is missing.
            self._count_dropped(
                len(events) + len(profiles),
                spans=self._count_spans(profiles),
            )
            logger.debug("task-event flush dropped %d events: %s", len(events), e)

    async def _flush_loop(self) -> None:
        while not self._stopped:
            period = GlobalConfig.task_events_flush_period_s
            if self.pull_mode:
                # The node agent drains this buffer each heartbeat; the
                # local loop stays only as a slow backup for agent gaps.
                period = max(5.0, period)
            await asyncio.sleep(period)
            await self.flush()


class TaskEventStore:
    """Control-plane side: bounded store of per-task merged events (the
    ``GcsTaskManager`` analog).  One entry per (task_id, attempt); state
    transitions merge into ``state_ts``; oldest finished entries evicted
    beyond the cap."""

    # Batches from the submitter and the executor arrive on independent flush
    # timers, so merges must be state-ranked, not last-write-wins: a late
    # PENDING_SUBMISSION must never regress a task already FINISHED.
    _STATE_RANK = {PENDING_SUBMISSION: 0, RUNNING: 1, FINISHED: 2, FAILED: 2}

    def __init__(self):
        self._tasks: Dict[tuple, dict] = {}
        self._profile_events: List[dict] = []
        self.num_dropped = 0
        # Cluster span-loss accounting: per-worker shed totals (reported
        # with each flush/pull, max-merged so the two delivery paths
        # can't double count) plus spans this store itself evicted.
        self._worker_span_drops: Dict[str, int] = {}
        self._own_span_drops = 0

    def report_span_drops(self, worker_id: str, total) -> None:
        """Record a worker's cumulative span-shed count (idempotent:
        totals only ratchet up, so redelivery is harmless)."""
        try:
            total = int(total)
        except (TypeError, ValueError):
            return
        if total > self._worker_span_drops.get(worker_id, 0):
            self._worker_span_drops[worker_id] = total

    def span_drop_total(self) -> int:
        return self._own_span_drops + sum(self._worker_span_drops.values())

    def add_batch(self, events: List[dict], profile_events: List[dict]) -> None:
        for ev in events:
            key = (ev["task_id"], ev["attempt"])
            entry = self._tasks.get(key)
            if entry is None:
                entry = {
                    "task_id": ev["task_id"],
                    "attempt": ev["attempt"],
                    "name": ev["name"],
                    "job_id": ev["job_id"],
                    "actor_id": ev["actor_id"],
                    "node_id": ev["node_id"],
                    "worker_id": ev["worker_id"],
                    "state": ev["state"],
                    "state_ts": {},
                    "error": None,
                    "resources": ev.get("resources"),
                }
                self._tasks[key] = entry
            rank = self._STATE_RANK.get(ev["state"], 0)
            if rank >= self._STATE_RANK.get(entry["state"], 0):
                entry["state"] = ev["state"]
            entry["state_ts"][ev["state"]] = ev["ts"]
            # The executing worker knows node/worker; the submitter doesn't.
            if ev["state"] in (RUNNING, FINISHED, FAILED):
                entry["node_id"] = ev["node_id"]
                entry["worker_id"] = ev["worker_id"]
            if ev.get("error"):
                entry["error"] = ev["error"]
            if ev.get("resources"):
                entry["resources"] = ev["resources"]
        self._profile_events.extend(profile_events)
        cap = GlobalConfig.task_events_max_stored
        if len(self._tasks) > cap:
            overflow = len(self._tasks) - cap
            # dicts iterate in insertion order: evict oldest *terminal*
            # entries first; still-running tasks are what operators look for.
            evicted = 0
            for key in list(self._tasks):
                if evicted >= overflow:
                    break
                if self._tasks[key]["state"] in (FINISHED, FAILED):
                    del self._tasks[key]
                    evicted += 1
            if evicted < overflow:  # everything is live; evict oldest anyway
                for key in list(self._tasks)[: overflow - evicted]:
                    del self._tasks[key]
                    evicted += 1
            self.num_dropped += evicted
        if len(self._profile_events) > cap:
            overflow = len(self._profile_events) - cap
            self._own_span_drops += TaskEventBuffer._count_spans(
                self._profile_events[:overflow]
            )
            del self._profile_events[:overflow]

    def list_tasks(
        self, filters: Optional[Dict[str, Any]] = None, limit: int = 1000
    ) -> List[dict]:
        out = []
        for entry in reversed(list(self._tasks.values())):
            if filters and any(
                str(entry.get(k)) != str(v) for k, v in filters.items()
            ):
                continue
            out.append(dict(entry, state_ts=dict(entry["state_ts"])))
            if len(out) >= limit:
                break
        return out

    def profile_events(self) -> List[dict]:
        return list(self._profile_events)
