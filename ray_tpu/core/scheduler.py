"""Cluster-level scheduling policies.

Equivalent of the reference's policy layer (Ray
``src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h``,
``bundle_scheduling_policy.h``): given an eventually-consistent view of all
nodes' resources (gossiped by the control plane), pick a node for a lease or
a set of nodes for a placement-group's bundles.

Policies:
  - hybrid (default): pack onto best-utilized feasible nodes until a node
    crosses ``scheduler_spread_threshold`` utilization, then spread; ties
    broken by top-k random choice to avoid herding.
  - spread: round-robin across feasible nodes.
  - node-affinity: pin to a node (soft or hard).
  - label-match: restrict to nodes whose labels satisfy a selector
    (used for ICI-topology-aware placement, e.g. {"tpu-slice": "v5e-16"}).
  - bundle pack/spread with STRICT variants for placement groups.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from .ids import NodeID
from .resources import NodeResources, ResourceSet
from .config import GlobalConfig


class SchedulingStrategy:
    """Base marker; concrete strategies below are plain picklable structs."""


class DefaultStrategy(SchedulingStrategy):
    pass


class SpreadStrategy(SchedulingStrategy):
    pass


class NodeAffinityStrategy(SchedulingStrategy):
    def __init__(self, node_id_hex: str, soft: bool = False):
        self.node_id_hex = node_id_hex
        self.soft = soft


class NodeLabelStrategy(SchedulingStrategy):
    def __init__(self, hard: Dict[str, str]):
        self.hard = hard


class PlacementGroupStrategy(SchedulingStrategy):
    def __init__(self, pg_id_hex: str, bundle_index: int = -1):
        self.pg_id_hex = pg_id_hex
        self.bundle_index = bundle_index


def _feasible(
    nodes: Dict[NodeID, NodeResources], request: ResourceSet, available: bool
) -> List[NodeID]:
    out = []
    for nid, res in nodes.items():
        ok = res.can_fit(request) if available else res.could_ever_fit(request)
        if ok:
            out.append(nid)
    return out


class ClusterScheduler:
    """Holds the cluster resource view; pure policy, no IO.

    The default (hybrid) pick path runs in the native scheduling core when
    the C++ library is available (``src/native/rtpu_sched.cc`` — interned
    resource ids + fixed-point arithmetic; reference
    ``raylet/scheduling/policy/hybrid_scheduling_policy.h``); label/affinity
    strategies and placement-group bundles stay in Python."""

    def __init__(self, use_native: bool = True):
        self.nodes: Dict[NodeID, NodeResources] = {}
        # Draining nodes stay in the view (so demand that only THEY could
        # satisfy queues as infeasible-now rather than hard-failing) but are
        # excluded from every pick path — a heartbeat can never re-open a
        # node the autoscaler is retiring.
        self._draining: set = set()
        self._spread_rr = 0
        self._native = None
        if use_native:
            try:
                from .native import make_scheduler

                self._native = make_scheduler()
            except Exception:  # noqa: BLE001 — toolchain missing
                self._native = None

    def update_node(self, node_id: NodeID, snapshot: dict):
        nr = self.nodes.get(node_id)
        if nr is None:
            nr = NodeResources(snapshot["total"], snapshot.get("labels"))
            self.nodes[node_id] = nr
        nr.total = ResourceSet(snapshot["total"])
        nr.available = ResourceSet(snapshot["available"])
        nr.labels = snapshot.get("labels", {})
        if self._native is not None:
            self._native.update_node(
                node_id.binary(), snapshot["total"], snapshot["available"]
            )

    def remove_node(self, node_id: NodeID):
        self.nodes.pop(node_id, None)
        self._draining.discard(node_id)
        if self._native is not None:
            self._native.remove_node(node_id.binary())

    def set_draining(self, node_id: NodeID, draining: bool = True):
        if draining:
            self._draining.add(node_id)
        else:
            self._draining.discard(node_id)

    def is_draining(self, node_id: NodeID) -> bool:
        return node_id in self._draining

    # ------------------------------------------------------------------ tasks
    def pick_node(
        self,
        request: ResourceSet,
        strategy: Optional[SchedulingStrategy] = None,
        preferred: Optional[NodeID] = None,
    ) -> Optional[NodeID]:
        """Returns a node id, or None if infeasible right now.  Raises
        ValueError if no node could *ever* satisfy the request."""
        if isinstance(strategy, NodeAffinityStrategy):
            target = NodeID.from_hex(strategy.node_id_hex)
            nr = self.nodes.get(target)
            if (
                nr is not None
                and target not in self._draining
                and nr.can_fit(request)
            ):
                return target
            if not strategy.soft:
                return None
            strategy = None  # soft: fall through to hybrid
        if (
            self._native is not None
            and not self._draining
            and (strategy is None or isinstance(strategy, DefaultStrategy))
        ):
            status, picked = self._native.pick_node(
                request.to_dict(),
                GlobalConfig.scheduler_spread_threshold,
                GlobalConfig.scheduler_top_k_fraction,
                preferred=preferred.binary() if preferred else None,
                seed=random.getrandbits(63),
            )
            if status == 1:
                return NodeID(picked)
            if status == 0:
                return None
            if status == -1:
                raise InfeasibleError(
                    f"no node can ever satisfy {request.to_dict()} "
                    f"(strategy=default)"
                )
            return None  # -2: empty cluster
        candidates = self.nodes
        if isinstance(strategy, NodeLabelStrategy):
            candidates = {
                nid: nr
                for nid, nr in self.nodes.items()
                if all(nr.labels.get(k) == v for k, v in strategy.hard.items())
            }
        schedulable = {
            nid: nr
            for nid, nr in candidates.items()
            if nid not in self._draining
        }
        feasible_now = _feasible(schedulable, request, available=True)
        if not feasible_now:
            # Feasibility ("could this EVER fit") is judged against all
            # candidates including draining ones: demand whose only home is
            # a retiring node queues until the drain resolves instead of
            # hard-failing with InfeasibleError.
            if not _feasible(candidates, request, available=False):
                if not candidates:
                    return None
                raise InfeasibleError(
                    f"no node can ever satisfy {request.to_dict()} "
                    f"(strategy={type(strategy).__name__ if strategy else 'default'})"
                )
            return None
        if isinstance(strategy, SpreadStrategy):
            feasible_now.sort(key=lambda n: self.nodes[n].utilization())
            return feasible_now[0]
        return self._hybrid_pick(feasible_now, preferred)

    def _hybrid_pick(
        self, feasible: List[NodeID], preferred: Optional[NodeID]
    ) -> NodeID:
        threshold = GlobalConfig.scheduler_spread_threshold
        # Prefer the local/preferred node if it is under the pack threshold.
        if preferred is not None and preferred in feasible:
            if self.nodes[preferred].utilization() < threshold:
                return preferred
        below = [n for n in feasible if self.nodes[n].utilization() < threshold]
        if below:
            # Pack: highest utilization first (fill nodes up), top-k random.
            below.sort(key=lambda n: -self.nodes[n].utilization())
            k = max(1, int(len(below) * GlobalConfig.scheduler_top_k_fraction))
            return random.choice(below[:k])
        # All above threshold: spread to least utilized.
        feasible.sort(key=lambda n: self.nodes[n].utilization())
        return feasible[0]

    # ---------------------------------------------------------------- bundles
    def pick_nodes_for_bundles(
        self,
        bundles: List[ResourceSet],
        strategy: str,
        extra_available: Optional[Dict[NodeID, ResourceSet]] = None,
    ) -> Optional[List[NodeID]]:
        """Two-phase-commit phase 0: choose a node per bundle (same node may
        appear multiple times for PACK).  Returns None if currently
        infeasible.  Simulates acquisition against a scratch copy of the view
        so co-scheduled bundles don't double-book.

        ``extra_available`` is the preemption what-if: per-node resources
        that *would* free if candidate victims were evicted, added to the
        scratch view so the control plane can test 'would this gang place
        after evicting these victims?' before committing to any eviction."""
        scratch: Dict[NodeID, NodeResources] = {}
        for nid, nr in self.nodes.items():
            if nid in self._draining:
                continue
            copy = NodeResources(nr.total.to_dict(), dict(nr.labels))
            copy.available = ResourceSet(nr.available.to_dict())
            if extra_available and nid in extra_available:
                copy.available = copy.available + extra_available[nid]
            scratch[nid] = copy

        assignment: List[Optional[NodeID]] = [None] * len(bundles)

        def try_assign(order_nodes: List[NodeID], idx: int) -> bool:
            for nid in order_nodes:
                if scratch[nid].acquire(bundles[idx]):
                    assignment[idx] = nid
                    return True
            return False

        if strategy in ("STRICT_PACK",):
            for nid, nr in scratch.items():
                total_needed = bundles[0]
                for b in bundles[1:]:
                    total_needed = total_needed + b
                if total_needed.is_subset_of(nr.available):
                    return [nid] * len(bundles)
            return None
        if strategy in ("STRICT_SPREAD",):
            used: set = set()
            for i, b in enumerate(bundles):
                cands = [
                    n
                    for n in scratch
                    if n not in used and scratch[n].can_fit(b)
                ]
                cands.sort(key=lambda n: scratch[n].utilization())
                if not cands:
                    return None
                scratch[cands[0]].acquire(b)
                assignment[i] = cands[0]
                used.add(cands[0])
            return assignment  # type: ignore[return-value]
        if strategy == "SPREAD":
            for i, b in enumerate(bundles):
                cands = sorted(
                    (n for n in scratch if scratch[n].can_fit(b)),
                    key=lambda n: scratch[n].utilization(),
                )
                if not try_assign(cands, i):
                    return None
            return assignment  # type: ignore[return-value]
        # PACK (default): minimize node count — fill best-utilized first.
        for i, b in enumerate(bundles):
            cands = sorted(
                (n for n in scratch if scratch[n].can_fit(b)),
                key=lambda n: -scratch[n].utilization(),
            )
            if not try_assign(cands, i):
                return None
        return assignment  # type: ignore[return-value]


class InfeasibleError(Exception):
    """Raised when a request can never be satisfied by the current cluster."""
