"""Binary identifiers for jobs, tasks, actors, objects, nodes, and placement groups.

Design follows the reference runtime's ID scheme (Ray `src/ray/common/id.h`):
fixed-width binary IDs with cheap hashing and hex round-tripping. Unlike the
reference we do not embed the parent-task lineage bits inside the ObjectID —
ownership is carried explicitly on the ObjectRef (owner address), which is the
piece of state the protocols actually need.
"""

from __future__ import annotations

import os
import threading

_ID_SIZE = 16  # bytes


class BaseID:
    """A fixed-width binary identifier. Immutable, hashable, comparable."""

    __slots__ = ("_bytes", "_hash")

    SIZE = _ID_SIZE

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes) or len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {id_bytes!r}"
            )
        self._bytes = id_bytes
        self._hash = hash(id_bytes)

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:12]}…)"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class ActorID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ObjectID(BaseID):
    """Object identifier.  Return objects of a task are derived
    deterministically from the TaskID + return index so that retries of the
    same task produce the same ObjectIDs (needed for lineage reconstruction)."""

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        suffix = index.to_bytes(4, "little")
        return cls(task_id.binary()[: cls.SIZE - 4] + suffix)


class _Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value


_task_counter = _Counter()

# One urandom syscall per process (re-read after fork), not one per task id:
# ids need uniqueness, not unpredictability.  8 random prefix bytes per
# process + an 8-byte little-endian in-process counter.  The counter's LOW
# bytes sit at offsets 8-11, inside the [:12] slice for_task_return keeps,
# so derived ObjectIDs stay distinct for 2^32 tasks per process.
_id_prefix: bytes = b""
_id_prefix_pid: int = -1


def new_task_id() -> TaskID:
    """Unique task id: per-process random prefix + process-local counter."""
    global _id_prefix, _id_prefix_pid
    if _id_prefix_pid != os.getpid():
        _id_prefix = os.urandom(8)
        _id_prefix_pid = os.getpid()
    ctr = _task_counter.next().to_bytes(8, "little")
    return TaskID(_id_prefix + ctr)


_object_counter = _Counter()
_obj_prefix: bytes = b""
_obj_prefix_pid: int = -1


def new_object_id() -> ObjectID:
    """Unique object id for puts (own random prefix, disjoint from the
    task-id space, + process-local counter)."""
    global _obj_prefix, _obj_prefix_pid
    if _obj_prefix_pid != os.getpid():
        _obj_prefix = os.urandom(8)
        _obj_prefix_pid = os.getpid()
    ctr = _object_counter.next().to_bytes(8, "little")
    return ObjectID(_obj_prefix + ctr)
