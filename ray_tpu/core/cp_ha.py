"""Control-plane high availability: leader lease, endpoint discovery,
and the warm-standby request rejector.

The reference gets GCS fault tolerance from an external replicated Redis
(``src/ray/gcs/store_client/redis_store_client.h:126``) plus a single
restartable GCS process.  TPU-native redesign: the cluster runs TWO
control-plane candidate processes over one shared journal directory
(``store_client.JournaledStoreClient``), coordinated by three small files
under the HA directory — no external store to operate:

  - ``lease.json``     — the leader lease: holder, fencing epoch, and a
    CLOCK_MONOTONIC deadline (system-wide on Linux, so comparable across
    processes on the host).  Read-modify-write is serialized by a flock
    on ``lease.lock`` held ONLY for the compare-and-swap — never during
    leadership — so a SIGSTOPped leader is dethroned by TTL expiry, not
    protected by a kernel lock it still holds.
  - ``endpoint.json``  — the published leader endpoint (address + epoch,
    adopted monotonically by epoch).  Clients re-anchor by re-resolving
    this inside their existing decorrelated-jitter reconnect loop.
  - ``standby-*.json`` — each follower's applied journal sequence, so
    the leader can report replication lag.

Fencing: every journal append calls ``LeaderLease.verify()`` which
raises ``FencedWriteError`` once the lease file names a different holder
or epoch — a paused-then-resumed old leader gets its first write
rejected and exits, so split-brain writes are structurally impossible
even though the old process may briefly keep its socket open.
"""

from __future__ import annotations

import fcntl
import json
import logging
import os
import time
from typing import Callable, List, Optional

from .config import GlobalConfig
from .store_client import FencedWriteError

logger = logging.getLogger(__name__)

LEASE_FILE = "lease.json"
LEASE_LOCK = "lease.lock"
ENDPOINT_FILE = "endpoint.json"


def _write_json_atomic(path: str, obj: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class LeaderLease:
    """TTL lease with a monotonically increasing fencing epoch.

    ``try_acquire`` succeeds when the recorded lease is absent, expired,
    or already ours; every fresh acquisition bumps the epoch, so a write
    fenced on (holder, epoch) from before the takeover can never be
    mistaken for a current one.  ``clock`` is injectable for tests."""

    def __init__(self, ha_dir: str, holder: str,
                 ttl_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        os.makedirs(ha_dir, exist_ok=True)
        self.ha_dir = ha_dir
        self.holder = holder
        self.ttl = ttl_s if ttl_s is not None else GlobalConfig.cp_lease_ttl_s
        self.epoch = 0
        self.address = ""
        self._clock = clock
        self._lease_path = os.path.join(ha_dir, LEASE_FILE)
        self._lock_path = os.path.join(ha_dir, LEASE_LOCK)
        self._verify_sig = None  # (mtime_ns, size) at the last full check

    def _cas(self):
        """flock guarding the lease read-modify-write.  Kernel-released
        on process death, held microseconds — leadership itself is
        guarded by the TTL, never by this lock."""
        f = open(self._lock_path, "a+")
        fcntl.flock(f.fileno(), fcntl.LOCK_EX)
        return f

    def try_acquire(self, address: str) -> bool:
        f = self._cas()
        try:
            cur = _read_json(self._lease_path)
            now = self._clock()
            if cur:
                ours = (
                    cur.get("holder") == self.holder
                    and cur.get("epoch") == self.epoch
                    and self.epoch > 0
                )
                if not ours and cur.get("deadline", 0) > now:
                    return False  # a live foreign lease
                epoch = cur.get("epoch", 0) if ours else cur.get("epoch", 0) + 1
            else:
                epoch = 1
            _write_json_atomic(self._lease_path, {
                "holder": self.holder,
                "epoch": epoch,
                "deadline": now + self.ttl,
                "address": address,
            })
            self.epoch = epoch
            self.address = address
            self._verify_sig = None
            return True
        finally:
            f.close()

    def renew(self) -> bool:
        """Extend our own, still-valid lease.  Refuses — returning False
        and zeroing the epoch — when the lease changed hands OR already
        expired: an expired lease may be acquired by a standby the very
        next instant, so re-extending it would race the takeover.  The
        caller must treat False as loss of leadership."""
        f = self._cas()
        try:
            cur = _read_json(self._lease_path)
            now = self._clock()
            if (
                not cur
                or cur.get("holder") != self.holder
                or cur.get("epoch") != self.epoch
                or cur.get("deadline", 0) <= now
            ):
                self.epoch = 0
                return False
            _write_json_atomic(self._lease_path, {
                "holder": self.holder,
                "epoch": self.epoch,
                "deadline": now + self.ttl,
                "address": self.address,
            })
            return True
        finally:
            f.close()

    def release(self) -> None:
        """Graceful abdication: expire our lease in place (keeping the
        epoch, so the next acquirer still bumps past it)."""
        f = self._cas()
        try:
            cur = _read_json(self._lease_path)
            if cur and cur.get("holder") == self.holder and cur.get("epoch") == self.epoch:
                cur["deadline"] = 0.0
                _write_json_atomic(self._lease_path, cur)
        finally:
            f.close()
        self.epoch = 0

    def verify(self) -> None:
        """Fencing check on the journal's write path: cheap (one stat)
        when the lease file is unchanged since the last full check;
        re-reads it whenever the mtime/size moved (every renewal rewrites
        the file, so at most one re-read per renewal — and the FIRST
        write of a paused-then-resumed stale leader always re-reads,
        because the new leader's acquisition rewrote the file)."""
        if self.epoch <= 0:
            raise FencedWriteError(f"{self.holder}: no leader lease held")
        try:
            st = os.stat(self._lease_path)
        except OSError:
            raise FencedWriteError(f"{self.holder}: lease file missing")
        sig = (st.st_mtime_ns, st.st_size)
        if sig == self._verify_sig:
            return
        cur = _read_json(self._lease_path)
        if (
            not cur
            or cur.get("holder") != self.holder
            or cur.get("epoch") != self.epoch
        ):
            raise FencedWriteError(
                f"{self.holder}: lease epoch {self.epoch} superseded by "
                f"{cur.get('holder') if cur else '?'} "
                f"epoch {cur.get('epoch') if cur else '?'}"
            )
        self._verify_sig = sig


def read_lease(ha_dir: str) -> Optional[dict]:
    return _read_json(os.path.join(ha_dir, LEASE_FILE))


# ------------------------------------------------------------- discovery
def publish_endpoint(ha_dir: str, address: str, epoch: int) -> None:
    """Record the serving leader; adopted monotonically by epoch so a
    slow stale leader can never roll the pointer backwards."""
    path = os.path.join(ha_dir, ENDPOINT_FILE)
    cur = _read_json(path)
    if cur and cur.get("epoch", 0) > epoch:
        return
    _write_json_atomic(path, {"address": address, "epoch": epoch})


def read_endpoint(ha_dir: str) -> Optional[dict]:
    return _read_json(os.path.join(ha_dir, ENDPOINT_FILE))


def make_cp_resolver(ha_dir: Optional[str], fallback: str) -> Callable[[], str]:
    """Address resolver for ``RetryableRpcClient``: each (re)connect
    re-reads the published endpoint, so clients follow the leader without
    any new discovery protocol — the reconnect loop they already run for
    plain CP restarts does the re-anchor."""

    def resolve() -> str:
        if ha_dir:
            info = read_endpoint(ha_dir)
            if info and info.get("address"):
                return info["address"]
        return fallback

    return resolve


# --------------------------------------------------------------- standby
def write_standby_status(ha_dir: str, holder: str, address: str,
                         applied_seq: int) -> None:
    _write_json_atomic(
        os.path.join(ha_dir, f"standby-{holder}.json"),
        {
            "holder": holder,
            "address": address,
            "applied_seq": applied_seq,
            "updated_at": time.time(),
        },
    )


def clear_standby_status(ha_dir: str, holder: str) -> None:
    try:
        os.unlink(os.path.join(ha_dir, f"standby-{holder}.json"))
    except OSError as e:
        logger.debug("standby status unlink failed: %s", e)


def read_standby_statuses(ha_dir: str) -> List[dict]:
    out = []
    try:
        names = os.listdir(ha_dir)
    except OSError:
        return out
    for name in names:
        if name.startswith("standby-") and name.endswith(".json"):
            info = _read_json(os.path.join(ha_dir, name))
            if info:
                out.append(info)
    return out


class StandbyControlPlane:
    """RPC handler a candidate serves while NOT leader: every control
    RPC is rejected with ``NotLeaderError`` carrying the published
    leader's address, so a client that raced the failover (connected to
    the standby's port directly) is redirected instead of hanging."""

    LANE_SAFE_METHODS: frozenset = frozenset()

    def __init__(self, leader_hint: Callable[[], Optional[str]]):
        self._leader_hint = leader_hint

    async def handle_ping(self, payload, conn):
        return {"ok": True, "role": "standby"}

    async def handle_cp_role(self, payload, conn):
        return {
            "role": "standby",
            "epoch": 0,
            "leader": self._leader_hint(),
        }

    def on_connection_closed(self, conn):
        pass

    def __getattr__(self, name):
        if name.startswith("handle_"):
            from .rpc import NotLeaderError

            hint = self._leader_hint

            async def _reject(payload, conn):
                raise NotLeaderError(hint())

            return _reject
        raise AttributeError(name)
