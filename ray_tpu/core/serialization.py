"""Value serialization for the object plane.

Equivalent of the reference's SerializationContext
(Ray ``python/ray/_private/serialization.py``): cloudpickle for code and
arbitrary Python objects, pickle protocol-5 out-of-band buffers for zero-copy
handling of large contiguous arrays, and special passes for device-resident
``jax.Array`` values (moved to host on serialization; the device-object store
in ``ray_tpu.collective`` keeps arrays on device instead and only ships
references).

Wire format of a serialized object:
    header  = pickled metadata (cloudpickle bytes + buffer descriptors)
    buffers = list of raw contiguous memoryviews (zero-copy where possible)
"""

from __future__ import annotations

import io
import pickle
from typing import Any, List, Tuple

import cloudpickle


class ObjectRefSerializationContext:
    """Thread-local-ish hook so ObjectRefs serialized inside task args carry
    their owner address and the deserializer can reconstruct live refs."""

    pass


def _is_jax_array(value) -> bool:
    mod = type(value).__module__
    if not (mod.startswith("jax") or mod.startswith("jaxlib")):
        return False
    try:
        import jax

        return isinstance(value, jax.Array)
    except Exception:  # pragma: no cover - jax not importable
        return False


def _device_to_host(obj):
    """Recursively convert jax.Arrays to numpy for cross-process transport."""
    import numpy as np

    if _is_jax_array(obj):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _device_to_host(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        converted = [_device_to_host(v) for v in obj]
        return type(obj)(converted) if not hasattr(obj, "_fields") else type(obj)(*converted)
    return obj


def serialize(
    value: Any, prefer_plain: bool = False
) -> Tuple[bytes, List[memoryview]]:
    """Serialize a value to (header_bytes, out_of_band_buffers).

    ``prefer_plain`` tries stdlib pickle before cloudpickle — ~10x cheaper
    on the hot task-args path.  Only pass it when the caller has verified
    the value contains no code objects or __main__-defined classes (plain
    pickle would serialize those by reference, which deserializes to the
    wrong thing in a worker process)."""
    buffers: List[pickle.PickleBuffer] = []
    if _is_jax_array(value) or (
        isinstance(value, (dict, list, tuple)) and _contains_jax(value)
    ):
        value = _device_to_host(value)
    if prefer_plain:
        try:
            header = pickle.dumps(
                value, protocol=5, buffer_callback=buffers.append
            )
            return header, [b.raw() for b in buffers]
        except Exception:  # noqa: BLE001 — fall through to cloudpickle
            buffers = []
    header = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    views = [b.raw() for b in buffers]
    return header, views


def _contains_jax(obj, depth=0) -> bool:
    if depth > 4:
        return False
    if _is_jax_array(obj):
        return True
    if isinstance(obj, dict):
        return any(_contains_jax(v, depth + 1) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return any(_contains_jax(v, depth + 1) for v in obj)
    return False


_PLAIN_TYPES = frozenset(
    (int, float, bool, str, bytes, bytearray, type(None))
)
_np_mod = None


def is_plain_data(value: Any, depth: int = 4) -> bool:
    """Exact check that ``value`` consists only of builtin scalars, ndarrays,
    and builtin containers of them — i.e. stdlib pickle serializes it
    correctly by value (no code objects, no by-reference classes).  Used to
    route hot-path values through pickle instead of cloudpickle."""
    global _np_mod
    t = type(value)
    if t in _PLAIN_TYPES:
        return True
    if depth <= 0:
        return False
    if t in (list, tuple, set, frozenset):
        return all(is_plain_data(x, depth - 1) for x in value)
    if t is dict:
        return all(
            is_plain_data(k, depth - 1) and is_plain_data(v, depth - 1)
            for k, v in value.items()
        )
    if _np_mod is None:
        import numpy as _np

        _np_mod = _np
    # Object-dtype arrays hold arbitrary Python objects that plain pickle
    # would serialize by reference — not plain.
    return t is _np_mod.ndarray and not value.dtype.hasobject


def deserialize(header: bytes, buffers: List) -> Any:
    return pickle.loads(header, buffers=buffers)


class SerializedPayload:
    """A ``(header, views)`` pair that travels through pickle protocol 5
    WITH its buffers out of band — the wire shape of the data-plane fast
    path.  Pickling one inside an RPC frame copies only the tiny rebuild
    envelope into the pickle stream; the header and every view ride as
    raw frame segments (see ``rpc._encode_frame``), and the receiving
    side gets memoryviews into the read buffer — no intermediate flat
    encoding on either end (the ``serialize_to_bytes`` round-trip this
    replaces cost two extra full-payload copies per hop).

    Falls back to a by-value copy under pickle protocols < 5 so a spec
    that strays into a non-frame pickle still round-trips correctly."""

    __slots__ = ("header", "views")

    def __init__(self, header, views):
        self.header = header
        self.views = views

    @property
    def nbytes(self) -> int:
        return len(self.header) + sum(
            memoryview(v).nbytes for v in self.views
        )

    def to_bytes(self) -> bytes:
        """Flat single-buffer encoding (same layout as serialize_to_bytes)."""
        buf = bytearray(8 + self.nbytes + 8 * len(self.views))
        write_serialized(self.header, self.views, buf)
        return bytes(buf)

    def deserialize(self) -> Any:
        return pickle.loads(self.header, buffers=self.views)

    def snapshot(self) -> "SerializedPayload":
        """Copy any view that aliases caller-owned mutable memory (e.g. a
        numpy array passed as a task arg): submission must capture values
        at call time, not at socket-flush time."""
        if not self.views:
            return self
        self.views = [bytes(v) for v in self.views]
        return self

    def __reduce_ex__(self, protocol):
        if protocol >= 5:
            return (
                SerializedPayload,
                (
                    pickle.PickleBuffer(self.header),
                    [pickle.PickleBuffer(v) for v in self.views],
                ),
            )
        return (
            SerializedPayload,
            (bytes(self.header), [bytes(v) for v in self.views]),
        )


def serialize_payload(value: Any, prefer_plain: bool = False) -> SerializedPayload:
    header, views = serialize(value, prefer_plain=prefer_plain)
    return SerializedPayload(header, views)


def deserialize_payload(payload) -> Any:
    """Decode either wire shape of a serialized value: the out-of-band
    ``SerializedPayload`` fast path or a legacy flat bytes encoding."""
    if type(payload) is SerializedPayload:
        return payload.deserialize()
    return deserialize_from_bytes(payload)


def payload_nbytes(payload) -> int:
    if type(payload) is SerializedPayload:
        return payload.nbytes
    return len(payload)


_OOB_MIN_BYTES = 4096  # below this, a dedicated frame segment costs more
# than riding the pickle stream in-band


def oob_bytes(data):
    """Mark an immutable flat encoding (bytes, or a memoryview over a
    sealed shm block) for out-of-band framing: wrapped in a PickleBuffer
    it rides the RPC frame as a raw segment (zero send copies); the
    receiver sees a memoryview into the read buffer, which
    ``deserialize_payload``/``deserialize_from_bytes`` accept as-is."""
    if len(data) >= _OOB_MIN_BYTES and type(data) in (bytes, memoryview):
        return pickle.PickleBuffer(data)
    return bytes(data) if type(data) is memoryview else data


def serialize_to_bytes(value: Any, prefer_plain: bool = False) -> bytes:
    """Flat single-buffer encoding: [4B nbufs][4B hlen][header][4B blen][buf]…"""
    header, views = serialize(value, prefer_plain=prefer_plain)
    out = io.BytesIO()
    out.write(len(views).to_bytes(4, "little"))
    out.write(len(header).to_bytes(4, "little"))
    out.write(header)
    for v in views:
        b = bytes(v)
        out.write(len(b).to_bytes(8, "little"))
        out.write(b)
    return out.getvalue()


def serialized_nbytes(header: bytes, views: List[memoryview]) -> int:
    """Size of the flat encoding without materializing it."""
    return 8 + len(header) + sum(8 + memoryview(v).nbytes for v in views)


_NT_COPY_THRESHOLD = 1 << 20  # use non-temporal stores for buffers >= 1 MiB


def write_serialized(header: bytes, views: List[memoryview], dest) -> int:
    """Write the flat encoding straight into ``dest`` (e.g. an shm arena
    block) — the zero-copy put path: one memcpy per buffer instead of the
    bytes()/BytesIO/getvalue() triple copy of ``serialize_to_bytes``.
    Large buffers stream through non-temporal stores (the destination is
    read by *other* processes, so bypassing this core's cache skips the
    read-for-ownership and nearly doubles put bandwidth).  Returns bytes
    written."""
    mv = memoryview(dest)
    mv[0:4] = len(views).to_bytes(4, "little")
    mv[4:8] = len(header).to_bytes(4, "little")
    off = 8
    mv[off : off + len(header)] = header
    off += len(header)
    for v in views:
        b = memoryview(v).cast("B")
        mv[off : off + 8] = b.nbytes.to_bytes(8, "little")
        off += 8
        if b.nbytes >= _NT_COPY_THRESHOLD:
            from . import native

            if not native.memcpy_nt(mv[off : off + b.nbytes], b):
                mv[off : off + b.nbytes] = b
        else:
            mv[off : off + b.nbytes] = b
        off += b.nbytes
    return off


def deserialize_from_bytes(data) -> Any:
    mv = memoryview(data)
    nbufs = int.from_bytes(mv[0:4], "little")
    hlen = int.from_bytes(mv[4:8], "little")
    off = 8
    header = bytes(mv[off : off + hlen])
    off += hlen
    buffers = []
    for _ in range(nbufs):
        blen = int.from_bytes(mv[off : off + 8], "little")
        off += 8
        buffers.append(mv[off : off + blen])
        off += blen
    return deserialize(header, buffers)


def dumps_function(fn) -> bytes:
    """Pickle user code (function / actor class) for export via the control
    plane KV store (reference: python/ray/_private/function_manager.py)."""
    return cloudpickle.dumps(fn)


def loads_function(data: bytes):
    return cloudpickle.loads(data)
