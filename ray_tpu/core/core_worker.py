"""The per-process worker runtime — core-worker equivalent.

Embedded in every driver and worker process (Ray
``src/ray/core_worker/core_worker.h``).  Owns:
  - the in-process memory store + shm store client (object plane)
  - the ownership table + distributed reference counting
    (Ray ``reference_counter.h`` — simplified borrow protocol: args-holds on
    submission, incref/decref from deserializing borrowers)
  - normal task submission: lease pools per scheduling class with pipelining,
    spillback handling, retries (Ray ``normal_task_submitter.h``)
  - actor task submission: per-actor sequencing, restart-aware retries
    (Ray ``actor_task_submitter.h``)
  - the task execution loop: ordered actor queues, concurrency via a thread
    pool, inline vs shm return routing (Ray ``task_execution/``)
  - pubsub subscriptions for actor/node state.

Threading model: one asyncio event loop runs all protocol work.  In a driver
the loop runs on a background thread and the public API bridges with
``run_coroutine_threadsafe``; in a worker the loop is the main thread and
user code runs on a thread pool, so the loop stays responsive to serve
owned objects while user code blocks.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Set, Tuple

from .config import GlobalConfig
from .exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from .ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    TaskID,
    WorkerID,
    new_object_id,
    new_task_id,
)
from .object_store import MemoryStore, ShmObjectStore
from .owner_table import OwnerTable
from .rpc import (
    UNBOUNDED,
    ClientPool,
    DirectCall,
    ForwardToPrimary,
    RetryableRpcClient,
    RpcConnectionError,
    RpcRemoteError,
    RpcServer,
    RpcTimeoutError,
    resolve_service_lanes,
)
from .serialization import (
    SerializedPayload,
    deserialize_from_bytes,
    deserialize_payload,
    dumps_function,
    is_plain_data,
    loads_function,
    oob_bytes,
    payload_nbytes,
    serialize_payload,
    serialize_to_bytes,
)
from .task_spec import ActorSpec, ObjectRef, TaskSpec, _RefMarker, function_key
from ..util.debug_locks import make_condition, make_lock

logger = logging.getLogger(__name__)


_current_trace_context = None


def _maybe_start_profile():
    """cProfile the protocol loop thread when RAY_TPU_PROFILE_DIR is set
    (per-process .prof dumps; see docs/profiling.md).  The loop thread is
    where all RPC/serialization work happens, so this is the flamegraph
    that matters for control-plane throughput."""
    if not os.environ.get("RAY_TPU_PROFILE_DIR"):
        return None
    import cProfile

    prof = cProfile.Profile()
    prof.enable()
    return prof


def _maybe_dump_profile(prof, role: str):
    if prof is None:
        return
    prof.disable()
    out_dir = os.environ.get("RAY_TPU_PROFILE_DIR", "/tmp")
    try:
        os.makedirs(out_dir, exist_ok=True)
        prof.dump_stats(os.path.join(out_dir, f"{role}-{os.getpid()}.prof"))
    except Exception:  # raylint: waive[RTL003] profiling must never break teardown
        pass


def _tracing_context():
    global _current_trace_context
    if _current_trace_context is None:
        from ray_tpu.util.tracing import current_context

        _current_trace_context = current_context
    return _current_trace_context()


_flight_recorder = None


def _fr():
    """Cached lazy import of the flight recorder (import-cycle-safe: core
    modules load before ray_tpu.util's package init can run)."""
    global _flight_recorder
    if _flight_recorder is None:
        from ray_tpu.util import flight_recorder

        _flight_recorder = flight_recorder
    return _flight_recorder

_global_worker: Optional["CoreWorker"] = None


def global_worker() -> "CoreWorker":
    if _global_worker is None:
        raise RuntimeError("ray_tpu is not initialized — call ray_tpu.init() first")
    return _global_worker


def try_global_worker() -> Optional["CoreWorker"]:
    return _global_worker


def set_global_worker(w: Optional["CoreWorker"]):
    global _global_worker
    _global_worker = w


PENDING, READY, ERROR = "PENDING", "READY", "ERROR"

_EMPTY_ARGS_PAYLOAD: Optional[bytes] = None


def _inline_to_bytes(payload) -> bytes:
    """Normalize a received inline value to owned flat bytes.  Out-of-band
    reply shapes (SerializedPayload / memoryview) reference the transport
    read buffer — persisting them in an OwnedObject would pin the whole
    frame for the object's lifetime."""
    if type(payload) is SerializedPayload:
        return payload.to_bytes()
    if type(payload) is memoryview:
        return bytes(payload)
    return payload


class _LocationCache:
    """Per-worker ``object_id -> shm locations`` cache consulted before any
    borrowed-ref owner round-trip, so repeated gets of stable objects skip
    the owner entirely (the deserialized-value memo in ``memory_store``
    only covers values this process already materialized).

    Entries carry the cache *generation* at fill time: any observed fetch
    failure bumps the generation, so a fill racing an invalidation (an
    owner reply that was in flight when the loss was noticed) is dropped
    instead of resurrecting dead locations.  Loop-thread only."""

    __slots__ = (
        "_entries", "capacity", "generation",
        "hits", "misses", "invalidations",
    )

    def __init__(self, capacity: int = 4096):
        from collections import OrderedDict

        self._entries: "OrderedDict[ObjectID, list]" = OrderedDict()
        self.capacity = capacity
        self.generation = 0
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    def lookup(self, oid: ObjectID):
        entry = self._entries.get(oid)
        if entry is None:
            self.misses += 1
            return None
        self._entries.move_to_end(oid)
        self.hits += 1
        return entry

    def fill(self, oid: ObjectID, locations, gen: int):
        if gen != self.generation:
            return  # a loss was observed while this reply was in flight
        self._entries[oid] = list(locations)
        self._entries.move_to_end(oid)
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)

    def invalidate(self, oid: ObjectID):
        """A fetch through these locations failed (or the owner pruned
        them): drop the entry and fence in-flight fills."""
        self.generation += 1
        self.invalidations += 1
        self._entries.pop(oid, None)

    def drop(self, oid: ObjectID):
        # Free-path removal — no loss observed, in-flight fills of other
        # objects stay valid, so the generation does not move.
        self._entries.pop(oid, None)


class _BatchedCompleter:
    """Shared completion-batching substrate for execution threads.

    One ``call_soon_threadsafe`` loop wakeup per drain pass instead of
    per finished call — the dominant per-call cost of run_in_executor on
    a 1-core box (self-pipe write + epoll + futex each).  Used by both
    ExecPipeline (exclusive drainer) and LanePool (concurrency lanes);
    any flush-path fix lands in exactly one place.
    """

    def _init_completer(self, loop: asyncio.AbstractEventLoop):
        self.loop = loop
        self._done: List[tuple] = []
        self._done_lock = make_lock("core_worker.completer.done")
        self._done_flush_scheduled = False

    def _complete(self, fut, res):
        schedule = False
        with self._done_lock:
            self._done.append((fut, res))
            if not self._done_flush_scheduled:
                self._done_flush_scheduled = True
                schedule = True
        if schedule:
            try:
                self.loop.call_soon_threadsafe(self._flush_done)
            except RuntimeError:  # loop closed at teardown
                pass

    def _flush_done(self):
        with self._done_lock:
            done, self._done = self._done, []
            self._done_flush_scheduled = False
        for fut, res in done:
            if not fut.done():
                fut.set_result(res)


class ExecPipeline(_BatchedCompleter):
    """Sticky exclusive-execution thread for task/actor-call execution at
    max_concurrency == 1 (the default).

    Why not ThreadPoolExecutor per call: each run_in_executor round trip
    costs two GIL/futex handoffs (wake the pool thread, wake the loop
    back) — ~1ms each under contention on a 1-core box, which capped
    actor-call throughput (reference analog: Ray executes actor tasks on
    a dedicated execution thread fed by a queue, not a fresh dispatch per
    call, ``core_worker/task_execution.cc``).  A single sticky drainer
    thread executes a run of queued calls back-to-back: handoffs amortize
    across the burst, and completions flush to the loop in batches (one
    wakeup per drain pass, not per call).

    Exclusivity: the drainer IS the mutual exclusion (one thread).
    Coroutine/streaming work enqueues a bridge item: the drainer submits
    it to the event loop and blocks until it finishes, preserving
    exclusion without holding an asyncio lock across the await.

    Ordering: tickets are issued at dispatch (loop thread, arrival
    order); the drainer executes strictly in ticket order, so a call
    whose argument resolution suspends cannot be overtaken by a later
    call.  A ticket that can't be used (dispatch failed) MUST be
    abandoned or the cursor wedges — _execute guarantees this.
    """

    class Ticket:
        __slots__ = ("seq", "consumed")

        def __init__(self, seq: int):
            self.seq = seq
            self.consumed = False

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._init_completer(loop)
        self._cv = make_condition("core_worker.exec_pipeline")
        self._items: Dict[int, tuple] = {}
        self._next_ticket = 0
        self._next_exec = 0
        self._stopped = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------- loop-thread API
    def ticket(self) -> "ExecPipeline.Ticket":
        t = self.Ticket(self._next_ticket)
        self._next_ticket += 1
        return t

    async def run_sync(self, ticket: "ExecPipeline.Ticket", fn, *args, **kwargs):
        """Execute ``fn(*args, **kwargs)`` on the drainer thread."""
        fut = self.loop.create_future()
        ticket.consumed = True
        with self._cv:
            self._items[ticket.seq] = ("sync", (fn, args, kwargs), fut)
            self._cv.notify()
        self._ensure_thread()
        ok, val = await fut
        if ok:
            return val
        raise val

    async def run_coro(self, ticket: "ExecPipeline.Ticket", coro_factory):
        """Run a coroutine on the event loop while the drainer blocks on
        it — exclusive like a sync item, but suspendable."""
        fut = self.loop.create_future()
        ticket.consumed = True
        with self._cv:
            self._items[ticket.seq] = ("coro", coro_factory, fut)
            self._cv.notify()
        self._ensure_thread()
        ok, val = await fut
        if ok:
            return val
        raise val

    def abandon(self, ticket: "ExecPipeline.Ticket"):
        """Release an issued-but-unused ticket (dispatch failed before
        enqueue) so the in-order cursor can pass it.  Idempotent."""
        if ticket.consumed:
            return
        ticket.consumed = True
        with self._cv:
            self._items[ticket.seq] = ("skip", None, None)
            self._cv.notify()

    def stop(self):
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    # ---------------------------------------------------------- drainer side
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._drain, daemon=True, name="exec-pipeline"
            )
            self._thread.start()

    def _drain(self):
        while True:
            with self._cv:
                while self._next_exec not in self._items and not self._stopped:
                    self._cv.wait()
                if self._next_exec not in self._items:
                    return  # stopped and drained
                kind, work, fut = self._items.pop(self._next_exec)
                self._next_exec += 1
            if kind == "skip":
                continue
            if kind == "sync":
                fn, args, kwargs = work
                try:
                    res = (True, fn(*args, **kwargs))
                except BaseException as e:  # noqa: BLE001 — reported to caller
                    res = (False, e)
            else:
                try:
                    cfut = asyncio.run_coroutine_threadsafe(work(), self.loop)
                    res = (True, cfut.result())
                except BaseException as e:  # noqa: BLE001
                    res = (False, e)
            self._complete(fut, res)



class LanePool(_BatchedCompleter):
    """N sticky execution threads for max_concurrency > 1 actors.

    run_in_executor's per-call cost on a 1-core box is dominated by the
    completion path: one ``call_soon_threadsafe`` loop wakeup per call
    (self-pipe write + epoll + futex).  The lanes share ExecPipeline's
    batched done-flush instead — a burst of overlapping calls completes
    with one loop wakeup per drain pass.  No ordering guarantees (that is
    the point of concurrency lanes); exclusion, when the user wants it,
    is the actor's own locks, exactly like the reference's concurrent
    actor threads.
    """

    def __init__(self, loop: asyncio.AbstractEventLoop, size: int):
        import queue as _queue

        self._init_completer(loop)
        self.size = max(1, size)
        self._q: "_queue.SimpleQueue" = _queue.SimpleQueue()
        self._threads: List[threading.Thread] = []
        # Under _lane_lock: lanes parked in q.get / items enqueued but not
        # yet claimed by a lane.  The spawn decision compares the two —
        # `_idle` alone LAGS the queue (an idle lane stays counted until
        # the OS schedules it), so back-to-back enqueues would under-spawn
        # and serialize behind one lane.
        self._idle = 0
        self._pending = 0
        self._lane_lock = make_lock("core_worker.lane_pool")
        self._stopped = False

    async def run(self, fn, *args, **kwargs):
        fut = self.loop.create_future()
        # Lanes spawn ON DEMAND, one per uncovered item: serve replicas
        # declare max_concurrency=1000, and eagerly spawning `size`
        # threads was a thread storm that starved a 1-core box long
        # enough to trip replica health checks.  The stopped check and
        # the enqueue share the lane lock with stop()'s drain, so no item
        # can slip into the queue after the drain ran (it would sit
        # behind the sentinels, unserved, hanging its awaiting handler).
        with self._lane_lock:
            if self._stopped:
                raise RuntimeError("lane pool is stopped")
            self._pending += 1
            spawn = (
                self._pending > self._idle
                and len(self._threads) < self.size
            )
            if spawn:
                t = threading.Thread(
                    target=self._worker, daemon=True,
                    name=f"actor-lane-{len(self._threads)}",
                )
                self._threads.append(t)
            self._q.put((fn, args, kwargs, fut))
        if spawn:
            t.start()
        ok, val = await fut
        if ok:
            return val
        raise val

    def stop(self):
        """Fail-fast shutdown.  Items a lane already claimed run to
        completion; items still QUEUED are failed with 'lane pool
        stopped' (their futures must resolve — a dropped item would hang
        its awaiting RPC handler forever).  The drain runs BEFORE the
        sentinels are pushed and under the lane lock: draining after
        would pop the sentinels themselves, stranding busy lanes blocked
        in q.get() forever, and an unlocked drain could race run() into
        enqueueing an item behind the sentinels where no lane ever serves
        it."""
        import queue as _queue

        with self._lane_lock:
            if self._stopped:
                return  # idempotent: a second drain would eat sentinels
            self._stopped = True
            while True:
                try:
                    item = self._q.get_nowait()
                except _queue.Empty:
                    break
                if item is None:  # unreachable (sentinels push below);
                    continue      # kept so a drained sentinel can't crash
                self._pending -= 1
                self._complete(
                    item[3], (False, RuntimeError("lane pool stopped"))
                )
            for _ in self._threads:
                self._q.put(None)

    def _worker(self):
        while True:
            item = None
            with self._lane_lock:
                self._idle += 1
            try:
                item = self._q.get()
            finally:
                with self._lane_lock:
                    self._idle -= 1
                    if item is not None:
                        self._pending -= 1
            if item is None:
                return  # items queued before the sentinel were served
            fn, args, kwargs, fut = item
            try:
                res = (True, fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 — reported to caller
                res = (False, e)
            self._complete(fut, res)



class _SubmitBudget:
    """Byte-budgeted submission backpressure (graceful overload
    degradation for the queued-task plane).

    Every task submission charges its serialized-args size (plus a small
    per-task overhead) against ``task_queue_memory_cap_bytes``; the charge
    is released when the task reaches a terminal state (reply or failure).
    A submission that would cross the cap BLOCKS its calling user thread
    until enough earlier work drains — so a producer loop submitting
    faster than the cluster executes reaches a steady state instead of
    growing driver RSS without bound (reference analog: the raylet's
    backpressure on task submission queues).  Invariants:

      - at least one submission is always admitted (a single charge larger
        than the cap passes when nothing is queued), so the cap can never
        deadlock a producer;
      - only USER threads block — the protocol loop must never wait on its
        own completions, so charges from the loop thread are
        account-only;
      - a block longer than ``task_queue_block_timeout_s`` raises
        PendingTaskBackpressureTimeout — overload surfaces as a clear
        error, not a silent hang.
    """

    # Fixed per-task cost charged on top of the args payload: spec object,
    # queue slots, return-object records.  Keeps a flood of empty-args
    # tasks bounded too.
    PER_TASK_OVERHEAD = 512

    def __init__(self):
        self._cv = make_condition("core_worker.submit_budget")
        self.queued_bytes = 0
        self.peak_bytes = 0
        self.blocked_total = 0  # submissions that had to wait at least once

    def charge(self, nbytes: int, may_block: bool):
        cap = GlobalConfig.task_queue_memory_cap_bytes
        block_start = None
        try:
            with self._cv:
                if cap > 0 and may_block:
                    deadline = None
                    while self.queued_bytes > 0 and (
                        self.queued_bytes + nbytes > cap
                    ):
                        if block_start is None:
                            block_start = time.monotonic()
                            self.blocked_total += 1
                        if deadline is None:
                            deadline = (
                                time.monotonic()
                                + GlobalConfig.task_queue_block_timeout_s
                            )
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            from .exceptions import (
                                PendingTaskBackpressureTimeout,
                            )

                            raise PendingTaskBackpressureTimeout(
                                f"submission of {nbytes} B blocked "
                                f">{GlobalConfig.task_queue_block_timeout_s}s on "
                                f"the task-queue memory cap ({cap} B, "
                                f"{self.queued_bytes} B queued)"
                            )
                        self._cv.wait(min(remaining, 1.0))
                self.queued_bytes += nbytes
                if self.queued_bytes > self.peak_bytes:
                    self.peak_bytes = self.queued_bytes
        finally:
            # Telemetry outside the cv (the flight recorder takes the
            # metrics lock); runs on both the admitted and timeout paths —
            # the wait happened either way.
            if block_start is not None:
                _fr().record_backpressure_wait(
                    time.monotonic() - block_start
                )

    def release(self, nbytes: int):
        with self._cv:
            self.queued_bytes -= nbytes
            self._cv.notify_all()

    def stats(self) -> dict:
        with self._cv:
            return {
                "queued_bytes": self.queued_bytes,
                "peak_bytes": self.peak_bytes,
                "blocked_total": self.blocked_total,
            }


class _InflightReplies:
    """Exactly-once execution under at-least-once push delivery.

    Transport-level retries of ``push_task``/``actor_push_task`` (the RPC
    layer reconnects and resends after a lost connection or a dropped
    reply) must NOT re-execute the task: the first push claims
    (task_id, attempt) and installs a future; duplicates await the same
    future and receive the same reply.  Completed entries age out FIFO
    (bounded memory); in-flight entries are never evicted.

    Reference analog: the raylet/worker task-dedup on lease retries —
    without it, a dropped REPLY would mean the task ran but the caller
    counts the attempt as failed, and any resend double-executes.
    """

    def __init__(self):
        self._futs: Dict[tuple, asyncio.Future] = {}
        self._order: deque = deque()  # (key, claim_time)

    def _retention_s(self) -> float:
        # An entry must outlive every possible resend of its push: the
        # caller retries after task_push_keepalive_s, so evicting sooner
        # than a couple of windows would let a late resend re-execute.
        return GlobalConfig.task_push_keepalive_s * 2 + 30.0

    def claim(self, key: tuple, loop) -> tuple:
        """Returns (future, is_owner)."""
        fut = self._futs.get(key)
        if fut is not None:
            return fut, False
        fut = loop.create_future()
        self._futs[key] = fut
        now = time.monotonic()
        self._order.append((key, now))
        # Age-based eviction ONLY (never count-based): exactly-once under
        # resends requires completed entries to survive the full resend
        # window regardless of how busy the worker is.
        horizon = now - self._retention_s()
        while self._order and self._order[0][1] < horizon:
            old, _ = self._order[0]
            done = self._futs.get(old)
            if done is not None and not done.done():
                break  # still running; nothing older can be evicted yet
            self._order.popleft()
            self._futs.pop(old, None)
        return fut, True


class OwnedObject:
    __slots__ = (
        "state", "inline_payload", "locations", "size", "local_refs",
        "borrows", "args_holds", "error", "event", "lineage",
        "sync_waiters",
    )

    def __init__(self):
        self.state = PENDING
        self.inline_payload: Optional[bytes] = None
        self.locations: Set[str] = set()  # agent addresses
        self.size = 0
        self.local_refs = 0
        self.borrows = 0
        self.args_holds = 0
        self.error: Optional[BaseException] = None
        self.event = asyncio.Event()
        self.lineage: Optional[TaskSpec] = None  # for reconstruction
        # threading.Events registered by user threads blocked in the
        # no-loop-roundtrip sync get fast path (see CoreWorker.get).
        self.sync_waiters: Optional[List[threading.Event]] = None

    def wake(self):
        """Mark complete: wake loop-side awaiters AND user threads blocked
        in the sync-get fast path.  Loop-thread only."""
        self.event.set()
        waiters = self.sync_waiters
        if waiters:
            for w in waiters:
                w.set()
            self.sync_waiters = None


class _ActorState:
    def __init__(self, actor_id: ActorID):
        self.actor_id = actor_id
        self.address: Optional[str] = None
        self.incarnation = 0
        self.state = "PENDING_CREATION"
        self.death_cause = ""
        self.max_task_retries = 0
        self.changed = asyncio.Event()
        self.next_seq = 0
        self.subscribed = False
        # Serializes wait-for-ALIVE + seq assignment so submission order is
        # preserved even when waiters wake in arbitrary order.  ``waiters``
        # counts submissions queued on (or about to take) the lock: the
        # synchronous ALIVE fast path may only run when it is zero, or it
        # would overtake an earlier submission still parked in the queue.
        self.submit_lock = asyncio.Lock()
        self.waiters = 0
        # Direct-submit coordination (CoreWorker._direct_submit_actor_task):
        # every seq assignment — loop path or user thread — happens under
        # seq_mutex; loop_submits counts loop-path submissions that have
        # not yet been assigned a seq, and the direct path only runs while
        # it is zero, so the two planes can never invert program order.
        self.seq_mutex = threading.Lock()
        self.loop_submits = 0
        # Direct pushes outstanding (accepted, no reply yet).  The direct
        # lane only engages while this is zero: a true sync caller waits
        # out each call so it is always zero at submit time, while an
        # async burst trips it after the first call and falls back to the
        # loop path — which batches frames.  Without this gate a burst
        # degrades into one raw send() syscall per call.
        self.direct_inflight = 0


class _DirectPushHandler(DirectCall):
    """Completion sink for a user-thread direct actor push
    (CoreWorker._direct_submit_actor_task)."""

    __slots__ = ("worker", "spec", "state", "incarnation", "seq")

    def __init__(self, worker: "CoreWorker", spec, state: _ActorState):
        super().__init__()
        self.worker = worker
        self.spec = spec
        self.state = state
        self.incarnation = 0
        self.seq = 0

    def on_reply(self, payload):
        # Fires on the worker's protocol loop — the owner→worker client's
        # read loop lives there — so the loop-affine reply plumbing runs
        # inline, exactly as it does after an awaited call().
        with self.state.seq_mutex:
            self.state.direct_inflight -= 1
        self.worker._handle_task_reply(self.spec, payload)

    def on_error(self, exc: BaseException):
        # May fire on the read loop OR, in teardown races, the submitting
        # thread; recovery touches loop-affine state, so always post.
        # Exactly one of on_reply/on_error fires per submit (the pending
        # table pops the handler before dispatch), so the inflight count
        # cannot double-decrement.
        with self.state.seq_mutex:
            self.state.direct_inflight -= 1
        self.worker._post(
            lambda: self.worker._recover_direct_push(self, exc)
        )


class _LeasePool:
    """Leases + pipelined pushes for one scheduling class
    (NormalTaskSubmitter analog)."""

    def __init__(self, worker: "CoreWorker", sched_class: tuple, template: TaskSpec):
        self.worker = worker
        self.sched_class = sched_class
        self.template = template
        self.queue: asyncio.Queue = asyncio.Queue()
        self.leases: Dict[int, dict] = {}  # lease_id -> {addr, client, inflight}
        self._tasks: set = set()  # in-flight pool coroutines (see _spawn)
        self.requesting = False
        self.idle_cancel: Dict[int, asyncio.TimerHandle] = {}
        self.pending_returns: set = set()  # in-flight return_lease RPCs
        # Per-lease pipelining cap; None = the global knob.  Recovery pools
        # pin it to 1 (see _resubmit_for_recovery); tasks submitted with
        # pipeline_depth carry their own (scheduling_class includes it, so
        # one pool never mixes depths).
        self.max_inflight: Optional[int] = (
            template.pipeline_depth or None
        )

    def submit(self, spec: TaskSpec, attempt: int = 0):
        self.queue.put_nowait((spec, attempt))
        self._pump()

    def _spawn(self, coro) -> bool:
        """create_task if a loop is running; else drop the coroutine.

        _pump/_drop_lease can fire from ``finally`` blocks while the event
        loop is tearing down (GeneratorExit during interpreter shutdown) —
        at that point there is no loop to schedule onto and the work is
        moot anyway.  Tasks are tracked so shutdown can cancel in-flight
        lease requests instead of leaving "Task was destroyed but it is
        pending" noise when the loop stops mid-grant.
        """
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            coro.close()
            return False
        t = loop.create_task(coro)
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)
        return True

    def _pump(self):
        # Dispatch queued tasks onto leases with spare in-flight capacity.
        # Pushes use transport-level call batching: a burst dispatched in
        # one loop pass rides one multiplexed frame with independent
        # per-call replies (see RpcClient.call(batch=True)).
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            # Loop tearing down (e.g. fired from a ``finally`` during
            # interpreter shutdown): bail before dequeuing anything so no
            # spec is dropped with its returns never failed.
            return
        max_inflight = (
            self.max_inflight
            if self.max_inflight is not None
            else GlobalConfig.max_tasks_in_flight_per_worker
        )
        while not self.queue.empty():
            lease = None
            for l in self.leases.values():
                if l["inflight"] < max_inflight and not l["dead"]:
                    lease = l
                    break
            if lease is None:
                self._maybe_request_lease()
                return
            spec, attempt = self.queue.get_nowait()
            if getattr(spec, "_cancelled", False):
                # ray_tpu.cancel: never push it.  A queued-path cancel
                # already failed the returns, but a pushed-then-resubmitted
                # spec (worker died after the cancel notify) has not — its
                # returns still sit in _task_of_return and would hang any
                # get() forever if dropped silently here.
                if any(
                    oid in self.worker._task_of_return
                    for oid in spec.return_ids()
                ):
                    self.worker._fail_task_returns(
                        spec, TaskCancelledError(spec.name)
                    )
                continue
            lease["inflight"] += 1
            # Recorded synchronously at dispatch (same loop thread as
            # cancel_tasks): a spec either has a push address or is still
            # queued — cancel never misses the window in between.
            spec._pushed_addr = lease["addr"]  # type: ignore[attr-defined]
            timer = self.idle_cancel.pop(lease["lease_id"], None)
            if timer:
                timer.cancel()
            self._spawn(self._push(lease, spec, attempt))
        # The queue can drain without a single push (every spec was
        # cancelled): any lease left idle must still get its idle-return
        # timer, or it holds a cluster worker slot for the driver's life.
        for l in self.leases.values():
            if l["inflight"] == 0 and not l["dead"]:
                self._arm_idle(l)

    def _maybe_request_lease(self):
        if self.requesting:
            return
        self.requesting = True
        if not self._spawn(self._request_lease()):
            self.requesting = False

    async def _request_lease(self):
        try:
            agent = self.worker.agent
            payload = {
                "resources": self.template.resources,
                "strategy": self.template.strategy,
                "placement_group_id": self.template.placement_group_id,
                "bundle_index": self.template.bundle_index,
                "env_vars": self.template.env_vars,
                # OOM-defense policy input: only leases whose tasks can be
                # resubmitted should be preferred kill victims.
                "retriable": self.template.max_retries > 0,
                # Stable owner identity: leases survive transport
                # reconnects (grace + owner_ping re-association).
                "owner_id": self.worker.address,
                # Quota admission input for control-plane spillback.
                "job_id": (
                    self.template.job_id.hex()
                    if self.template.job_id else None
                ),
            }
            while True:
                try:
                    reply = await agent.call(
                        "request_lease", payload,
                        timeout=GlobalConfig.worker_startup_timeout_s + 30,
                    )
                except RpcConnectionError:
                    # A spillback target died before (or while) granting —
                    # the control plane may not have noticed yet (health
                    # timeout).  Fall back to the local agent, which will
                    # re-pick a live node; only a dead LOCAL agent is fatal.
                    if agent is self.worker.agent:
                        raise
                    agent = self.worker.agent
                    await asyncio.sleep(0.2)
                    continue
                if reply.get("granted"):
                    lease = {
                        "lease_id": reply["lease_id"],
                        "addr": reply["worker_address"],
                        "client": self.worker.worker_clients.get(
                            reply["worker_address"]
                        ),
                        "inflight": 0,
                        "dead": False,
                        "agent": agent,
                    }
                    self.leases[reply["lease_id"]] = lease
                    if self.queue.empty():
                        # Work drained while we waited for the grant: don't
                        # leak the lease — arm its idle-return timer.
                        self._arm_idle(lease)
                    break
                if reply.get("spillback"):
                    agent = self.worker.agent_clients.get(reply["spillback"])
                    continue
                await asyncio.sleep(0.2)  # cluster full; retry
        except Exception as e:  # noqa: BLE001
            # Fail one queued task so the error surfaces; rest retried later.
            if not self.queue.empty():
                spec, _ = self.queue.get_nowait()
                self.worker._fail_task_returns(spec, e)
        finally:
            self.requesting = False
            if not self.queue.empty():
                self._pump()

    async def _push(self, lease, spec: TaskSpec, attempt: int):
        try:
            # Keepalive re-push: tasks may run arbitrarily long, but an
            # UNBOUNDED reply wait turns a silently lost reply (peer
            # closed between execute and send) into an infinite hang.
            # Bounded waits + resend are SAFE: the worker dedups by
            # (task_id, attempt) (_InflightReplies), so a resend either
            # joins the still-running execution or returns the finished
            # reply instantly — exactly-once execution either way.
            delivered = False
            while True:
                try:
                    reply = await lease["client"].call(
                        "push_task",
                        {"spec": spec, "attempt": attempt},
                        timeout=GlobalConfig.task_push_keepalive_s,
                        retries=3,
                        batch=True,
                    )
                    break
                except RpcTimeoutError:
                    # The request went out and the worker is (still)
                    # executing — a later connection failure is a
                    # mid-execution death, not a failed hand-off.
                    delivered = True
                    continue
            self.worker._handle_task_reply(spec, reply)
        except RpcRemoteError as e:
            # The worker is healthy — the handler itself raised (e.g. the
            # function failed to deserialize).  Fail the task, KEEP the lease.
            self.worker._fail_task_returns(spec, e)
        except RpcConnectionError as e:
            # Worker died: drop the lease (resources are released by the
            # agent's worker monitor) and retry if allowed.
            lease["dead"] = True
            self._drop_lease(lease, returned=False)
            never_started = (
                not delivered and not getattr(e, "maybe_delivered", True)
            )
            if never_started and getattr(spec, "_handoff_retries", 0) < 20:
                # Every connect attempt was refused before the push frame
                # was ever written: the task never started anywhere, so
                # re-leasing it is exactly-once safe whatever its
                # max_retries (that budget is for mid-execution deaths).
                # Typical cause: a lease granted on a node that died in
                # the grant→push window, before the control plane's
                # health check noticed.  Bounded separately so a
                # persistently unreachable grant target cannot spin the
                # submit loop forever.
                spec._handoff_retries = getattr(spec, "_handoff_retries", 0) + 1
                logger.warning(
                    "task %s never reached its leased worker (%s); "
                    "re-leasing (handoff retry %d)",
                    spec.name, e, spec._handoff_retries,
                )
                await asyncio.sleep(0.2)  # let the health check catch up
                spec._pushed_addr = None  # re-queued: cancellable again
                self.submit(spec, attempt)
            elif attempt < spec.max_retries:
                logger.warning(
                    "task %s attempt %d failed (%s); retrying", spec.name, attempt, e
                )
                if spec.streaming:
                    # A retried generator replays from scratch; drop the
                    # dead attempt's undelivered items + stragglers.
                    self.worker._reset_stream_for_retry(spec.task_id)
                spec._pushed_addr = None  # re-queued: cancellable again
                self.submit(spec, attempt + 1)
            else:
                self.worker._fail_task_returns(
                    spec,
                    WorkerCrashedError(f"worker died executing {spec.name}: {e}"),
                )
            return
        finally:
            if not lease["dead"]:
                lease["inflight"] -= 1
        self._pump()
        if lease["inflight"] == 0 and self.queue.empty() and not lease["dead"]:
            self._arm_idle(lease)

    def _arm_idle(self, lease):
        if lease["lease_id"] in self.idle_cancel:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:  # loop tearing down; idle return is moot
            return
        self.idle_cancel[lease["lease_id"]] = loop.call_later(
            GlobalConfig.lease_idle_timeout_s,
            lambda: self._drop_lease(lease, returned=True),
        )

    def _drop_lease(self, lease, returned: bool):
        self.leases.pop(lease["lease_id"], None)
        timer = self.idle_cancel.pop(lease["lease_id"], None)
        if timer:
            timer.cancel()
        if returned:
            # Tracked: shutdown must await in-flight returns, or a lease
            # whose return RPC hasn't flushed stays pinned on the agent
            # for the owner-reap grace period after a clean exit.
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return
            t = loop.create_task(self._return_lease_rpc(lease))
            self.pending_returns.add(t)
            t.add_done_callback(self.pending_returns.discard)

    async def _return_lease_rpc(self, lease):
        try:
            await lease["agent"].call(
                "return_lease", {"lease_id": lease["lease_id"]}, retries=2
            )
        except Exception as e:
            logger.debug("return_lease RPC failed: %s", e)


class ObjectRefGenerator:
    """Iterator over a streaming-generator task's yields (reference:
    ``ObjectRefGenerator``/streaming generator returns).  Each ``next()``
    blocks until the executor pushes the next item and yields an ObjectRef
    whose ``get`` returns the value."""

    def __init__(self, task_id: TaskID, worker: "CoreWorker"):
        self._task_id = task_id
        self._worker = worker

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self):
        kind, value = self._worker._run_sync(
            self._worker._stream_next(self._task_id)
        )
        if kind == "item":
            return value
        self._closed = True
        if kind == "err":
            raise value
        raise StopIteration

    def close(self):
        """Drop the stream (abandoned consumers must not leak the queue
        and undelivered item refs for the process lifetime)."""
        if not getattr(self, "_closed", False):
            self._closed = True
            try:
                self._worker.cancel_stream(self._task_id)
            except Exception:  # raylint: waive[RTL003] shutdown races
                pass

    def __del__(self):
        self.close()


class CoreWorker:
    DRIVER = "driver"
    WORKER = "worker"

    # Owner-service methods the multi-lane RPC server may run directly on
    # a lane thread (see rpc.RpcServer): read-only resolution against the
    # sharded owner table + memory store, with ``ForwardToPrimary`` punts
    # for anything that must wait or mutate (unset events, loss reports,
    # reconstruction).  Everything NOT named here — task pushes, ref
    # counting, streams, cancels — transparently forwards to the primary
    # loop and keeps its single-threaded semantics.
    LANE_SAFE_METHODS = frozenset({
        "get_object",
        "get_object_batch",
        "probe_object",
        "probe_object_batch",
        "ping",
        # Pipeline microbatch pushes deposit into the process-local p2p
        # mailbox (own lock, no owner-table access) — lane execution keeps
        # activation streaming off the primary control loop entirely.
        "pipeline_push",
    })

    def __init__(
        self,
        mode: str,
        cp_address: str,
        agent_address: str,
        session_id: str,
        node_id: NodeID,
        job_id: Optional[JobID] = None,
        worker_id: Optional[WorkerID] = None,
        job_priority: Optional[int] = None,
        job_quota: Optional[Dict[str, float]] = None,
    ):
        self.mode = mode
        self.cp_address = cp_address
        self.agent_address = agent_address
        self.session_id = session_id
        self.node_id = node_id
        self.job_id = job_id or JobID.from_random()
        self.worker_id = worker_id or WorkerID.from_random()
        # Multi-tenant arbitration inputs, shipped with register_job (and
        # every re-register, so they survive a control-plane restart).
        self.job_priority = job_priority
        self.job_quota = dict(job_quota) if job_quota else None

        self.server = RpcServer(
            self, "127.0.0.1", 0,
            lanes=resolve_service_lanes(
                "worker" if mode == self.WORKER else "driver"
            ),
        )
        self.address: str = ""
        self.cp: Optional[RetryableRpcClient] = None
        self.agent: Optional[RetryableRpcClient] = None
        self.agent_clients = ClientPool()
        self.worker_clients = ClientPool()

        self.memory_store = MemoryStore()
        self.shm_store = ShmObjectStore(session_id)
        self.submit_budget = _SubmitBudget()
        # Sharded ownership table: lane threads resolve READY objects
        # against shards directly (see LANE_SAFE_METHODS); all mutation
        # stays on the protocol loop.
        self.owned: OwnerTable = OwnerTable(GlobalConfig.owner_table_shards)
        self.lease_pools: Dict[tuple, _LeasePool] = {}
        self.actors: Dict[ActorID, _ActorState] = {}

        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread: Optional[threading.Thread] = None
        self._fn_cache: Dict[str, Any] = {}
        self._exported_fns: Set[str] = set()
        self._task_executor = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="task"
        )
        self._exec_pipeline: Optional[ExecPipeline] = None  # created on loop
        # Actor-execution state (when this worker hosts an actor)
        self.actor_instance = None
        self.actor_spec: Optional[ActorSpec] = None
        self.actor_incarnation = 0
        self._actor_exec_lock: Optional[asyncio.Semaphore] = None
        self._actor_seq_state: Dict[tuple, dict] = {}  # (caller, inc) -> {expected, buffer}
        self._current_task_name = ""
        self._shutdown = False
        self._inflight_submits: set = set()  # cancelled at shutdown
        self.task_events = None  # TaskEventBuffer, created on the loop
        # Streaming-generator returns: task_id -> stream state.  The item
        # queue holds ("item", ref) | ("end", None) | ("err", exc); "end"
        # enqueues only after ALL `expected` items arrived (stream notifies
        # and the task reply travel on different sockets and may reorder).
        self._streams: Dict[TaskID, dict] = {}
        # In-flight lineage reconstructions, keyed by creating task id
        # (reference: core_worker/object_recovery_manager.h:41 — concurrent
        # gets of lost objects share one resubmission).
        self._reconstructions: Dict[TaskID, asyncio.Future] = {}
        self._recovery_waiters: Dict[TaskID, asyncio.Event] = {}
        # Cross-thread callback batching: a burst of submissions/ref events
        # from user threads wakes the loop once, not once per callback.
        self._post_lock = make_lock("core_worker.post_queue")
        self._post_queue: List = []
        # Borrowed refs this process re-serialized (lent onward): their
        # outgoing decref is grace-delayed.  See on_ref_relent.
        self._relent_refs: Set[ObjectID] = set()
        # token -> (timer handle, fn): grace-delayed ref ops, flushed
        # immediately at shutdown (see _delay_refop).
        self._delayed_refops: Dict[object, tuple] = {}
        # Data-plane fast path state: borrowed-object location cache +
        # batched-get counters (published by the flight recorder flush).
        self._loc_cache = _LocationCache()
        self._batch_get_calls = 0
        self._batch_get_refs = 0
        # Owner-service shard accounting: entries served by the lock-free
        # READY fast path (any lane) vs punted to the primary loop.
        self._shard_fast_entries = 0
        self._shard_forwarded_entries = 0
        # Best-effort task cancellation (ray_tpu.cancel).  Owner side:
        # return-object id -> live TaskSpec for normal tasks, pruned when
        # the task reply lands or its returns fail.  Executor side:
        # _pending_exec_tasks holds ids of pushed-but-not-replied normal
        # tasks; a cancel notify is recorded in _cancelled_tasks only for
        # a pending task (push and cancel share one ordered connection,
        # so an absent id means the task already replied) and is dropped
        # again when the reply goes out — a stale entry would wrongly
        # skip a later re-execution of the same task id (retry / lineage
        # reconstruction).  _cancelled_order bounds the set as a backstop.
        self._task_of_return: Dict[ObjectID, TaskSpec] = {}
        self._pending_exec_tasks: Set[TaskID] = set()
        self._cancelled_tasks: Set[TaskID] = set()
        self._cancelled_order: deque = deque()
        self._tasks_cancelled = 0  # owner-side accepted cancels

    def _post(self, cb) -> None:
        """Run ``cb()`` on the protocol loop; bursts coalesce into a single
        loop wakeup (the per-call ``call_soon_threadsafe`` socketpair write
        was the dominant cost of high-rate submission from user threads)."""
        with self._post_lock:
            self._post_queue.append(cb)
            if len(self._post_queue) > 1:
                return  # a drain is already scheduled
        try:
            self.loop.call_soon_threadsafe(self._drain_posts)
        except RuntimeError:
            # Loop already closed (interpreter teardown racing GC-driven
            # ref releases): drop the callback, nothing left to run it on.
            with self._post_lock:
                self._post_queue.clear()

    def _drain_posts(self) -> None:
        # One swap per invocation: callbacks posted while this batch runs
        # schedule their own drain (the len==1 guard in _post), so a fast
        # producer cannot starve the event loop inside one callback.
        with self._post_lock:
            cbs, self._post_queue = self._post_queue, []
        for cb in cbs:
            try:
                cb()
            except Exception:  # noqa: BLE001 — isolate callbacks
                logger.exception("posted callback failed")

    # ------------------------------------------------------------- lifecycle
    async def async_start(self):
        self.loop = asyncio.get_running_loop()
        self._exec_pipeline = ExecPipeline(asyncio.get_running_loop())
        self._lane_pool = None  # created at actor init for max_concurrency>1
        self._inflight_replies = _InflightReplies()
        self.address = await self.server.start()
        cp_ha_dir = os.environ.get("RAY_TPU_CP_HA_DIR")
        cp_resolver = None
        if cp_ha_dir:
            from .cp_ha import make_cp_resolver

            cp_resolver = make_cp_resolver(cp_ha_dir, self.cp_address)
        self.cp = RetryableRpcClient(
            self.cp_address,
            push_handler=self._on_push,
            address_resolver=cp_resolver,
        )
        self.agent = RetryableRpcClient(self.agent_address)
        from .task_events import TaskEventBuffer

        self.task_events = TaskEventBuffer(
            self.cp, self.node_id.hex(), self.worker_id.hex()
        )
        # Leased workers are drained by their node agent's heartbeat pull
        # (obs_pull); their own flush loop drops to a backup cadence.
        # Drivers have no agent pulling them and keep the fast loop.
        self.task_events.pull_mode = (
            self.mode == self.WORKER and GlobalConfig.enable_obs_aggregator
        )
        self.task_events.start()
        # obs_pull staging (at-least-once): the last pull reply is kept
        # until the agent acks it on a later pull.
        self._obs_pending = None
        self._obs_batch_seq = 0
        if self.mode == self.DRIVER:
            await self.cp.call(
                "register_job",
                {"job_id": self.job_id, "driver_address": self.address,
                 "priority": self.job_priority, "quota": self.job_quota},
            )
            self._heartbeat_task = self.loop.create_task(
                self._job_heartbeat_loop()
            )
        return self.address

    async def _job_heartbeat_loop(self):
        """Job liveness signal; survives transient control-plane reconnects
        (and re-registers if the control plane restarted)."""
        period = GlobalConfig.health_check_period_s
        while not self._shutdown:
            await asyncio.sleep(period)
            try:
                reply = await self.cp.call(
                    "job_heartbeat", {"job_id": self.job_id}, retries=1
                )
                if reply.get("reregister"):
                    await self.cp.call(
                        "register_job",
                        {"job_id": self.job_id,
                         "driver_address": self.address,
                         "priority": self.job_priority,
                         "quota": self.job_quota},
                        retries=1,
                    )
            except Exception as e:
                logger.debug("driver reregister failed: %s", e)
            # Lease re-association + liveness toward EVERY agent that
            # granted this driver a lease (spillback leases live on remote
            # agents whose socket may sit idle while pushes go straight to
            # the worker): after a client reconnect these pings rebind the
            # leases to the new connection before the grace expires.
            agents = {id(self.agent): self.agent} if self.agent else {}
            for pool in list(self.lease_pools.values()):
                for lease in list(pool.leases.values()):
                    granter = lease.get("agent")
                    if granter is not None:
                        agents[id(granter)] = granter
            for agent in agents.values():
                try:
                    await agent.notify(
                        "owner_ping", {"owner_id": self.address}
                    )
                except Exception as e:
                    logger.debug("owner_ping to agent failed: %s", e)

    def start_threaded(self):
        """Driver mode: run the protocol loop on a background thread."""
        ready = threading.Event()
        err: List[BaseException] = []

        def run():
            prof = _maybe_start_profile()
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self.loop = loop

            async def boot():
                try:
                    await self.async_start()
                finally:
                    ready.set()

            try:
                loop.run_until_complete(boot())
                loop.run_forever()
            except BaseException as e:  # noqa: BLE001
                err.append(e)
                ready.set()
            finally:
                _maybe_dump_profile(prof, "driver-loop")
                try:
                    loop.close()
                except Exception as e:
                    logger.debug("loop close failed at thread exit: %s", e)

        self._loop_thread = threading.Thread(target=run, daemon=True, name="core-worker")
        self._loop_thread.start()
        ready.wait(timeout=30)
        if err:
            raise err[0]
        if not self.address:
            raise RuntimeError("core worker failed to start")

    def _run_sync(self, coro, timeout=None):
        """Bridge from user threads into the protocol loop."""
        if self.loop is None:
            raise RuntimeError("core worker not started")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    async def async_shutdown(self):
        self._shutdown = True
        # Pending grace-delayed decrefs/releases fire NOW (their sends get
        # one loop tick to reach the wire before clients close).
        self._flush_delayed_refops()
        await asyncio.sleep(0)
        for t in list(self._inflight_submits):
            if not t.done():
                t.cancel()
        # Return every held lease NOW.  Leases are keyed to a stable owner
        # id with a reconnect grace window (chaos hardening), so a clean
        # exit that merely closes its sockets would pin the node's
        # resources for the full grace period — starving whatever runs
        # next on the cluster.  Idle-return timers are cancelled first
        # (their _drop_lease would race this sweep), and a second pass
        # catches leases landed by in-flight grant replies mid-shutdown.
        pools = list(self.lease_pools.values())
        for pool in pools:
            for timer in pool.idle_cancel.values():
                timer.cancel()
            pool.idle_cancel.clear()
        for _ in range(2):
            returns = []
            for pool in pools:
                for lease in list(pool.leases.values()):
                    pool.leases.pop(lease["lease_id"], None)
                    returns.append(pool._return_lease_rpc(lease))
                returns.extend(pool.pending_returns)
                pool.pending_returns = set()
            if returns:
                try:
                    await asyncio.wait_for(
                        asyncio.gather(*returns, return_exceptions=True),
                        timeout=2.0,
                    )
                except Exception:  # raylint: waive[RTL003] agent may be gone
                    pass
            await asyncio.sleep(0)
        # Only AFTER the return sweep: cancel in-flight pool coroutines so
        # the stopping loop leaves no destroyed-pending-task noise.
        # Cancelling BEFORE would defeat the sweep's second pass — a lease
        # granted server-side whose reply is still in flight would never
        # land in pool.leases and never be returned, pinning the node's
        # resources for the reconnect-grace window.
        for pool in pools:
            for t in list(pool._tasks):
                if not t.done():
                    t.cancel()
        # Ordered teardown (reference: core_worker/shutdown_coordinator.h):
        # cancel periodic loops first so nothing is left pending when the
        # event loop stops.
        hb = getattr(self, "_heartbeat_task", None)
        if hb is not None and not hb.done():
            hb.cancel()
            try:
                await hb
            except (asyncio.CancelledError, Exception):  # raylint: waive[RTL003] awaiting a cancelled task raises by design
                pass
        if self.task_events is not None:
            try:
                await asyncio.wait_for(self.task_events.stop(), timeout=2)
            except Exception as e:
                logger.debug("task-event stop flush failed: %s", e)
        await self._flush_obs_pending()
        # Final metrics push: a short-lived worker/driver must not silently
        # lose the last _FLUSH_INTERVAL_S window of counters on exit.
        try:
            await asyncio.wait_for(self._flush_metrics(), timeout=2)
        except Exception as e:
            logger.debug("final metrics flush failed: %s", e)
        if self._exec_pipeline is not None:
            self._exec_pipeline.stop()
        if self._lane_pool is not None:
            self._lane_pool.stop()
        await self.server.stop()
        for pool in (self.worker_clients, self.agent_clients):
            await pool.close_all()
        if self.cp:
            await self.cp.close()
        if self.agent:
            await self.agent.close()

    async def _flush_metrics(self):
        """Push the local metrics registry to the control plane NOW (loop
        coroutine — bypasses the blocking kv_put bridge)."""
        from ray_tpu.util import metrics as _metrics

        try:
            # Fold the data-plane fast-path counters (framing/batch-get/
            # location-cache ints) into the registry before snapshotting.
            _fr().record_data_plane(self)
        except Exception as e:
            logger.debug("data-plane counter publish failed: %s", e)
        payload = _metrics.payload_snapshot()
        if payload is not None and self.cp is not None:
            await _metrics._kv_put_async(self, payload)

    async def _flush_obs_pending(self):
        """Deliver an unacked obs_pull staging batch straight to the
        control plane (exit path: the agent will never re-pull us).  On
        failure the loss is counted — never silent."""
        pending = getattr(self, "_obs_pending", None)
        if pending is None or self.cp is None:
            return
        te = self.task_events
        try:
            await asyncio.wait_for(
                self.cp.call("task_events", {
                    "events": pending["events"],
                    "profile_events": pending["profile_events"],
                    "worker_id": self.worker_id.hex(),
                    "span_drops": te.num_span_dropped if te else 0,
                }),
                timeout=2,
            )
            self._obs_pending = None
        except Exception as e:  # noqa: BLE001 — exit flush is best-effort
            if te is not None:
                te._count_dropped(
                    len(pending["events"]) + len(pending["profile_events"]),
                    spans=te._count_spans(pending["profile_events"]),
                )
            logger.debug("obs pending flush failed on exit: %s", e)

    async def _flush_observability(self):
        """Flush the task-event buffer AND the metrics registry — the final
        window must survive worker disconnect/exit."""
        if self.task_events is not None:
            try:
                await asyncio.wait_for(self.task_events.flush(), timeout=2)
            except Exception as e:
                logger.debug("task-event flush failed on disconnect: %s", e)
        await self._flush_obs_pending()
        try:
            await asyncio.wait_for(self._flush_metrics(), timeout=2)
        except Exception as e:
            logger.debug("metrics flush failed on disconnect: %s", e)

    def shutdown(self):
        if self.loop and self._loop_thread:
            try:
                self._run_sync(self.async_shutdown(), timeout=5)
            except Exception as e:
                logger.debug("async shutdown failed: %s", e)
            try:
                self.loop.call_soon_threadsafe(self.loop.stop)
            except RuntimeError:
                pass  # loop already closed — don't abort the caller's
                # teardown (node.stop() must still run)
            self._loop_thread.join(timeout=5)
        self._task_executor.shutdown(wait=False)

    # ----------------------------------------------------------------- puts
    def _new_owned(self, object_id: ObjectID, lineage=None) -> OwnedObject:
        obj = OwnedObject()
        obj.lineage = None
        self.owned[object_id] = obj
        if lineage is not None and GlobalConfig.lineage_pinning:
            self._lineage_attach(obj, lineage)
        return obj

    async def _put_async(self, value: Any) -> ObjectRef:
        from .serialization import (
            is_plain_data,
            serialize,
            serialized_nbytes,
            write_serialized,
        )

        oid = new_object_id()
        obj = self._new_owned(oid)
        obj.local_refs += 1
        header, views = serialize(value, prefer_plain=is_plain_data(value))
        size = serialized_nbytes(header, views)
        obj.size = size
        if size <= GlobalConfig.max_inline_object_bytes:
            buf = bytearray(size)
            write_serialized(header, views, buf)
            obj.inline_payload = bytes(buf)
            self.memory_store.put(oid, value)
        else:
            # Zero-copy: pickle-5 buffers memcpy straight into the arena.
            # The arena entry is sealed natively before this returns, so
            # readers (local mmap or agent chunk reads, which fall back to
            # the arena) never race it; the agent-side directory seal is
            # only eviction bookkeeping and rides a pipelined oneway frame
            # — FIFO on the agent connection, so any later free/pull on
            # this conn observes it.  Skipping the awaited round trip is
            # worth ~20% put bandwidth at 64 MiB.  Any DISK-bound write
            # (arena-oversized value, or shm exhaustion discovered
            # mid-write — NeedsSpill) moves to an executor thread: a
            # multi-GiB disk write must not stall the protocol loop.  An
            # exhausted spill tier raises ObjectStoreFullError — the put
            # fails loudly instead of hanging or SIGBUS-ing on tmpfs —
            # and a failed put must not strand its owned record.
            try:
                from .object_store import NeedsSpill

                try:
                    _, tier = self.shm_store.create_serialized(
                        oid, header, views, inline_spill_ok=False
                    )
                except NeedsSpill:
                    loop = asyncio.get_running_loop()
                    _, tier = await loop.run_in_executor(
                        None, self.shm_store.create_serialized,
                        oid, header, views,
                    )
            except BaseException:
                self.owned.pop(oid, None)
                self.memory_store.free(oid)
                raise
            await self.agent.notify(
                "seal_object", {"object_id": oid, "size": size, "tier": tier}
            )
            obj.locations.add(self.agent_address)
            if tier != "spill":
                # Local cache for owner gets.  Spilled values stay on
                # disk: caching would pin an arena-oversized value in the
                # driver heap — exactly the RSS growth spilling avoids.
                self.memory_store.put(oid, value)
        obj.state = READY
        obj.wake()
        ref = ObjectRef.__new__(ObjectRef)
        ref.id = oid
        ref.owner_address = self.address
        ref._worker = self
        return ref

    def put(self, value: Any) -> ObjectRef:
        return self._run_sync(self._put_async(value))

    # ----------------------------------------------------------------- gets
    async def get_async(self, ref: ObjectRef, timeout: Optional[float] = None):
        try:
            return await asyncio.wait_for(self._get_one(ref), timeout)
        except asyncio.TimeoutError:
            raise GetTimeoutError(f"get() timed out on {ref}")

    async def _get_one(self, ref: ObjectRef):
        oid = ref.id
        if ref.owner_address == self.address:
            obj = self.owned.get(oid)
            if obj is None:
                # Owned but already freed, or unknown.
                if self.memory_store.contains(oid):
                    return self.memory_store.peek(oid)
                raise ObjectLostError(oid.hex(), "owner has no record")
            await obj.event.wait()
            if obj.state == ERROR:
                raise obj.error
            if self.memory_store.contains(oid):
                return self.memory_store.peek(oid)
            if obj.inline_payload is not None:
                value = deserialize_from_bytes(obj.inline_payload)
                self.memory_store.put(oid, value)
                return value
            for attempt in range(GlobalConfig.max_object_reconstructions + 1):
                try:
                    return await self._fetch_from_locations(
                        oid, sorted(obj.locations)
                    )
                except Exception as fetch_exc:  # noqa: BLE001 — loss shapes vary
                    if (
                        obj.lineage is None
                        or attempt >= GlobalConfig.max_object_reconstructions
                    ):
                        if isinstance(fetch_exc, ObjectLostError):
                            raise
                        raise ObjectLostError(oid.hex(), str(fetch_exc))
                    await self._reconstruct_object(oid, obj)
                    if obj.state == ERROR:
                        raise obj.error
                    if obj.inline_payload is not None:
                        value = deserialize_from_bytes(obj.inline_payload)
                        self.memory_store.put(oid, value)
                        return value
        # Borrowed object: resolve via the owner.
        if self.memory_store.contains(oid):
            return self.memory_store.peek(oid)
        return await self._get_borrowed(ref)

    async def _get_borrowed(self, ref: ObjectRef, lost: Optional[list] = None):
        oid = ref.id
        cache = self._loc_cache
        if not lost:
            # Location-cache fast path: a stable shm object fetches with
            # zero owner round-trips after the first resolution.
            cached = cache.lookup(oid)
            if cached is not None:
                try:
                    return await self._fetch_from_locations(oid, cached)
                except Exception as fetch_exc:  # noqa: BLE001 — any miss falls to the owner
                    cache.invalidate(oid)
                    lost = list(getattr(fetch_exc, "failed_locations", ()))
        lost = lost or []
        owner = self.worker_clients.get(ref.owner_address)
        for attempt in range(GlobalConfig.max_object_reconstructions + 1):
            # The owner's handler blocks until the producing task finishes
            # (and reconstructs lost values) — don't let the default RPC
            # deadline fire.  Record the generation BEFORE the call: a
            # loss observed while the reply is in flight must fence the
            # fill below.
            gen = cache.generation
            reply = await owner.call(
                "get_object", {"object_id": oid, "lost_locations": lost},
                timeout=UNBOUNDED,
            )
            kind = reply["kind"]
            if kind == "inline":
                value = deserialize_payload(reply["payload"])
                self.memory_store.put(oid, value)
                return value
            if kind == "error":
                raise deserialize_payload(reply["payload"])
            cache.fill(oid, reply["locations"], gen)
            try:
                # shm: fetch via local agent (zero-copy if node-local)
                return await self._fetch_from_locations(
                    oid, reply["locations"]
                )
            except Exception as fetch_exc:  # noqa: BLE001
                # Report ONLY the copies actually tried and failed back to
                # the owner, which prunes them and reconstructs via
                # lineage if none remain (borrower-observed loss;
                # reference: ownership_object_directory + recovery).
                # Claiming every listed copy died would trigger needless
                # lineage reconstruction of still-healthy replicas.
                cache.invalidate(oid)
                lost = list(getattr(fetch_exc, "failed_locations", ()))
                if attempt >= GlobalConfig.max_object_reconstructions:
                    raise ObjectLostError(oid.hex(), str(fetch_exc))
        raise ObjectLostError(oid.hex(), "reconstruction attempts exhausted")

    async def _reconstruct_object(self, oid: ObjectID, obj: "OwnedObject"):
        """Re-run the creating task to rebuild a lost object (reference:
        core_worker/object_recovery_manager.h:41 — all alternate copies are
        gone, so resubmit via lineage).  Concurrent losses of sibling
        return objects share one resubmission."""
        spec = obj.lineage
        fut = self._reconstructions.get(spec.task_id)
        if fut is None:
            fut = asyncio.ensure_future(self._resubmit_for_recovery(spec))
            self._reconstructions[spec.task_id] = fut
            fut.add_done_callback(
                lambda _f: self._reconstructions.pop(spec.task_id, None)
            )
        await asyncio.shield(fut)
        # The resubmission repopulated this object's record; wait for it.
        target = self.owned.get(oid)
        if target is not None:
            await target.event.wait()

    async def _resubmit_for_recovery(self, spec: TaskSpec):
        logger.warning(
            "reconstructing lost object(s) of task %s (%s) via lineage",
            spec.task_id.hex()[:8], spec.name,
        )
        attempt = 0
        if spec.streaming:
            state = self._streams.get(spec.task_id)
            if state is None:
                self._new_stream(spec.task_id, spec)
                state = self._streams[spec.task_id]
                watermark = 10**12  # finished stream: every index is old
            else:
                watermark = state["received"]
                self._reset_stream_for_retry(spec.task_id)
            # Replay-for-recovery: indices the consumer already received
            # ([0, watermark)) are recorded without new refs or enqueues;
            # the live tail (>= watermark) streams to the consumer normally.
            state["recovery_replay"] = True
            state["replay_watermark"] = watermark
            if watermark < 10**12:
                state["received"] = watermark  # old items stay counted
            attempt = state["attempt"]
            # Reset every still-owned item record of this stream so getters
            # wait for the replayed values instead of reading dead
            # locations.
            for robj in list(self.owned.values()):  # user threads insert (submit paths)
                if robj.lineage is spec:
                    robj.state = PENDING
                    robj.error = None
                    robj.inline_payload = None
                    robj.locations = set()
                    robj.event = asyncio.Event()
        else:
            for roid in spec.return_ids():
                robj = self.owned.get(roid)
                if robj is None:
                    continue  # freed meanwhile; the task may still re-run
                robj.state = PENDING
                robj.error = None
                robj.inline_payload = None
                robj.locations = set()
                robj.event = asyncio.Event()
        self.task_events.record(
            spec.task_id.hex(), spec.name, "PENDING_RECONSTRUCTION",
            job_id_hex=spec.job_id.hex(), resources=spec.resources,
        )
        # Recovery submissions use a DEDICATED pool with one task per
        # lease: a shared lease could pipeline the re-execution behind a
        # task that is blocked waiting for this very object (observed
        # deadlock: consume(x) holds the worker while x's producer queues
        # behind it).  One-per-lease also keeps chained reconstructions
        # (b needs a, a lost too) on separate workers.
        sched_key = (spec.scheduling_class, "__recovery__")
        pool = self.lease_pools.get(sched_key)
        if pool is None:
            pool = _LeasePool(self, sched_key, spec)
            pool.max_inflight = 1
            self.lease_pools[sched_key] = pool
        done = asyncio.Event()
        self._recovery_waiters[spec.task_id] = done
        pool.submit(spec, attempt)
        try:
            await done.wait()
        finally:
            self._recovery_waiters.pop(spec.task_id, None)

    async def _fetch_from_locations(self, oid: ObjectID, locations: List[str]):
        if not locations:
            raise ObjectLostError(oid.hex(), "no locations")
        # Track which copies this attempt actually touched: on failure the
        # exception carries them so loss reporting prunes exactly those
        # (never the untouched replicas).
        if self.agent_address not in locations:
            tried = (locations[0],)
        else:
            tried = (self.agent_address,)
        try:
            if self.agent_address not in locations:
                await self.agent.call(
                    "pull_object",
                    {"object_id": oid, "from_agent": locations[0]},
                    timeout=GlobalConfig.rpc_call_timeout_s * 4,
                )
            loop = asyncio.get_running_loop()
            value = await loop.run_in_executor(None, self.shm_store.get, oid)
        except BaseException as e:
            try:
                e.failed_locations = tried  # type: ignore[attr-defined]
            except Exception:  # raylint: waive[RTL003] exotic exception refuses attrs; loss report degrades
                pass
            raise
        self.memory_store.put(oid, value)
        return value

    async def _fetch_batch(self, items: List[tuple]) -> List[Any]:
        """Fetch ``[(oid, locations)]`` shm objects as one batch: remote
        pulls fan in through a single ``pull_objects`` agent RPC, and the
        local arena reads + deserialization for the whole batch ride ONE
        executor hop instead of one per object.  Returns a value or the
        per-object exception in each slot (callers fall back to the
        robust per-ref path for failed slots)."""
        pulls = [
            (oid, locations[0])
            for oid, locations in items
            if locations and self.agent_address not in locations
        ]
        failures: Dict[ObjectID, BaseException] = {}
        if pulls:
            try:
                reply = await self.agent.call(
                    "pull_objects", {"items": pulls},
                    timeout=GlobalConfig.rpc_call_timeout_s * 4,
                )
                for (oid, src), err in zip(pulls, reply["errors"]):
                    if err is not None:
                        e = ObjectLostError(oid.hex(), err)
                        e.failed_locations = (src,)  # type: ignore[attr-defined]
                        failures[oid] = e
            except RpcRemoteError:
                # Agent predates the batch RPC: fall back to per-object
                # pulls (still concurrent).
                outcomes = await asyncio.gather(
                    *(
                        self.agent.call(
                            "pull_object",
                            {"object_id": oid, "from_agent": src},
                            timeout=GlobalConfig.rpc_call_timeout_s * 4,
                        )
                        for oid, src in pulls
                    ),
                    return_exceptions=True,
                )
                for (oid, src), outcome in zip(pulls, outcomes):
                    if isinstance(outcome, BaseException):
                        try:
                            outcome.failed_locations = (src,)  # type: ignore[attr-defined]
                        except Exception:  # raylint: waive[RTL003] exotic exception refuses attrs
                            pass
                        failures[oid] = outcome

        def read_all():
            out = []
            for oid, locations in items:
                failed = failures.get(oid)
                if failed is not None:
                    out.append(failed)
                    continue
                try:
                    out.append(self.shm_store.get(oid))
                except BaseException as e:  # noqa: BLE001 — per-slot isolation
                    if self.agent_address in locations:
                        tried = (self.agent_address,)
                    else:
                        tried = tuple(locations[:1])
                    try:
                        e.failed_locations = tried  # type: ignore[attr-defined]
                    except Exception:  # raylint: waive[RTL003] exotic exception refuses attrs
                        pass
                    out.append(e)
            return out

        loop = asyncio.get_running_loop()
        values = await loop.run_in_executor(None, read_all)
        for (oid, _locations), value in zip(items, values):
            if not isinstance(value, BaseException):
                self.memory_store.put(oid, value)
        return values

    async def _get_batch_from_owner(
        self, owner_address: str, refs: List[ObjectRef]
    ) -> List[Any]:
        """Resolve borrowed refs sharing one owner with a single
        ``get_object_batch`` RPC (mixed inline/shm/error entries), shm
        fetches for the batch issued as one concurrent fan-in."""
        oids = [r.id for r in refs]
        cache = self._loc_cache
        self._batch_get_calls += 1
        self._batch_get_refs += len(refs)
        owner = self.worker_clients.get(owner_address)
        gen = cache.generation
        try:
            reply = await owner.call(
                "get_object_batch", {"object_ids": oids}, timeout=UNBOUNDED
            )
        except RpcRemoteError:
            # Owner predates the batch RPC: per-ref resolution.
            return list(
                await asyncio.gather(*(self._get_one(r) for r in refs))
            )
        entries = reply["entries"]
        results: List[Any] = [None] * len(refs)
        fetch_items: List[tuple] = []  # (slot, locations)
        for i, entry in enumerate(entries):
            kind = entry["kind"]
            if kind == "inline":
                value = deserialize_payload(entry["payload"])
                self.memory_store.put(oids[i], value)
                results[i] = value
            elif kind == "error":
                raise deserialize_payload(entry["payload"])
            else:
                cache.fill(oids[i], entry["locations"], gen)
                fetch_items.append((i, entry["locations"]))
        if fetch_items:
            try:
                values = await self._fetch_batch(
                    [(oids[i], locations) for i, locations in fetch_items]
                )
            except Exception as batch_exc:  # noqa: BLE001
                # Transport-level batch failure (pull deadline over N
                # concurrent pulls, agent reconnect): recover per-ref via
                # the robust path — it retries, reports losses, and
                # surfaces the documented error types instead of a raw
                # transport error aborting the whole get.
                logger.debug("batched fetch failed, per-ref fallback: %s",
                             batch_exc)
                fetched = await asyncio.gather(
                    *(self._get_borrowed(refs[i]) for i, _ in fetch_items)
                )
                for (i, _locations), value in zip(fetch_items, fetched):
                    results[i] = value
                return results
            for (i, _locations), value in zip(fetch_items, values):
                if isinstance(value, BaseException):
                    # Slot failed: retry via the robust per-ref path,
                    # reporting exactly the copies that failed.
                    cache.invalidate(oids[i])
                    results[i] = await self._get_borrowed(
                        refs[i],
                        lost=list(getattr(value, "failed_locations", ())),
                    )
                else:
                    results[i] = value
        return results

    async def _get_many(self, refs: List[ObjectRef]) -> List[Any]:
        """Resolve many refs concurrently.  Borrowed refs are grouped by
        owner into one vectorized ``get_object_batch`` call per owner —
        an N-ref get costs one round-trip per owner, not N."""
        results: List[Any] = [None] * len(refs)
        owner_groups: Dict[str, List[int]] = {}
        coros: List = []
        slots: List[tuple] = []
        for i, ref in enumerate(refs):
            if ref.owner_address == self.address:
                coros.append(self._get_one(ref))
                slots.append((i,))
            elif self.memory_store.contains(ref.id):
                results[i] = self.memory_store.peek(ref.id)
            else:
                owner_groups.setdefault(ref.owner_address, []).append(i)
        for owner_address, idxs in owner_groups.items():
            if len(idxs) == 1:
                coros.append(self._get_one(refs[idxs[0]]))
                slots.append((idxs[0],))
            else:
                coros.append(
                    self._get_batch_from_owner(
                        owner_address, [refs[i] for i in idxs]
                    )
                )
                slots.append(tuple(idxs))
        if coros:
            outs = await asyncio.gather(*coros)
            for slot, out in zip(slots, outs):
                if len(slot) == 1:
                    results[slot[0]] = out
                else:
                    for j, i in enumerate(slot):
                        results[i] = out[j]
        return results

    _GET_MISS = object()  # sentinel: fast path can't serve, use the loop

    def _try_get_sync(self, refs, timeout: Optional[float]):
        """Resolve self-owned inline/in-memory results WITHOUT a protocol
        loop round trip: the user thread parks on a threading.Event that
        the reply handler wakes directly (OwnedObject.wake).  This removes
        the run_coroutine_threadsafe wakeup + gather machinery from the
        hot sync-call path (~2x on 1:1 sync calls on a 1-core box) and
        moves result deserialization off the protocol loop.  Returns
        _GET_MISS if any ref needs the full path (borrowed, shm-located,
        or reconstruction)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            if ref.owner_address != self.address:
                return self._GET_MISS
            oid = ref.id
            obj = self.owned.get(oid)
            if obj is None:
                if self.memory_store.contains(oid):
                    out.append(self.memory_store.peek(oid))
                    continue
                return self._GET_MISS
            if not obj.event.is_set():
                ev = threading.Event()
                waiters = obj.sync_waiters
                if waiters is None:
                    waiters = obj.sync_waiters = []
                waiters.append(ev)
                # Re-check after registering: wake() may have run between
                # the is_set probe and the append (it reads sync_waiters
                # after setting the event, so one side always sees the
                # other).
                if not obj.event.is_set():
                    remaining = (
                        None if deadline is None
                        else max(0.0, deadline - time.monotonic())
                    )
                    if not ev.wait(remaining):
                        raise GetTimeoutError(
                            f"get() timed out on {len(refs)} object(s)"
                        )
            if obj.state == ERROR:
                raise obj.error
            if self.memory_store.contains(oid):
                out.append(self.memory_store.peek(oid))
            elif obj.inline_payload is not None:
                value = deserialize_from_bytes(obj.inline_payload)
                self.memory_store.put(oid, value)
                out.append(value)
            elif self.agent_address in obj.locations:
                # Locally-available shm object: read + deserialize HERE,
                # on the user thread — no protocol-loop round trip and no
                # executor handoff (those two wakeups dominated repeated
                # gets of stable shm objects).  The arena is cross-process
                # locked and acquire() pins the block, so a user-thread
                # read is as safe as the loop's executor read.
                try:
                    value = self.shm_store.get(oid)
                except Exception:  # noqa: BLE001 — evicted/spill race: full path recovers
                    return self._GET_MISS
                self.memory_store.put(oid, value)
                out.append(value)
            else:
                return self._GET_MISS  # remote locations / reconstruction
        return out

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        # One deadline across both paths: time the fast path burned
        # waiting before a _GET_MISS must not be granted again to the
        # async fallback.
        deadline = None if timeout is None else time.monotonic() + timeout
        results = self._try_get_sync(refs, timeout)
        if results is not self._GET_MISS:
            return results[0] if single else results
        if deadline is not None:
            timeout = max(0.001, deadline - time.monotonic())

        async def get_all():
            # Resolve concurrently: borrowed refs group into one batched
            # owner call per owner (see _get_many), and remote-owner
            # round-trips / shm pulls overlap instead of summing.  One
            # deadline timer covers the whole batch (not one per ref) —
            # same semantics, since every ref resolves concurrently under
            # the same timeout.
            if timeout is None:
                return await self._get_many(refs)
            try:
                return await asyncio.wait_for(self._get_many(refs), timeout)
            except asyncio.TimeoutError:
                raise GetTimeoutError(
                    f"get() timed out on {len(refs)} object(s)"
                )

        results = self._run_sync(get_all())
        return results[0] if single else results

    # ----------------------------------------------------------------- wait
    async def _probe_many(self, refs: List[ObjectRef]) -> List[bool]:
        """Readiness probes with the same owner-grouping as _get_many: one
        probe_object_batch RPC per owner per poll pass, not one per ref."""
        out = [False] * len(refs)
        remote: Dict[str, List[int]] = {}
        for i, ref in enumerate(refs):
            oid = ref.id
            if ref.owner_address == self.address:
                obj = self.owned.get(oid)
                out[i] = (
                    self.memory_store.contains(oid)
                    if obj is None
                    else obj.event.is_set()
                )
            elif self.memory_store.contains(oid):
                out[i] = True
            else:
                remote.setdefault(ref.owner_address, []).append(i)

        async def probe_owner(owner_address: str, idxs: List[int]):
            owner = self.worker_clients.get(owner_address)
            try:
                if len(idxs) == 1:
                    reply = await owner.call(
                        "probe_object", {"object_id": refs[idxs[0]].id}
                    )
                    flags = [reply["ready"]]
                else:
                    reply = await owner.call(
                        "probe_object_batch",
                        {"object_ids": [refs[i].id for i in idxs]},
                    )
                    flags = reply["ready"]
            except Exception:  # noqa: BLE001
                flags = [True] * len(idxs)  # owner gone: surface via get()
            for i, flag in zip(idxs, flags):
                out[i] = flag

        if remote:
            await asyncio.gather(
                *(probe_owner(a, idxs) for a, idxs in remote.items())
            )
        return out

    def wait(self, refs: List[ObjectRef], num_returns=1, timeout=None):
        async def do_wait():
            deadline = None if timeout is None else time.monotonic() + timeout
            ready: List[ObjectRef] = []
            pending = list(refs)
            while len(ready) < num_returns:
                flags = await self._probe_many(pending)
                new_pending = []
                for r, ok in zip(pending, flags):
                    if ok:
                        ready.append(r)
                    else:
                        new_pending.append(r)
                pending = new_pending
                if len(ready) >= num_returns or not pending:
                    break
                if deadline is not None and time.monotonic() >= deadline:
                    break
                await asyncio.sleep(0.01)
            return ready, pending

        return self._run_sync(do_wait())

    # ------------------------------------------------------------ ref count
    def on_ref_created(self, ref: ObjectRef):
        # Called on deserialization in a borrower (via _rehydrate_ref) and on
        # explicit construction by the owner.
        if ref.owner_address == self.address:
            obj = self.owned.get(ref.id)
            if obj is not None and self.loop is not None:
                self._post(lambda oid=ref.id: self._incr_local(oid))
        else:
            if self.loop is not None:
                self._post(lambda r=ref: self._send_incref(r))

    def _incr_local(self, oid: ObjectID):
        obj = self.owned.get(oid)
        if obj is not None:
            obj.local_refs += 1

    def on_ref_relent(self, oid: ObjectID):
        """A borrowed ref was re-serialized (lent onward): mark it so this
        process's eventual decref is grace-delayed.  Thread-safe (called
        from pickling on arbitrary threads); set mutation is atomic."""
        self._relent_refs.add(oid)

    def on_ref_escaped(self, oid: ObjectID):
        """An owned ref was serialized for another process: hold a borrow
        for a grace period so the receiver's incref can't race our free.

        Honest scope (vs the reference's exact borrower registration in
        reply metadata, reference_counter.cc): task ARGS are protected
        exactly by args_holds until the task reply; this grace hold covers
        the remaining escape paths (refs inside return values / stored
        messages), where the receiver deserializes within one RPC hop —
        a receiver stalled longer than borrow_handoff_grace_s after
        physically receiving the bytes can still lose the race."""
        if self._shutdown or self.loop is None or self.loop.is_closed():
            return

        def hold():
            obj = self.owned.get(oid)
            if obj is None:
                return
            obj.borrows += 1

            def release():
                o = self.owned.get(oid)
                if o is not None:
                    o.borrows -= 1
                    self._maybe_free(oid)

            self._delay_refop(release)

        try:
            self._post(hold)
        except RuntimeError:
            pass

    def _delay_refop(self, fn):
        """Run ``fn`` after the borrow-handoff grace period — but flush it
        IMMEDIATELY at shutdown: a borrower exiting cleanly inside the
        grace window must not leak the owner's borrow count forever
        (the grace-delayed decref would simply never fire)."""
        token = object()

        def run():
            self._delayed_refops.pop(token, None)
            fn()

        handle = asyncio.get_running_loop().call_later(
            GlobalConfig.borrow_handoff_grace_s, run
        )
        self._delayed_refops[token] = (handle, fn)

    def _flush_delayed_refops(self):
        ops, self._delayed_refops = self._delayed_refops, {}
        for handle, fn in ops.values():
            handle.cancel()
            try:
                fn()
            except Exception:  # raylint: waive[RTL003] best-effort at teardown
                pass

    def _send_incref(self, ref: ObjectRef):
        client = self.worker_clients.get(ref.owner_address)
        asyncio.get_running_loop().create_task(
            self._oneway(client, "incref", {"object_id": ref.id})
        )

    async def _oneway(self, client, method, payload):
        try:
            await client.notify(method, payload)
        except Exception as e:
            logger.debug("oneway %s notify failed: %s", method, e)

    def on_ref_deleted(self, oid: ObjectID, owner_address: str):
        if self._shutdown or self.loop is None or self.loop.is_closed():
            return
        if owner_address == self.address:
            self._post(lambda o=oid: self._decr_local(o))
        else:
            def send():
                # Last borrowed ref gone: its cached locations are dead
                # weight (and a recycled id must never hit stale entries).
                self._loc_cache.drop(oid)
                # Only refs this borrower actually RE-LENT need the grace
                # delay (the sub-borrower's incref must reach the owner
                # before our decref); plain borrows decref immediately so
                # owner-side lifetime isn't inflated.
                def fire():
                    client = self.worker_clients.get(owner_address)
                    asyncio.get_running_loop().create_task(
                        self._oneway(client, "decref", {"object_id": oid})
                    )

                if oid in self._relent_refs:
                    self._relent_refs.discard(oid)
                    self._delay_refop(fire)
                else:
                    fire()
            try:
                self._post(send)
            except RuntimeError:
                pass

    def _decr_local(self, oid: ObjectID):
        obj = self.owned.get(oid)
        if obj is not None:
            obj.local_refs -= 1
            self._maybe_free(oid)

    def _maybe_free(self, oid: ObjectID):
        obj = self.owned.get(oid)
        if obj is None:
            return
        if obj.local_refs <= 0 and obj.borrows <= 0 and obj.args_holds <= 0:
            if obj.state == PENDING:
                return  # task still running; free after completion
            del self.owned[oid]
            self._lineage_detach(obj)
            self.memory_store.free(oid)
            for agent_addr in obj.locations:
                # The local agent's free MUST ride the same connection as
                # _put_async's pipelined seal notify, or the free can be
                # processed before the seal and the late seal would
                # re-register a deleted arena entry (directory leak).
                if agent_addr == self.agent_address:
                    client = self.agent
                else:
                    client = self.agent_clients.get(agent_addr)
                asyncio.get_running_loop().create_task(
                    self._oneway_call_free(client, oid)
                )

    async def _oneway_call_free(self, client, oid):
        try:
            await client.call("free_objects", {"object_ids": [oid]}, retries=1)
        except Exception as e:
            logger.debug("oneway free_objects failed: %s", e)

    # ------------------------------------------------- streaming (owner side)
    def _new_stream(self, task_id: TaskID, spec: "TaskSpec" = None):
        if spec is not None and (
            spec.actor_id is not None or spec.max_retries <= 0
        ):
            # Actor method items can't be rebuilt by a stateless re-run;
            # non-retriable generators must not re-execute either.
            spec = None
        self._streams[task_id] = {
            "queue": asyncio.Queue(),
            "received": 0,
            "expected": None,  # set by the task reply ("streamed": n)
            "attempt": 0,
            "pending_error": None,  # delivered after in-flight items drain
            "spec": spec,  # lineage for reconstruction of item objects
        }

    def _reset_stream_for_retry(self, task_id: TaskID):
        """A retried streaming task replays from scratch: drop undelivered
        items from the dead attempt and ignore its stragglers.  The queue
        object is drained IN PLACE — a consumer may be blocked awaiting it."""
        state = self._streams.get(task_id)
        if state is not None:
            state["attempt"] += 1
            state["received"] = 0
            state["expected"] = None
            state["pending_error"] = None
            queue = state["queue"]
            while not queue.empty():
                try:
                    queue.get_nowait()
                except asyncio.QueueEmpty:
                    break

    def handle_stream_item(self, payload, conn):
        """Oneway push from the executing worker: one yielded item."""
        state = self._streams.get(payload["task_id"])
        if state is None:
            return  # stream finished/cancelled; drop
        if payload.get("attempt", 0) != state["attempt"]:
            return  # straggler from a dead attempt
        oid = ObjectID.for_task_return(payload["task_id"], payload["index"])
        replaying_old = (
            state.get("recovery_replay")
            and payload["index"] < state.get("replay_watermark", 0)
        )
        if replaying_old:
            # Lineage-reconstruction replay of an index the consumer was
            # already handed: repopulate the owned record in place — no new
            # ref, nothing enqueued, ``received`` already counted it.  An
            # index the consumer freed stays freed (the re-sealed shm copy
            # is orphaned and falls to arena LRU eviction).
            obj = self.owned.get(oid)
            if obj is None:
                return
            ret = payload["ret"]
            if ret[0] == "inline":
                obj.inline_payload = _inline_to_bytes(ret[1])
                obj.size = len(obj.inline_payload)
            else:
                obj.locations.add(ret[1])
                obj.size = ret[2]
            obj.state = READY
            obj.error = None
            obj.wake()
            self._maybe_terminate_stream(state)
            return
        obj = self.owned.get(oid)
        if obj is None:
            obj = self._new_owned(oid, lineage=state.get("spec"))
        ret = payload["ret"]
        if ret[0] == "inline":
            obj.inline_payload = _inline_to_bytes(ret[1])
            obj.size = len(obj.inline_payload)
        else:  # ("shm", agent_addr, size)
            obj.locations.add(ret[1])
            obj.size = ret[2]
        obj.state = READY
        obj.error = None
        obj.wake()
        state["received"] += 1
        # EVERY ObjectRef handed to the consumer carries one local ref —
        # a retry replay of an index the consumer still holds must not
        # alias two refs onto a single count (premature free).
        obj.local_refs += 1
        ref = ObjectRef.__new__(ObjectRef)
        ref.id = oid
        ref.owner_address = self.address
        ref._worker = self
        state["queue"].put_nowait(("item", ref))
        self._maybe_terminate_stream(state)

    @staticmethod
    def _maybe_terminate_stream(state: dict):
        if state["expected"] is not None and state["received"] >= state["expected"]:
            err = state.get("pending_error")
            state["queue"].put_nowait(
                ("err", err) if err is not None else ("end", None)
            )

    def _finish_stream(self, task_id: TaskID, streamed: Optional[int] = None,
                       error=None):
        """Terminal signal from the task reply.  Both ends (success AND
        error) wait for all ``streamed`` in-flight items first — the reply
        and the item notifies ride different sockets and may reorder."""
        state = self._streams.get(task_id)
        if state is None:
            return
        if error is not None:
            state["pending_error"] = error
            if streamed is None:
                # No count available (e.g. lease/connection failure):
                # nothing more is coming — fail now.
                state["queue"].put_nowait(("err", error))
                return
        state["expected"] = streamed if streamed is not None else state["received"]
        self._maybe_terminate_stream(state)

    async def _stream_next(self, task_id: TaskID):
        state = self._streams.get(task_id)
        if state is None:
            return ("end", None)
        kind, value = await state["queue"].get()
        if kind != "item":
            self._streams.pop(task_id, None)
        return (kind, value)

    def cancel_stream(self, task_id: TaskID):
        """Abandoned-generator cleanup (called from ObjectRefGenerator)."""
        if self.loop is not None and not self.loop.is_closed():
            self.loop.call_soon_threadsafe(self._streams.pop, task_id, None)

    def handle_incref(self, payload, conn):
        obj = self.owned.get(payload["object_id"])
        if obj is not None:
            obj.borrows += 1

    def handle_decref(self, payload, conn):
        obj = self.owned.get(payload["object_id"])
        if obj is not None:
            obj.borrows -= 1
            self._maybe_free(payload["object_id"])

    # ------------------------------------------------- owner serving objects
    def _serialize_inline_entry(self, value) -> dict:
        # Out-of-band inline reply: header + buffers ride the reply frame
        # as raw segments.  snapshot() detaches buffers aliasing the live
        # (mutable) memory-store value before the frame flushes.
        return {
            "kind": "inline",
            "payload": serialize_payload(
                value, prefer_plain=is_plain_data(value)
            ).snapshot(),
        }

    async def _get_object_entry(self, oid: ObjectID, lost=()) -> dict:
        """One owner-side resolution: the per-object body of both
        ``get_object`` and ``get_object_batch``.  Returns a reply entry —
        kind 'inline' (payload), 'shm' (locations, size) or 'error'
        (payload)."""
        obj = self.owned.get(oid)
        if obj is None:
            if self.memory_store.contains(oid):
                return self._serialize_inline_entry(self.memory_store.peek(oid))
            return {
                "kind": "error",
                "payload": serialize_to_bytes(
                    ObjectLostError(oid.hex(), "not owned by this worker")
                ),
            }
        await obj.event.wait()
        # Borrower-observed loss: prune the dead copies; reconstruct via
        # lineage if no copy remains (the borrower side of
        # object_recovery_manager.h recovery).
        if lost:
            obj.locations -= set(lost)
            if (
                not obj.locations
                and obj.inline_payload is None
                and obj.state == READY
                and not self.memory_store.contains(oid)
            ):
                if obj.lineage is not None:
                    try:
                        await self._reconstruct_object(oid, obj)
                    except Exception:  # raylint: waive[RTL003] surfaced below
                        pass
                else:
                    obj.state = ERROR
                    obj.error = ObjectLostError(
                        oid.hex(), "all copies lost and no lineage"
                    )
        if obj.state == ERROR:
            return {"kind": "error", "payload": serialize_to_bytes(obj.error)}
        if obj.inline_payload is not None:
            # Immutable flat bytes: ship them out of band, zero copies.
            return {"kind": "inline", "payload": oob_bytes(obj.inline_payload)}
        if obj.locations:
            return {"kind": "shm", "locations": sorted(obj.locations), "size": obj.size}
        # Value only in local memory store (e.g. small put): serialize now.
        if self.memory_store.contains(oid):
            return self._serialize_inline_entry(self.memory_store.peek(oid))
        return {
            "kind": "error",
            "payload": serialize_to_bytes(ObjectLostError(oid.hex(), "value missing")),
        }

    def _owner_entry_fast(self, oid: ObjectID):
        """Owner-side resolution of a READY object — pure reads against
        the sharded owner table + memory store, valid on any thread (the
        multi-lane fast path; also the no-task-allocation fast path on the
        primary loop).  Returns a reply entry, or None when the call needs
        the primary loop (event not yet set — the producing task is still
        running, or a reconstruction is in flight).

        Lane threads race primary-loop mutation (location pruning,
        reconstruction resets, frees): every ambiguous read punts to the
        primary instead of guessing.  The reconstruction reset writes
        ``state`` FIRST and swaps ``event`` LAST, so re-reading both after
        building the reply closes the torn-read window — a reset that
        cleared fields mid-read has already flipped ``state`` off READY
        by the time the post-check runs."""
        obj = self.owned.get(oid)
        if obj is None:
            if self.memory_store.contains(oid):
                try:
                    return self._serialize_inline_entry(
                        self.memory_store.peek(oid)
                    )
                except KeyError:  # contains/peek raced a free
                    return None
            return {
                "kind": "error",
                "payload": serialize_to_bytes(
                    ObjectLostError(oid.hex(), "not owned by this worker")
                ),
            }
        ev = obj.event
        state = obj.state
        if not ev.is_set() or state == PENDING:
            return None
        try:
            if state == ERROR:
                err = obj.error
                if err is None:  # reset raced between state/error writes
                    return None
                entry = {"kind": "error", "payload": serialize_to_bytes(err)}
            elif obj.inline_payload is not None:
                entry = {
                    "kind": "inline", "payload": oob_bytes(obj.inline_payload)
                }
            elif obj.locations:
                entry = {
                    "kind": "shm", "locations": sorted(obj.locations),
                    "size": obj.size,
                }
            elif self.memory_store.contains(oid):
                entry = self._serialize_inline_entry(self.memory_store.peek(oid))
            else:
                entry = {
                    "kind": "error",
                    "payload": serialize_to_bytes(
                        ObjectLostError(oid.hex(), "value missing")
                    ),
                }
        except (RuntimeError, KeyError):
            # Set/dict mutated mid-iteration or memo freed mid-peek by
            # the primary loop: resolve there instead.
            return None
        if obj.event is not ev or obj.state != state:
            return None  # reconstruction reset raced the reads above
        return entry

    def handle_get_object(self, payload, conn):
        oid = payload["object_id"]
        lost = payload.get("lost_locations") or ()
        if not lost:
            entry = self._owner_entry_fast(oid)
            if entry is not None:
                self._shard_fast_entries += 1  # raylint: waive[RTL007] 2026-08-07 lock-free telemetry; lost increments tolerated (flight-recorder gauge)
                return entry
        self._shard_forwarded_entries += 1  # raylint: waive[RTL007] 2026-08-07 lock-free telemetry; lost increments tolerated (flight-recorder gauge)
        return ForwardToPrimary(lambda: self._get_object_entry(oid, lost))

    def handle_get_object_batch(self, payload, conn):
        """Vectorized borrower resolution: one reply with an entry per
        requested object (mixed inline/shm/error).  READY entries resolve
        on the receiving lane (or inline on the primary) without a task
        allocation; only the unresolved remainder rides to the primary
        loop, where entries resolve concurrently — each may block on its
        still-running producing task without holding up the rest."""
        oids = payload["object_ids"]
        if not oids:
            return {"entries": []}
        lost = payload.get("lost_locations") or {}
        entries: List[Optional[dict]] = [None] * len(oids)
        missing: List[int] = []
        for i, oid in enumerate(oids):
            if lost.get(oid):
                missing.append(i)
                continue
            entry = self._owner_entry_fast(oid)
            if entry is None:
                missing.append(i)
            else:
                entries[i] = entry
        self._shard_fast_entries += len(oids) - len(missing)  # raylint: waive[RTL007] 2026-08-07 lock-free telemetry; lost increments tolerated (flight-recorder gauge)
        if not missing:
            return {"entries": entries}
        self._shard_forwarded_entries += len(missing)  # raylint: waive[RTL007] 2026-08-07 lock-free telemetry; lost increments tolerated (flight-recorder gauge)

        async def resolve_missing():
            resolved = await asyncio.gather(
                *(
                    self._get_object_entry(oids[i], lost.get(oids[i]) or ())
                    for i in missing
                )
            )
            for i, entry in zip(missing, resolved):
                entries[i] = entry
            return {"entries": entries}

        return ForwardToPrimary(resolve_missing)

    def handle_probe_object(self, payload, conn):
        obj = self.owned.get(payload["object_id"])
        if obj is None:
            return {"ready": self.memory_store.contains(payload["object_id"])}
        return {"ready": obj.event.is_set()}

    def handle_probe_object_batch(self, payload, conn):
        """Vectorized readiness probes for ray_tpu.wait over many refs."""
        ready = []
        for oid in payload["object_ids"]:
            obj = self.owned.get(oid)
            ready.append(
                self.memory_store.contains(oid)
                if obj is None
                else obj.event.is_set()
            )
        return {"ready": ready}

    # ------------------------------------------------------------ cluster KV
    # Public façade over the control plane's KV table (the reference's
    # ``ray.experimental.internal_kv`` / GCS InternalKV, gcs_kv_manager.cc).
    def kv_put(self, namespace: str, key: str, value, overwrite: bool = True):
        return self._run_sync(
            self.cp.call(
                "kv_put",
                {"namespace": namespace, "key": key, "value": value,
                 "overwrite": overwrite},
            )
        )

    def kv_get(self, namespace: str, key: str):
        return self._run_sync(
            self.cp.call("kv_get", {"namespace": namespace, "key": key})
        )

    def kv_del(self, namespace: str, key: str) -> bool:
        return self._run_sync(
            self.cp.call("kv_del", {"namespace": namespace, "key": key})
        )

    def kv_keys(self, namespace: str, prefix: str = ""):
        return self._run_sync(
            self.cp.call(
                "kv_keys", {"namespace": namespace, "prefix": prefix}
            )
        )

    def kv_exists(self, namespace: str, key: str) -> bool:
        return self._run_sync(
            self.cp.call("kv_exists", {"namespace": namespace, "key": key})
        )

    # ------------------------------------------------------ task submission
    def _export_function(self, fn_or_cls, prefix="fn") -> str:
        pickled = dumps_function(fn_or_cls)
        key = prefix + ":" + function_key(pickled)
        if key not in self._exported_fns:
            self._run_sync(
                self.cp.call(
                    "kv_put",
                    {
                        "namespace": "functions",
                        "key": key,
                        "value": pickled,
                        "overwrite": False,
                    },
                )
            )
            self._exported_fns.add(key)
        return key

    async def _get_function(self, function_id: str):
        fn = self._fn_cache.get(function_id)
        if fn is None:
            data = await self.cp.call(
                "kv_get", {"namespace": "functions", "key": function_id}
            )
            if data is None:
                raise RuntimeError(f"function {function_id} not found in KV")
            fn = loads_function(data)
            self._fn_cache[function_id] = fn
        return fn

    _PLAIN_LEAF_TYPES = frozenset(
        (int, float, bool, str, bytes, bytearray, type(None))
    )

    def _prepare_args(self, args, kwargs) -> Tuple[bytes, List[ObjectRef]]:
        """Top-level ObjectRefs become resolve-markers (Ray semantics: task
        args are resolved to values; nested refs stay refs).  Returns the
        payload and the list of refs to hold until the task completes."""
        global _EMPTY_ARGS_PAYLOAD
        if not args and not kwargs:
            if _EMPTY_ARGS_PAYLOAD is None:
                _EMPTY_ARGS_PAYLOAD = serialize_to_bytes(([], {}))
            return _EMPTY_ARGS_PAYLOAD, []
        held: List[ObjectRef] = []

        def convert(v):
            if isinstance(v, ObjectRef):
                # scan() below records the hold; convert only rewrites.
                return _RefMarker(v.id, v.owner_address)
            return v

        conv_args = [convert(a) for a in args]
        conv_kwargs = {k: convert(v) for k, v in kwargs.items()}

        # One walk does two jobs: hold refs nested anywhere inside standard
        # containers so the owner keeps them alive while the task is in
        # flight (refs inside arbitrary user objects are still covered by
        # the worker's deserialize-time incref, with a small window — same
        # caveat as the reference's borrower protocol), and classify whether
        # every leaf is a plain-picklable builtin/ndarray so serialization
        # can skip cloudpickle (see serialize(prefer_plain=...)).
        import numpy as _np

        plain = True
        leaf_types = self._PLAIN_LEAF_TYPES

        def scan(v, depth=0):
            nonlocal plain
            t = type(v)
            if t in leaf_types:
                return
            if depth > 10:
                plain = False
                return
            if t is ObjectRef:
                held.append(v)
            elif t in (list, tuple, set, frozenset):
                for x in v:
                    scan(x, depth + 1)
            elif t is dict:
                for kk, x in v.items():
                    # Keys can't be refs (unhashable) but CAN be
                    # __main__-defined objects — they affect plainness.
                    kt = type(kk)
                    if kt not in leaf_types:
                        plain = False
                    scan(x, depth + 1)
            elif t is _np.ndarray:
                if v.dtype.hasobject:
                    plain = False
            else:
                plain = False
                # Subclassed containers/refs still get ref-hold semantics.
                if isinstance(v, ObjectRef):
                    held.append(v)
                elif isinstance(v, (list, tuple, set, frozenset)):
                    for x in v:
                        scan(x, depth + 1)
                elif isinstance(v, dict):
                    for x in v.values():
                        scan(x, depth + 1)

        for v in list(args) + list(kwargs.values()):
            scan(v, 1)
        # Out-of-band payload: the args pickle header and its buffers ride
        # the push frame as raw segments (rpc._encode_frame) instead of
        # being flattened into bytes and re-pickled — two fewer
        # full-payload copies per submission.  snapshot() preserves
        # capture-at-call-time semantics for mutable buffers (numpy args).
        payload = serialize_payload(
            (conv_args, conv_kwargs), prefer_plain=plain
        ).snapshot()
        return payload, held

    def _charge_submission(self, spec: TaskSpec, payload):
        """Charge this submission against the pending-task memory budget.
        Blocks (backpressure) only when called off the protocol loop — the
        loop itself must stay free to drain the completions that release
        charges."""
        n = payload_nbytes(payload) + _SubmitBudget.PER_TASK_OVERHEAD
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        # Only THIS worker's protocol loop is exempt from blocking (it
        # drains the completions that release charges).  A user's own
        # asyncio loop is an ordinary producer thread: its completions
        # arrive via our loop regardless, so blocking it is safe — and
        # exempting it would let an async producer bypass the cap.
        self.submit_budget.charge(n, may_block=running is not self.loop)
        spec._queue_charge = n  # type: ignore[attr-defined]

    def _release_queue_charge(self, spec: TaskSpec):
        # Idempotent: reply and failure paths may both fire for one spec.
        n = getattr(spec, "_queue_charge", 0)
        if n:
            spec._queue_charge = 0  # type: ignore[attr-defined]
            self.submit_budget.release(n)

    def _hold_args(self, held: List[ObjectRef]):
        for r in held:
            if r.owner_address == self.address:
                obj = self.owned.get(r.id)
                if obj is not None:
                    obj.args_holds += 1

    def _release_args(self, spec: TaskSpec):
        # Idempotent: the success path defers release to lineage GC while
        # the failure path releases immediately — both may fire.
        if getattr(spec, "_args_released", False):
            return
        spec._args_released = True  # type: ignore[attr-defined]
        for r in getattr(spec, "_held_refs", ()):  # type: ignore[attr-defined]
            if r.owner_address == self.address:
                obj = self.owned.get(r.id)
                if obj is not None:
                    obj.args_holds -= 1
                    self._maybe_free(r.id)

    # ------------------------------------------------- lineage bookkeeping
    # Lineage pinning (reference: task_manager.h:184 lineage pinning +
    # reference_counter.cc lineage ref counting): while any return object
    # of a task is still owned, the task's arg objects stay held so a
    # reconstruction can re-run it.  When the last return object is freed,
    # the args release — recursively freeing upstream lineage.

    def _lineage_attach(self, obj: "OwnedObject", spec: TaskSpec):
        obj.lineage = spec
        spec._lineage_outstanding = (  # type: ignore[attr-defined]
            getattr(spec, "_lineage_outstanding", 0) + 1
        )

    def _lineage_detach(self, obj: "OwnedObject"):
        spec = obj.lineage
        if spec is None:
            return
        obj.lineage = None
        n = getattr(spec, "_lineage_outstanding", 1) - 1
        spec._lineage_outstanding = n  # type: ignore[attr-defined]
        if n <= 0:
            self._release_args(spec)

    def submit_task(
        self,
        fn,
        args,
        kwargs,
        *,
        name: str = "",
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        strategy=None,
        max_retries: int = 0,
        placement_group_id=None,
        bundle_index: int = -1,
        env_vars: Optional[Dict[str, str]] = None,
        function_id: Optional[str] = None,
        pipeline_depth: int = 0,
    ) -> List[ObjectRef]:
        streaming = num_returns == "streaming"
        function_id = function_id or self._export_function(fn)
        payload, held = self._prepare_args(args, kwargs)
        spec = TaskSpec(
            task_id=new_task_id(),
            job_id=self.job_id,
            function_id=function_id,
            name=name or getattr(fn, "__name__", "task"),
            args_payload=payload,
            num_returns=0 if streaming else num_returns,
            streaming=streaming,
            resources=resources or {"CPU": 1},
            strategy=strategy,
            max_retries=max_retries,
            owner_address=self.address,
            placement_group_id=placement_group_id,
            bundle_index=bundle_index,
            env_vars=env_vars or {},
            trace_ctx=_tracing_context(),
            pipeline_depth=pipeline_depth,
        )
        spec._held_refs = held  # type: ignore[attr-defined]
        self._charge_submission(spec, payload)
        refs = []
        return_ids = spec.return_ids()

        # Return-object records are created HERE, on the calling thread,
        # so an immediate get() on the returned refs finds them and can
        # take the no-loop-roundtrip fast path (_try_get_sync).  Only
        # dict/obj mutations — safe under the GIL; the posted setup below
        # happens-before any reply that could touch them.
        # Reconstruction eligibility matches the reference: only
        # retriable tasks re-execute on object loss (a max_retries=0
        # task may have non-idempotent side effects).
        lineage = spec if spec.max_retries > 0 else None
        for oid in return_ids:
            obj = self._new_owned(oid, lineage=lineage)
            obj.local_refs += 1
            # Cancellation index (ray_tpu.cancel maps a return ref back to
            # its producing task); pruned when the task reply lands.
            self._task_of_return[oid] = spec

        def setup():
            self._hold_args(held)
            self.task_events.record(
                spec.task_id.hex(),
                spec.name,
                "PENDING_SUBMISSION",
                job_id_hex=spec.job_id.hex(),
                resources=spec.resources,
            )
            if streaming:
                self._new_stream(spec.task_id, lineage)
            pool = self.lease_pools.get(spec.scheduling_class)
            if pool is None:
                pool = _LeasePool(self, spec.scheduling_class, spec)
                self.lease_pools[spec.scheduling_class] = pool
            pool.submit(spec)

        self._post(setup)
        if streaming:
            return ObjectRefGenerator(spec.task_id, self)
        for oid in return_ids:
            ref = ObjectRef.__new__(ObjectRef)
            ref.id = oid
            ref.owner_address = self.address
            ref._worker = self
            refs.append(ref)
        return refs

    def _handle_task_reply(self, spec: TaskSpec, reply: dict):
        for oid in spec.return_ids():
            self._task_of_return.pop(oid, None)
        self._release_queue_charge(spec)
        done = self._recovery_waiters.get(spec.task_id)
        if done is not None:
            done.set()
        if (
            not GlobalConfig.lineage_pinning
            or getattr(spec, "_lineage_outstanding", 0) <= 0
        ):
            # No return object pinned this task's lineage (actor tasks,
            # non-retriable tasks, zero-item streams): release args now.
            self._release_args(spec)
        if reply.get("error") is not None:
            exc = deserialize_from_bytes(reply["error"])
            if reply.get("streamed") is not None:
                # Mid-stream failure: deliver the items yielded before the
                # error, THEN the error.
                self._finish_stream(
                    spec.task_id, streamed=reply["streamed"], error=exc
                )
                return
            self._fail_task_returns(spec, exc)
            return
        if reply.get("streamed") is not None:
            self._finish_stream(spec.task_id, streamed=reply["streamed"])
            return
        for oid, ret in zip(spec.return_ids(), reply["returns"]):
            obj = self.owned.get(oid)
            if obj is None:
                obj = self._new_owned(oid)
            if ret[0] == "inline":
                obj.inline_payload = _inline_to_bytes(ret[1])
                obj.size = len(obj.inline_payload)
            else:  # ("shm", agent_addr, size)
                obj.locations.add(ret[1])
                obj.size = ret[2]
            obj.state = READY
            obj.wake()
            self._maybe_free(oid)

    def _fail_task_returns(self, spec: TaskSpec, exc: BaseException):
        for oid in spec.return_ids():
            self._task_of_return.pop(oid, None)
        self._release_queue_charge(spec)
        done = self._recovery_waiters.get(spec.task_id)
        if done is not None:
            done.set()
        if spec.task_id in self._streams:
            self._finish_stream(spec.task_id, error=exc)
        if spec.streaming:
            # Item records reset by a failed reconstruction would otherwise
            # stay PENDING forever and hang their getters.
            for obj in list(self.owned.values()):  # user threads insert (submit paths)
                if obj.lineage is spec and obj.state == PENDING:
                    obj.state = ERROR
                    obj.error = exc
                    obj.wake()
        for oid in spec.return_ids():
            obj = self.owned.get(oid)
            if obj is None:
                obj = self._new_owned(oid)
            self._lineage_detach(obj)  # an errored task is not re-runnable
            obj.state = ERROR
            obj.error = exc
            obj.wake()
        self._release_args(spec)

    # --------------------------------------------------------------- actors
    def create_actor(
        self,
        cls,
        args,
        kwargs,
        *,
        name=None,
        namespace="",
        resources=None,
        max_restarts=0,
        max_task_retries=0,
        max_concurrency=1,
        strategy=None,
        placement_group_id=None,
        bundle_index=-1,
        env_vars=None,
        detached=False,
        get_if_exists=False,
        tensor_transport="",
        priority=None,
    ) -> Tuple[ActorID, ActorSpec]:
        class_id = self._export_function(cls, prefix="cls")
        payload, held = self._prepare_args(args, kwargs)
        actor_id = ActorID.from_random()
        spec = ActorSpec(
            actor_id=actor_id,
            job_id=self.job_id,
            class_id=class_id,
            name=name,
            namespace=namespace,
            ctor_args_payload=payload,
            resources=resources or {"CPU": 1},
            max_restarts=max_restarts,
            max_task_retries=max_task_retries,
            max_concurrency=max_concurrency,
            strategy=strategy,
            placement_group_id=placement_group_id,
            bundle_index=bundle_index,
            env_vars=env_vars or {},
            detached=detached,
            owner_address=self.address,
            tensor_transport=tensor_transport,
            priority=priority,
        )

        async def register():
            state = self._actor_state(actor_id)
            await self._subscribe_actor(state)
            info = await self.cp.call(
                "register_actor", {"spec": spec, "get_if_exists": get_if_exists},
                timeout=GlobalConfig.worker_startup_timeout_s + 30,
            )
            self._apply_actor_info(info)
            return info

        info = self._run_sync(register())
        real_id = info["actor_id"]
        return real_id, spec

    def _actor_state(self, actor_id: ActorID) -> _ActorState:
        st = self.actors.get(actor_id)
        if st is None:
            # setdefault: submit paths now call this from user threads too,
            # so losing an insertion race must return the winner's state.
            st = self.actors.setdefault(actor_id, _ActorState(actor_id))
        return st

    async def _subscribe_actor(self, state: _ActorState):
        if not state.subscribed:
            state.subscribed = True
            await self.cp.call(
                "subscribe", {"channels": ["actor:" + state.actor_id.hex()]}
            )

    def _apply_actor_info(self, info: dict):
        state = self._actor_state(info["actor_id"])
        # seq_mutex: user-thread direct submits snapshot
        # (state, incarnation, next_seq) atomically against this update.
        with state.seq_mutex:
            state.state = info["state"]
            state.address = info["address"]
            if info.get("incarnation", 0) != state.incarnation:
                # New incarnation ⇒ the executor's per-caller sequence
                # restarts.
                state.next_seq = 0
            state.incarnation = info.get("incarnation", 0)
        state.death_cause = info.get("death_cause") or ""
        state.max_task_retries = info.get("max_task_retries", 0)
        state.changed.set()
        state.changed = asyncio.Event()

    def _on_push(self, method: str, payload):
        if method == "pub":
            channel = payload["channel"]
            if channel.startswith("actor:"):
                self._apply_actor_info(payload["message"])

    def get_actor_by_name(self, name: str, namespace: str = ""):
        async def lookup():
            return await self.cp.call(
                "get_named_actor", {"name": name, "namespace": namespace}
            )

        return self._run_sync(lookup())

    def submit_actor_task(
        self,
        actor_id: ActorID,
        method_name: str,
        args,
        kwargs,
        *,
        num_returns: int = 1,
        name: str = "",
    ) -> List[ObjectRef]:
        streaming = num_returns == "streaming"
        payload, held = self._prepare_args(args, kwargs)
        spec = TaskSpec(
            task_id=new_task_id(),
            job_id=self.job_id,
            function_id="",  # actor methods dispatch by name
            name=name or method_name,
            args_payload=payload,
            num_returns=0 if streaming else num_returns,
            streaming=streaming,
            owner_address=self.address,
            actor_id=actor_id,
            trace_ctx=_tracing_context(),
        )
        spec.method_name = method_name  # type: ignore[attr-defined]
        spec._held_refs = held  # type: ignore[attr-defined]
        self._charge_submission(spec, payload)
        return_ids = spec.return_ids()

        # Created on the calling thread so an immediate get() takes the
        # sync fast path (see submit_task).
        for oid in return_ids:
            obj = self._new_owned(oid)
            obj.local_refs += 1

        # Direct submit: the sync fast lane pickles and sends the push on
        # THIS thread (no loop wake, no submission task) when the actor is
        # alive, nothing is queued ahead, and the args pin no refs (the
        # loop-affine _hold_args step must not be skipped otherwise).
        if (
            GlobalConfig.rpc_direct_submit
            and not streaming
            and not held
            and self._direct_submit_actor_task(spec)
        ):
            refs = []
            for oid in return_ids:
                ref = ObjectRef.__new__(ObjectRef)
                ref.id = oid
                ref.owner_address = self.address
                ref._worker = self
                refs.append(ref)
            return refs

        # Loop path: count this submission until its seq is assigned so a
        # later direct submit cannot overtake it (program order).
        state = self._actor_state(actor_id)
        with state.seq_mutex:
            state.loop_submits += 1
        spec._loop_seq_pending = True  # type: ignore[attr-defined]

        def setup():
            self._hold_args(held)
            self.task_events.record(
                spec.task_id.hex(),
                spec.name,
                "PENDING_SUBMISSION",
                job_id_hex=spec.job_id.hex(),
                actor_id_hex=spec.actor_id.hex(),
            )
            if streaming:
                self._new_stream(spec.task_id, spec)
            t = asyncio.get_running_loop().create_task(
                self._submit_actor_task(spec)
            )
            # Tracked so shutdown can cancel in-flight submissions instead
            # of leaving "Task was destroyed but it is pending" noise.
            self._inflight_submits.add(t)
            t.add_done_callback(self._inflight_submits.discard)

        self._post(setup)
        if streaming:
            return ObjectRefGenerator(spec.task_id, self)
        refs = []
        for oid in return_ids:
            ref = ObjectRef.__new__(ObjectRef)
            ref.id = oid
            ref.owner_address = self.address
            ref._worker = self
            refs.append(ref)
        return refs

    def _loop_submit_done(self, state: _ActorState, spec) -> None:
        """A loop-path submission reached seq assignment (or died trying):
        stop blocking the direct fast lane on its account."""
        if getattr(spec, "_loop_seq_pending", False):
            spec._loop_seq_pending = False
            with state.seq_mutex:
                state.loop_submits -= 1

    async def _submit_actor_task(self, spec: TaskSpec, attempt: int = 0):
        state = self._actor_state(spec.actor_id)
        if state.state == "ALIVE" and state.waiters == 0 and state.subscribed:
            # Fast path: actor alive, nothing queued ahead of us — assign
            # the sequence number synchronously (no lock round trip) and
            # push; a burst of pushes coalesces into one multiplexed frame
            # at the transport (call(batch=True)).  Submission tasks start
            # in FIFO order on the loop, so order is preserved.  seq_mutex
            # orders the assignment against user-thread direct submits.
            with state.seq_mutex:
                incarnation = state.incarnation
                seq = state.next_seq
                state.next_seq += 1
                if getattr(spec, "_loop_seq_pending", False):
                    spec._loop_seq_pending = False
                    state.loop_submits -= 1
            await self._push_actor_task(spec, state, incarnation, seq, attempt)
            return
        try:
            ok = await self._submit_actor_task_slow(spec, state)
        except BaseException:
            self._loop_submit_done(state, spec)
            raise
        if ok is None:
            self._loop_submit_done(state, spec)
            return
        incarnation, seq = ok
        await self._push_actor_task(spec, state, incarnation, seq, attempt)

    async def _submit_actor_task_slow(self, spec: TaskSpec, state: _ActorState):
        """Wait-for-ALIVE path: seq assignment under a FIFO lock so two
        concurrent submissions can't swap order via the poll fallback.
        Returns (incarnation, seq) or None if the task was failed."""
        state.waiters += 1
        try:
            if not state.subscribed:
                await self._subscribe_actor(state)
            async with state.submit_lock:
                deadline = (
                    time.monotonic() + GlobalConfig.worker_startup_timeout_s * 2
                )
                while state.state in ("PENDING_CREATION", "RESTARTING"):
                    if time.monotonic() > deadline:
                        self._fail_task_returns(
                            spec,
                            ActorDiedError(
                                spec.actor_id.hex(), "creation timed out"
                            ),
                        )
                        return None
                    changed = state.changed
                    try:
                        await asyncio.wait_for(changed.wait(), timeout=1.0)
                    except asyncio.TimeoutError:
                        # Re-poll the control plane in case we missed a pub.
                        info = await self.cp.call(
                            "get_actor_info", {"actor_id": spec.actor_id}
                        )
                        if info is not None:
                            self._apply_actor_info(info)
                if state.state == "DEAD":
                    self._fail_task_returns(
                        spec, ActorDiedError(spec.actor_id.hex(), state.death_cause)
                    )
                    return None
                with state.seq_mutex:
                    seq = state.next_seq
                    state.next_seq += 1
                    incarnation = state.incarnation
                    if getattr(spec, "_loop_seq_pending", False):
                        spec._loop_seq_pending = False
                        state.loop_submits -= 1
                return incarnation, seq
        finally:
            state.waiters -= 1

    def _direct_submit_actor_task(self, spec: TaskSpec) -> bool:
        """Submit one actor push from the CALLING thread (sync fast lane).

        Eligibility (all checked, the decisive ones under ``seq_mutex``):
        the actor is ALIVE and subscribed, its worker client is already
        connected, no slow-path waiter is parked, and no loop-path
        submission is still awaiting a seq (``loop_submits == 0`` —
        program order), and no earlier direct push is still unanswered
        (``direct_inflight == 0`` — an async burst falls back to the
        batched loop path after its first call instead of degrading into
        one send() syscall per call).  Returns ``False`` → caller takes
        the loop path.
        Once the seq is consumed the push MUST converge on it (the
        executor's ordering gate admits seqs in order), so post-accept
        failures re-push the same seq via _recover_direct_push."""
        state = self.actors.get(spec.actor_id)
        if state is None or state.state != "ALIVE" or not state.subscribed:
            return False
        addr = state.address
        if addr is None:
            return False
        raw = getattr(self.worker_clients.peek(addr), "_client", None)
        if raw is None or not raw.connected:
            return False
        # Burst suppression, connection level: any outstanding reply or
        # buffered frame means loop-path traffic is in flight on this
        # connection — a direct send now would fragment its batch
        # containers for no latency win (nobody is blocked waiting).
        # Racy reads (GIL-atomic) — this only picks the lane, never
        # correctness.
        if raw._pending or raw._wsegs:
            return False
        handler = _DirectPushHandler(self, spec, state)
        with state.seq_mutex:
            if (
                state.state != "ALIVE"
                or not state.subscribed
                or state.address != addr
                or state.waiters != 0
                or state.loop_submits != 0
                or state.direct_inflight != 0
            ):
                return False
            handler.incarnation = state.incarnation
            handler.seq = state.next_seq
            if not raw.submit_direct(
                "actor_push_task",
                {
                    "spec": spec,
                    "caller": self.address,
                    "seq": handler.seq,
                    "incarnation": handler.incarnation,
                    "attempt": 0,
                },
                handler,
                timeout=GlobalConfig.task_push_keepalive_s,
            ):
                return False
            # Accepted: the handler owns completion now; consume the seq.
            state.next_seq += 1
            state.direct_inflight += 1
        # Safe from user threads (flat tuple append under the GIL).
        self.task_events.record(
            spec.task_id.hex(),
            spec.name,
            "PENDING_SUBMISSION",
            job_id_hex=spec.job_id.hex(),
            actor_id_hex=spec.actor_id.hex(),
        )
        return True

    def _recover_direct_push(self, h: _DirectPushHandler, exc: BaseException):
        """Loop-side recovery for a failed direct push (posted by
        _DirectPushHandler.on_error)."""
        if isinstance(exc, RpcRemoteError):
            self._fail_task_returns(h.spec, exc)
            return
        # Timeout or connection loss AFTER the seq was consumed: re-enter
        # the loop path's keepalive machinery with the SAME
        # (incarnation, seq) — resends dedup executor-side by
        # (task_id, attempt), and abandoning the seq would wedge the
        # actor's ordering gate.
        t = asyncio.get_running_loop().create_task(
            self._push_actor_task(h.spec, h.state, h.incarnation, h.seq, 0)
        )
        self._inflight_submits.add(t)
        t.add_done_callback(self._inflight_submits.discard)

    async def _push_actor_task(
        self, spec: TaskSpec, state: _ActorState, incarnation: int, seq: int,
        attempt: int,
    ):
        addr = state.address
        client = self.worker_clients.get(addr) if addr is not None else None
        try:
            if client is None:
                # Death already applied (address cleared) before we got
                # here — a direct push's on_error can arrive after
                # _apply_actor_info ran.  Treat it as the connection loss
                # it is: the branch below re-enters the normal submission
                # pipeline (new incarnation, new seq).
                raise RpcConnectionError(
                    f"actor {spec.actor_id.hex()} connection gone"
                )
            # Keepalive re-push (see _LeasePool._push): bounded waits +
            # dedup-safe resends instead of an unbounded reply wait.
            while True:
                try:
                    reply = await client.call(
                        "actor_push_task",
                        {
                            "spec": spec,
                            "caller": self.address,
                            "seq": seq,
                            "incarnation": incarnation,
                            "attempt": attempt,
                        },
                        timeout=GlobalConfig.task_push_keepalive_s,
                        retries=3,
                        batch=True,
                    )
                    break
                except RpcTimeoutError:
                    continue
            self._handle_task_reply(spec, reply)
        except (RpcConnectionError, RpcRemoteError) as e:
            if isinstance(e, RpcRemoteError):
                self._fail_task_returns(spec, e)
                return
            # Connection died: actor crashed or restarting.
            if addr is not None:
                await self.worker_clients.close(addr)
            if attempt < state.max_task_retries:
                await asyncio.sleep(0.2)
                if spec.streaming:
                    # The restarted actor replays the generator from
                    # scratch; drop the dead attempt's items/stragglers.
                    self._reset_stream_for_retry(spec.task_id)
                await self._submit_actor_task(spec, attempt + 1)
            else:
                self._fail_task_returns(
                    spec,
                    ActorDiedError(
                        spec.actor_id.hex(), f"connection lost during call: {e}"
                    ),
                )

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self._run_sync(
            self.cp.call(
                "kill_actor", {"actor_id": actor_id, "no_restart": no_restart}
            )
        )

    # --------------------------------------------------------- cancellation
    def cancel_tasks(self, refs: List[ObjectRef]) -> None:
        """Best-effort cancel of the normal tasks producing ``refs``.

        A task still queued owner-side is dequeued and its returns fail
        with ``TaskCancelledError`` immediately.  A task already pushed
        gets a one-way cancel notify to its executor, which skips it if it
        has not started (exec-pipeline / lane queue wait) — the executor's
        cancelled reply then fails the returns.  A task that already
        finished (or an actor task / a ref from ``put``) is left alone.
        Fire-and-forget: completion is observed through the refs
        themselves.
        """
        ids = [ref.id for ref in refs]

        def do():
            n_accepted = 0
            by_addr: Dict[str, List[TaskID]] = {}
            for oid in ids:
                spec = self._task_of_return.get(oid)
                if spec is None or getattr(spec, "_cancelled", False):
                    continue  # finished, unknown, or already cancelled
                spec._cancelled = True  # type: ignore[attr-defined]
                n_accepted += 1
                addr = getattr(spec, "_pushed_addr", None)
                if addr is None:
                    # Still queued in a lease pool: fail returns now; the
                    # pool's dequeue skips cancelled specs.
                    self._fail_task_returns(
                        spec, TaskCancelledError(spec.name)
                    )
                else:
                    by_addr.setdefault(addr, []).append(spec.task_id)
            for addr, tids in by_addr.items():
                client = self.worker_clients.get(addr)
                self._spawn_inflight(
                    self._oneway(client, "cancel_task", {"task_ids": tids})
                )
            if n_accepted:
                self._tasks_cancelled += n_accepted
                _fr().counter(
                    _fr().TASKS_CANCELLED_TOTAL, float(n_accepted)
                )

        self._post(do)

    def _spawn_inflight(self, coro):
        """Track a fire-and-forget coroutine so shutdown can cancel it."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            coro.close()
            return
        t = loop.create_task(coro)
        self._inflight_submits.add(t)
        t.add_done_callback(self._inflight_submits.discard)

    def handle_cancel_task(self, payload, conn):
        """Executor side: mark tasks to be skipped if not yet started.

        Only PENDING tasks are recorded: the cancel rides the same ordered
        connection as the push, so an id absent from _pending_exec_tasks
        means the task already replied — recording it anyway would leave a
        stale entry that silently fails a later re-execution of the same
        task id (retry / lineage reconstruction) with TaskCancelledError.
        """
        for tid in payload["task_ids"]:
            if (
                tid in self._pending_exec_tasks
                and tid not in self._cancelled_tasks
            ):
                self._cancelled_tasks.add(tid)
                self._cancelled_order.append(tid)
        # Backstop bound (entries are normally dropped at task reply).
        while len(self._cancelled_order) > 4096:
            self._cancelled_tasks.discard(self._cancelled_order.popleft())
        return {"ok": True}

    # ------------------------------------------------------------ execution
    async def _resolve_args(self, payload):
        global _EMPTY_ARGS_PAYLOAD
        if _EMPTY_ARGS_PAYLOAD is None:
            _EMPTY_ARGS_PAYLOAD = serialize_to_bytes(([], {}))
        if type(payload) in (bytes, memoryview) and payload == _EMPTY_ARGS_PAYLOAD:
            return [], {}
        args, kwargs = deserialize_payload(payload)

        # Resolve all distinct markers CONCURRENTLY, one fetch per unique
        # object.  Sequentially awaiting each arg made a wide-args task
        # (the 10k-arg limit case) pay one full owner round trip per arg
        # — resolution wall time scaled with count x latency instead of
        # count / pipeline depth — and a ref passed N times fetched (and
        # increfed) N times.
        markers: Dict[tuple, _RefMarker] = {}
        for v in list(args) + list(kwargs.values()):
            if isinstance(v, _RefMarker):
                markers.setdefault((v.object_id, v.owner_address), v)
        resolved: Dict[tuple, Any] = {}
        fetch: Dict[tuple, _RefMarker] = {}
        for key, m in markers.items():
            # Memo short-circuit BEFORE creating a worker-bound ref: a
            # repeatedly-passed arg (n:n-with-arg pattern) resolves from
            # the local memo without the per-call incref/decref oneway
            # pair that a live ObjectRef costs (the value needs no borrow
            # — args_holds on the owner cover the in-flight task).
            if self.memory_store.contains(m.object_id):
                resolved[key] = self.memory_store.peek(m.object_id)
            else:
                fetch[key] = m
        if len(fetch) == 1:
            # Hot path (one ref arg): skip the gather machinery.
            ((key, m),) = fetch.items()
            resolved[key] = await self._get_one(
                ObjectRef(m.object_id, m.owner_address, _worker=self)
            )
        elif fetch:
            # Owner-grouped batch resolution: a wide-args task resolves
            # all refs of one owner with a single get_object_batch RPC.
            values = await self._get_many(
                [
                    ObjectRef(m.object_id, m.owner_address, _worker=self)
                    for m in fetch.values()
                ]
            )
            resolved.update(zip(fetch.keys(), values))

        def resolve(v):
            if isinstance(v, _RefMarker):
                return resolved[(v.object_id, v.owner_address)]
            return v

        args = [resolve(a) for a in args]
        kwargs = {k: resolve(v) for k, v in kwargs.items()}
        return args, kwargs

    async def _package_value(self, spec: TaskSpec, value, index: int) -> tuple:
        """Package one return/stream value: inline if small, else sealed
        zero-copy into the shm arena."""
        from .serialization import (
            is_plain_data,
            serialize,
            serialized_nbytes,
            write_serialized,
        )

        header, views = serialize(value, prefer_plain=is_plain_data(value))
        size = serialized_nbytes(header, views)
        if size <= GlobalConfig.max_inline_object_bytes:
            # Out-of-band reply payload: header + buffers ride the reply
            # frame as raw segments (no flat re-encoding, no frame-pickle
            # copy).  snapshot() detaches buffers that alias user-owned
            # values — an actor may mutate a returned array after we
            # queue the reply but before the transport flushes it.
            return ("inline", SerializedPayload(header, views).snapshot())
        oid = ObjectID.for_task_return(spec.task_id, index)
        loop = asyncio.get_running_loop()
        _, tier = await loop.run_in_executor(
            None, self.shm_store.create_serialized, oid, header, views
        )
        # Pipelined oneway (see _put_async): the arena entry is already
        # sealed natively; chunk reads fall back to the arena if the
        # directory seal hasn't landed yet.  An arena-oversized return
        # lands on the disk spill tier (tier == "spill") and is indexed
        # there by the agent; readers fall through shm to the spill file.
        await self.agent.notify(
            "seal_object", {"object_id": oid, "size": size, "tier": tier}
        )
        return ("shm", self.agent_address, size)

    # ------------------------------------------------- streaming generators
    async def _execute_streaming(self, spec: TaskSpec, fn, args, kwargs,
                                 ev_kw) -> dict:
        """Run a (sync or async) generator task, pushing each yielded item
        to the owner as it is produced (reference: streaming-generator
        returns, ray ``task_manager.h`` num_returns="streaming")."""
        caller = self.worker_clients.get(spec.owner_address)
        count = 0
        try:
            if inspect.isasyncgenfunction(fn):
                agen = fn(*args, **kwargs)
                async for item in agen:
                    ret = await self._package_value(spec, item, count)
                    await caller.notify(
                        "stream_item",
                        {"task_id": spec.task_id, "index": count,
                         "ret": ret, "attempt": getattr(spec, "_attempt", 0)},
                    )
                    count += 1
            else:
                gen = fn(*args, **kwargs)
                loop = asyncio.get_running_loop()
                sentinel = object()
                while True:
                    item = await loop.run_in_executor(
                        self._task_executor,
                        lambda: next(gen, sentinel),
                    )
                    if item is sentinel:
                        break
                    ret = await self._package_value(spec, item, count)
                    await caller.notify(
                        "stream_item",
                        {"task_id": spec.task_id, "index": count,
                         "ret": ret, "attempt": getattr(spec, "_attempt", 0)},
                    )
                    count += 1
            self.task_events.record(
                spec.task_id.hex(), spec.name, "FINISHED", **ev_kw
            )
            return {"returns": [], "error": None, "streamed": count}
        except BaseException as e:  # noqa: BLE001
            import traceback as tb

            self.task_events.record(
                spec.task_id.hex(), spec.name, "FAILED", error=repr(e), **ev_kw
            )
            err = TaskError(e, tb.format_exc(), spec.name)
            return {
                "returns": None,
                "error": serialize_to_bytes(err),
                "streamed": count,
            }

    async def _package_returns(self, spec: TaskSpec, result) -> List[tuple]:
        if spec.num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != spec.num_returns:
                raise ValueError(
                    f"task {spec.name} declared {spec.num_returns} returns "
                    f"but produced {len(values)}"
                )
        return [
            await self._package_value(spec, value, i)
            for i, value in enumerate(values)
        ]

    def _device_transport_active(self) -> bool:
        return bool(
            self.actor_spec is not None
            and getattr(self.actor_spec, "tensor_transport", "") == "device"
        )

    async def _device_unwrap(self, value):
        """DeviceRefs anywhere in the arg pytree resolve to their resident
        jax.Arrays (RDT analog).  Runs ON the worker event loop, so remote
        fetches await the RPC directly — blocking here would deadlock the
        loop."""
        import jax

        from ..collective.device_objects import (
            DeviceRef,
            array_from_fetch_reply,
            device_object_store,
        )

        store = device_object_store()
        is_ref = lambda v: isinstance(v, DeviceRef)  # noqa: E731
        leaves, treedef = jax.tree.flatten(value, is_leaf=is_ref)
        out = []
        for v in leaves:
            if not is_ref(v):
                out.append(v)
            elif store.contains(v):
                out.append(store.get_local(v))
            elif v.owner_address:
                client = self.worker_clients.get(v.owner_address)
                reply = await client.call(
                    "device_fetch", {"object_id": v.object_id}
                )
                out.append(array_from_fetch_reply(v, reply))
            else:  # collective-group fallback (owner must serve_fetch)
                out.append(store.fetch(v))
        return jax.tree.unflatten(treedef, out)

    @staticmethod
    def _device_wrap(value):
        """jax.Arrays anywhere in the return pytree stay in HBM; DeviceRefs
        travel instead."""
        import jax

        from ..collective.device_objects import device_object_store

        store = device_object_store()
        return jax.tree.map(
            lambda v: store.put(v) if isinstance(v, jax.Array) else v,
            value,
        )

    async def _execute(self, spec: TaskSpec, fn, ticket=None) -> dict:
        from ray_tpu.util.tracing import task_execution_span

        ev_kw = {
            "job_id_hex": spec.job_id.hex(),
            "actor_id_hex": spec.actor_id.hex() if spec.actor_id else "",
        }
        self.task_events.record(spec.task_id.hex(), spec.name, "RUNNING", **ev_kw)
        try:
            with task_execution_span(spec):
                return await self._execute_inner(spec, fn, ev_kw, ticket)
        finally:
            # A wedged pipeline cursor would stall every later call: any
            # path that didn't consume the ticket (coroutine fn, streaming,
            # early error) must release it.
            if ticket is not None:
                self._exec_pipeline.abandon(ticket)

    async def _execute_inner(self, spec: TaskSpec, fn, ev_kw, ticket=None) -> dict:
        # Flight-recorder phase boundaries (each timestamp closes the
        # previous phase): push arrival -> here = queue wait (function
        # fetch + pipeline sequencing), then arg resolution, execution,
        # return packaging.  Recorded only on success — error paths must
        # stay lean, and a failed task's phases would skew the envelope.
        fr_on = GlobalConfig.enable_flight_recorder
        t_start = time.time()
        if spec.actor_id is None and spec.task_id in self._cancelled_tasks:
            # Owner cancelled while this task sat in the executor queue:
            # skip the run, reply with the cancellation (serialized bare —
            # get() raises TaskCancelledError, not a TaskError wrapper).
            self._cancelled_tasks.discard(spec.task_id)
            self.task_events.record(
                spec.task_id.hex(), spec.name, "FAILED",
                error="cancelled", **ev_kw,
            )
            return {
                "returns": None,
                "error": serialize_to_bytes(TaskCancelledError(spec.name)),
            }
        try:
            args, kwargs = await self._resolve_args(spec.args_payload)
            if self._device_transport_active():
                args = await self._device_unwrap(list(args))
                kwargs = await self._device_unwrap(kwargs)
            t_args = time.time()
            self._current_task_name = spec.name
            if spec.streaming:
                if inspect.isgeneratorfunction(fn) or inspect.isasyncgenfunction(fn):
                    reply = await self._execute_streaming(
                        spec, fn, args, kwargs, ev_kw
                    )
                    if fr_on and reply.get("error") is None:
                        t_end = time.time()
                        _fr().record_task_phases(self, spec, (
                            ("queue_wait",
                             getattr(spec, "_recv_ts", t_start), t_start),
                            ("arg_resolution", t_start, t_args),
                            ("execute", t_args, t_end),
                        ))
                    return reply
                # Loud failure beats a consumer hung on a stream that no
                # code path would ever terminate.
                err = TaskError(
                    TypeError(
                        f"{spec.name!r} requested num_returns='streaming' "
                        f"but is not a generator function"
                    ),
                    "",
                    spec.name,
                )
                self.task_events.record(
                    spec.task_id.hex(), spec.name, "FAILED",
                    error="not a generator", **ev_kw,
                )
                return {
                    "returns": None,
                    "error": serialize_to_bytes(err),
                    "streamed": 0,
                }
            loop = asyncio.get_running_loop()
            if asyncio.iscoroutinefunction(fn):
                result = await fn(*args, **kwargs)
            else:
                # copy_context does double duty: the tracing contextvar
                # (and any other context) follows user code into the
                # executor thread, AND each task runs in its own context so
                # contextvars set by user code die with the task instead of
                # leaking into later tasks on the reused pool thread.
                import contextvars as _cv

                _ctx = _cv.copy_context()

                def _guarded_run(*a, **kw):
                    # Re-checked at actual execution start: a cancel that
                    # landed while this task waited behind others in the
                    # pipeline/lane queue still skips the user function.
                    if (
                        spec.actor_id is None
                        and spec.task_id in self._cancelled_tasks
                    ):
                        self._cancelled_tasks.discard(spec.task_id)
                        raise TaskCancelledError(spec.name)
                    return _ctx.run(fn, *a, **kw)

                if ticket is not None:
                    result = await self._exec_pipeline.run_sync(
                        ticket, _guarded_run, *args, **kwargs
                    )
                elif self._lane_pool is not None:
                    # Concurrency lanes: sticky threads + batched
                    # completion flushes (one loop wakeup per burst, not
                    # per call).
                    result = await self._lane_pool.run(
                        _guarded_run, *args, **kwargs
                    )
                else:
                    result = await loop.run_in_executor(
                        self._task_executor,
                        lambda: _guarded_run(*args, **kwargs),
                    )
            if self._device_transport_active():
                result = self._device_wrap(result)
            t_exec = time.time()
            returns = await self._package_returns(spec, result)
            self.task_events.record(
                spec.task_id.hex(), spec.name, "FINISHED", **ev_kw
            )
            if fr_on:
                _fr().record_task_phases(self, spec, (
                    ("queue_wait",
                     getattr(spec, "_recv_ts", t_start), t_start),
                    ("arg_resolution", t_start, t_args),
                    ("execute", t_args, t_exec),
                    ("return_put", t_exec, time.time()),
                ))
            return {"returns": returns, "error": None}
        except BaseException as e:  # noqa: BLE001
            import traceback as tb

            self.task_events.record(
                spec.task_id.hex(), spec.name, "FAILED", error=repr(e), **ev_kw
            )
            if isinstance(e, TaskCancelledError):
                # Not a user-code failure: ship bare so get() raises
                # TaskCancelledError, not a TaskError wrapper.
                return {"returns": None, "error": serialize_to_bytes(e)}
            err = TaskError(e, tb.format_exc(), spec.name)
            return {"returns": None, "error": serialize_to_bytes(err)}

    async def handle_push_task(self, payload, conn):
        spec: TaskSpec = payload["spec"]
        spec._attempt = payload.get("attempt", 0)  # stream notify tagging
        spec._recv_ts = time.time()  # queue-wait phase start
        # At-least-once delivery, exactly-once execution: a transport
        # retry of the same (task, attempt) awaits the original run.
        key = (spec.task_id, spec._attempt)
        fut, owner = self._inflight_replies.claim(
            key, asyncio.get_running_loop()
        )
        if not owner:
            return await asyncio.shield(fut)
        self._pending_exec_tasks.add(spec.task_id)
        try:
            reply = await self._handle_push_task_once(spec)
        except BaseException as e:  # noqa: BLE001
            if not fut.done():
                fut.set_exception(e)
                fut.exception()  # consumed here; mark retrieved
            raise
        finally:
            # Reply (or failure) ends this execution: clear the pending
            # mark AND any unconsumed cancel mark so a re-push of the same
            # task id starts from a clean slate.
            self._pending_exec_tasks.discard(spec.task_id)
            self._cancelled_tasks.discard(spec.task_id)
        if not fut.done():
            fut.set_result(reply)
        return reply

    async def _handle_push_task_once(self, spec: TaskSpec):
        # The ticket MUST be issued before ANY await: ticket order is the
        # pipeline's execution order, so it has to equal push-arrival
        # order.  Allocating it after the function fetch deadlocked a
        # pipelined pair once the LATER task's function was already cached
        # (cache-hit task got the earlier ticket, then suspended forever
        # in _resolve_args waiting for the cache-miss task's output, which
        # sat behind it in the pipeline).
        ticket = self._exec_pipeline.ticket()
        try:
            fn = await self._get_function(spec.function_id)
            if spec.streaming or asyncio.iscoroutinefunction(fn):
                return await self._exec_pipeline.run_coro(
                    ticket, lambda: self._execute(spec, fn)
                )
            return await self._execute(spec, fn, ticket=ticket)
        finally:
            # Idempotent: covers _get_function failures and every
            # non-consuming path so the cursor can never wedge.
            self._exec_pipeline.abandon(ticket)

    async def handle_actor_init(self, payload, conn):
        spec: ActorSpec = payload["spec"]
        try:
            cls = await self._get_function(spec.class_id)
            args, kwargs = await self._resolve_args(spec.ctor_args_payload)
            loop = asyncio.get_running_loop()
            instance = await loop.run_in_executor(
                self._task_executor, lambda: cls(*args, **kwargs)
            )
            self.actor_instance = instance
            self.actor_spec = spec
            self.actor_incarnation = payload.get("incarnation", 0)
            self._actor_exec_lock = asyncio.Semaphore(max(1, spec.max_concurrency))
            if spec.max_concurrency > 1:
                # Overlapping sync methods run on the lane pool (sticky
                # threads, batched completion flushes); the small default
                # _task_executor stays for ctor/streaming/one-off
                # run_in_executor uses — resizing it to max_concurrency
                # would just park N idle threads next to the N lanes.
                self._lane_pool = LanePool(loop, spec.max_concurrency)
            return {"ok": True}
        except BaseException as e:  # noqa: BLE001
            import traceback as tb

            logger.error("actor init failed: %s\n%s", e, tb.format_exc())
            return {"ok": False, "error": f"{e!r}\n{tb.format_exc()}"}

    async def handle_actor_push_task(self, payload, conn):
        spec: TaskSpec = payload["spec"]
        spec._attempt = payload.get("attempt", 0)  # stream notify tagging
        spec._recv_ts = time.time()  # queue-wait phase start
        # Dedup BEFORE the sequence gate: a duplicate push's seq has
        # already been consumed, so re-entering the gate would hang (or,
        # worse, re-execute); it simply awaits the original run's reply.
        key = (spec.task_id, spec._attempt)
        fut, owner = self._inflight_replies.claim(
            key, asyncio.get_running_loop()
        )
        if not owner:
            return await asyncio.shield(fut)
        try:
            reply = await self._handle_actor_push_once(payload, spec)
        except BaseException as e:  # noqa: BLE001
            if not fut.done():
                fut.set_exception(e)
                fut.exception()  # consumed here; mark retrieved
            raise
        if not fut.done():
            fut.set_result(reply)
        return reply

    async def _handle_actor_push_once(self, payload, spec: TaskSpec):
        caller = payload["caller"]
        seq = payload["seq"]
        key = (caller, payload.get("incarnation", 0))
        st = self._actor_seq_state.setdefault(
            key, {"expected": 0, "waiters": {}}
        )
        # In-order execution per caller: wait for our turn.
        while st["expected"] < seq:
            ev = st["waiters"].setdefault(seq, asyncio.Event())
            await ev.wait()

        def advance():
            # Always advance the sequence, even on lookup errors — a wedged
            # sequence would hang every later call from this caller.
            if st["expected"] <= seq:
                st["expected"] = seq + 1
                ev = st["waiters"].pop(seq + 1, None)
                if ev:
                    ev.set()

        try:
            if self.actor_instance is None:
                raise RuntimeError("actor not initialized")
            method_name = getattr(spec, "method_name", spec.name)
            if method_name == "__rtpu_dag_exec_loop__":
                # Compiled-graph execution loop (ray dag/compiled_dag_node.py
                # analog): a long-lived task that reads/writes shm channels
                # instead of per-call RPC.  Dispatched to the dag module with
                # the actor instance bound.
                import functools

                from ..dag.worker_loop import dag_exec_loop

                method = functools.partial(dag_exec_loop, self.actor_instance)
            elif method_name == "__rtpu_exec__":
                # Generic in-actor execution (ray's ``__ray_call__`` analog):
                # first arg is a pickled callable invoked with the actor
                # instance — how out-of-band protocols (collective group
                # init, device-object hooks) run inside user actors without
                # requiring methods on the user class.
                import functools

                from .serialization import loads_function

                def _exec(fn_payload, *a, **kw):
                    return loads_function(fn_payload)(
                        self.actor_instance, *a, **kw
                    )

                method = _exec
            else:
                method = getattr(self.actor_instance, method_name)
            if self.actor_spec is not None and self.actor_spec.max_concurrency > 1:
                # Overlapping execution: the semaphore bounds concurrency,
                # the thread pool provides the parallel lanes.
                async with self._actor_exec_lock:
                    # Advance as soon as execution begins so overlap is
                    # possible.
                    advance()
                    return await self._execute(spec, method)
            # max_concurrency == 1: the exec pipeline IS the exclusion.
            # Ticket before advance() so the next call (released by
            # advance) cannot overtake this one in execution order.
            ticket = self._exec_pipeline.ticket()
            advance()
            if spec.streaming or asyncio.iscoroutinefunction(method):
                try:
                    return await self._exec_pipeline.run_coro(
                        ticket, lambda: self._execute(spec, method)
                    )
                finally:
                    self._exec_pipeline.abandon(ticket)
            return await self._execute(spec, method, ticket=ticket)
        except BaseException as e:  # noqa: BLE001 - report as task error
            from .serialization import serialize_to_bytes as _ser

            return {"returns": None,
                    "error": _ser(TaskError.from_exception(e, spec.name))}
        finally:
            advance()

    def handle_worker_debug(self, payload, conn):
        """Introspection: exec-pipeline cursor + dedup table state."""
        pipe = self._exec_pipeline
        infl = self._inflight_replies
        return {
            "pipeline_next_ticket": pipe._next_ticket if pipe else None,
            "pipeline_next_exec": pipe._next_exec if pipe else None,
            "pipeline_queued": sorted(pipe._items) if pipe else None,
            "inflight_total": len(infl._futs) if infl else None,
            "inflight_pending": (
                [str(k) for k, f in infl._futs.items() if not f.done()]
                if infl else None
            ),
        }

    def handle_obs_pull(self, payload, conn):
        """Node-agent observability pull (heartbeat cadence): drain this
        worker's task-event/span buffers and snapshot its metrics
        registry.  The agent forwards the merged batches to the control
        plane as ONE ``obs_report`` per beat — so per-worker telemetry
        reaches the cluster store without each worker keeping its own
        fast flush timer against the control plane.

        At-least-once: the reply is STAGED here until the agent acks its
        batch_id on a later pull (it acks only after a successful
        obs_report), so a lost reply or failed report re-delivers
        instead of silently dropping the drained events.  Sustained
        delivery failure degrades into oldest-first shedding with the
        normal drop accounting — loss stays explicit."""
        from ..util import metrics as _metrics

        te = self.task_events
        pending = self._obs_pending
        if pending is not None and payload.get("ack") == pending["batch_id"]:
            pending = self._obs_pending = None
        events, profiles = te.drain() if te is not None else ([], [])
        metrics_payload = _metrics.payload_snapshot(only_dirty=True)
        new_content = bool(events or profiles or metrics_payload is not None)
        if pending is not None:
            events = pending["events"] + events
            profiles = pending["profile_events"] + profiles
            if metrics_payload is None:
                metrics_payload = pending["metrics"]
        if te is not None:
            cap = 2 * GlobalConfig.task_events_max_buffer
            if len(events) > cap:
                shed = len(events) - cap
                del events[:shed]
                te._count_dropped(shed)
            if len(profiles) > cap:
                shed = len(profiles) - cap
                shed_rows = profiles[:shed]
                del profiles[:shed]
                te._count_dropped(shed, spans=te._count_spans(shed_rows))
        span_drops = te.num_span_dropped if te is not None else 0
        if not events and not profiles and metrics_payload is None:
            return {"worker_id": self.worker_id.hex(), "batch_id": None,
                    "span_drops": span_drops}
        if pending is not None and not new_content:
            # Pure re-delivery: keep the id so the control plane can
            # drop the duplicate if the first report DID land.
            batch_id = pending["batch_id"]
        else:
            self._obs_batch_seq += 1
            batch_id = self._obs_batch_seq
        self._obs_pending = {
            "batch_id": batch_id,
            "events": events,
            "profile_events": profiles,
            "metrics": metrics_payload,
        }
        return {
            "worker_id": self.worker_id.hex(),
            "batch_id": batch_id,
            "events": events,
            "profile_events": profiles,
            "span_drops": span_drops,
            "metrics_key": f"worker:{self.worker_id.hex()}",
            "metrics": metrics_payload,
        }

    def handle_remediate(self, payload, conn):
        """Remediation directive fan-in (node-agent broadcast): apply
        each directive against THIS process's local actuators — e.g. a
        ``collective_reprobe`` arms the process-wide tuner so every
        group member re-probes in lockstep (util/remediation.py)."""
        from ..util import remediation

        return {
            "worker_id": self.worker_id.hex(),
            "results": [
                remediation.apply_local_directive(d)
                for d in payload.get("directives", ())
            ],
        }

    async def handle_prepare_evict(self, payload, conn):
        """Checkpoint-then-evict fan-in: the node agent warns this worker
        that its placement-group bundle is about to be reclaimed.  Two
        checkpoint channels, both best-effort: process-local eviction
        hooks (``core.eviction``, for non-actor workloads), and the
        hosted actor's ``prepare_evict()`` method — if it returns bytes
        they are parked in the cluster KV under the actor's id, where the
        next incarnation (or the driver's restart machinery) can pick
        them up.  Failures never block the eviction; the workload then
        falls back to its last driver-side checkpoint."""
        from . import eviction

        cause = payload.get("cause", "")
        hooks = eviction.run_eviction_hooks(cause)
        checkpointed = hooks > 0
        inst = getattr(self, "actor_instance", None)
        prepare = getattr(inst, "prepare_evict", None) if inst else None
        if callable(prepare):
            try:
                blob = prepare()
                if isinstance(blob, (bytes, bytearray)):
                    await self.cp.call(
                        "kv_put",
                        {
                            "namespace": "eviction",
                            "key": self.actor_spec.actor_id.hex(),
                            "value": bytes(blob),
                        },
                    )
                checkpointed = True
            except Exception as e:  # noqa: BLE001 — evict proceeds anyway
                logger.warning("prepare_evict checkpoint failed: %s", e)
        return {"checkpointed": checkpointed, "hooks": hooks}

    def handle_pipeline_push(self, payload, conn):
        """Stage-boundary p2p delivery (train.pipeline activations/grads):
        park the still-serialized payload in the local mailbox for the
        consuming actor thread.  Lane-safe — one dict insert + notify."""
        from ..collective.p2p import deposit_push

        deposit_push(payload["edge"], payload["seq"], payload["data"],
                     payload.get("trace"))
        return True

    def handle_device_fetch(self, payload, conn):
        """Point-to-point DeviceRef resolution (RDT analog): serialize the
        resident array to the requester (one host hop).  The reply rides
        the zero-copy path: the host view of the array goes out as an
        out-of-band frame segment (no ``tobytes()`` flat copy), and the
        requester's ``np.frombuffer`` reads straight from the receive
        buffer — this is the prefill→decode KV-cache handoff, so the two
        copies this saves are per KV block."""
        import numpy as np

        from ..collective.device_objects import device_object_store

        store = device_object_store()
        arr = store._objects.get(payload["object_id"])
        if arr is None:
            return {"found": False}
        host = np.ascontiguousarray(np.asarray(arr))
        # Raw-byte view (uint8) rather than memoryview(host): custom
        # dtypes (ml_dtypes bfloat16) don't export a buffer format.
        raw = memoryview(host.reshape(-1).view(np.uint8))
        return {"found": True, "data": oob_bytes(raw)}

    def handle_device_free(self, payload, conn):
        """Owner-side release of one reference (refcounted residency)."""
        from ..collective.device_objects import device_object_store

        store = device_object_store()
        oid = payload["object_id"]
        with store._lock:
            if oid not in store._objects:
                return False
            store._refcounts[oid] -= 1
            if store._refcounts[oid] <= 0:
                del store._objects[oid]
                del store._refcounts[oid]
                return True
            return False

    def handle_device_retain(self, payload, conn):
        from ..collective.device_objects import device_object_store

        store = device_object_store()
        oid = payload["object_id"]
        with store._lock:
            if oid not in store._objects:
                raise KeyError(f"device object {oid} not resident")
            store._refcounts[oid] += 1
            return store._refcounts[oid]

    def handle_device_refcount(self, payload, conn):
        from ..collective.device_objects import device_object_store

        store = device_object_store()
        with store._lock:
            return store._refcounts.get(payload["object_id"], 0)

    def handle_ping(self, payload, conn):
        return "pong"

    def handle_exit_worker(self, payload, conn):
        logger.info("worker exiting on request")

        async def _graceful_exit():
            # Flush the final task-event/metrics window before dying — a
            # short-lived worker must not take its last counters with it.
            try:
                await asyncio.wait_for(self._flush_observability(), timeout=2)
            except BaseException:  # raylint: waive[RTL003] exit must proceed regardless
                pass
            os._exit(0)

        loop = asyncio.get_running_loop()
        # 50 ms grace so this RPC's reply reaches the wire first; the 3 s
        # backstop timer preserves the old guarantee that exit_worker
        # ALWAYS kills the process — even if the flush task is cancelled
        # or the loop stops mid-flush, a timer callback still fires.
        threading.Timer(3.0, os._exit, args=(0,)).start()
        loop.call_later(0.05, lambda: loop.create_task(_graceful_exit()))
        return True
