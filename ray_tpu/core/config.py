"""Typed, env-var-overridable configuration knobs.

Equivalent of the reference's ``RAY_CONFIG(type, name, default)`` macro table
(Ray ``src/ray/common/ray_config_def.h``, overridden via ``RAY_<name>`` env
vars).  Here each knob is declared once in ``_KNOBS`` and can be overridden by
``RAY_TPU_<name>`` in the environment or programmatically via
``Config.override`` (the analog of the driver-shipped ``_system_config``).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

_ENV_PREFIX = "RAY_TPU_"


def _parse(typ, raw: str):
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    if typ in (dict, list):
        return json.loads(raw)
    return typ(raw)


# name -> (type, default, doc)
_KNOBS: Dict[str, tuple] = {
    # -- RPC layer --
    "rpc_connect_timeout_s": (float, 10.0, "TCP connect timeout"),
    "rpc_call_timeout_s": (float, 60.0, "Default RPC deadline"),
    "rpc_retry_base_delay_s": (float, 0.05, "Exponential backoff base"),
    "rpc_retry_max_delay_s": (float, 2.0, "Backoff cap"),
    "rpc_max_retries": (int, 8, "Retryable RPC attempts"),
    "rpc_retry_jitter": (
        bool, True,
        "Decorrelated-jitter backoff (AWS-style: sleep = uniform(base, "
        "prev*3) capped) instead of the deterministic doubling schedule.  "
        "Deterministic backoff synchronizes every client's reconnect "
        "attempt after a control-plane restart — a thundering herd",
    ),
    "rpc_native_codec": (
        bool, True,
        "Use the C frame codec (librtpu_native.so rtpu_frame_*) for v2 "
        "wire frames when the native library loads; the pure-Python codec "
        "is the always-available, byte-identical fallback",
    ),
    "rpc_direct_submit": (
        bool, True,
        "User-thread direct submit: eligible sync-path actor pushes "
        "serialize and send() on the submitting thread under the "
        "connection's write lock, skipping the call_soon_threadsafe "
        "self-pipe wake and the per-call submission task on the loop",
    ),
    "rpc_timeout_wheel_ms": (
        int, 50,
        "Bucket granularity of the shared RPC timeout wheel (one coarse "
        "timer services every in-flight call deadline on a loop; a "
        "deadline fires at most one bucket late).  0 restores per-call "
        "asyncio.wait_for timers",
    ),
    "rpc_service_lanes": (
        int, 0,
        "Event-loop lanes per RPC service (0 = auto: min(4, cpus) for the "
        "many-client servers — control plane, node agent, driver owner "
        "service — and 1 for worker executors).  Connections pin to a "
        "lane at accept time, preserving per-connection ordering; "
        "handlers outside LANE_SAFE_METHODS forward to the primary loop",
    ),
    "owner_table_shards": (
        int, 16,
        "Shards of the per-worker owned-object table (power of two).  "
        "Lane-side get/probe resolution indexes shards independently so "
        "many borrower connections resolve concurrently",
    ),
    "pg_commit_batch_max": (
        int, 64,
        "Max placement groups per control-plane group-commit sweep: "
        "concurrent create/remove requests arriving while a sweep is in "
        "flight coalesce into the next one (single bundle-reservation "
        "sweep + one prepare/commit RPC pass per node per batch)",
    ),
    "testing_rpc_failure": (str, "", "Chaos spec: 'method:prob_req:prob_resp,…'"),
    "testing_network_delay": (
        str, "",
        "Latency chaos: 'method:prob:delay_ms[:jitter_ms],…' ('*' = all)",
    ),
    # -- control plane --
    "cp_persistence": (int, 1, "Durable sqlite control-plane tables (restart FT)"),
    "cp_ha": (
        int, 0,
        "Control-plane high availability: the head spawns two CP "
        "candidates contending for a leader lease over a shared journal "
        "(core/cp_ha.py); the warm standby takes over within the lease "
        "TTL when the leader dies",
    ),
    "cp_lease_ttl_s": (
        float, 2.0,
        "Leader lease validity window: a standby may take over this long "
        "after the leader's last renewal.  The detect half of the "
        "failover window — keep well above cp_lease_poll_s",
    ),
    "cp_lease_poll_s": (
        float, 0.25,
        "Standby lease-acquisition poll (and journal tail) period",
    ),
    "cp_journal_fsync_interval_s": (
        float, 0.05,
        "Journal fsync batching: appends flush to the OS immediately "
        "(process kill -9 loses nothing) and fsync at most this often "
        "(whole-host crash window, the synchronous=NORMAL trade)",
    ),
    "cp_journal_compact_bytes": (
        int, 8 << 20,
        "Journal bytes past the last snapshot before the leader compacts "
        "into a fresh snapshot",
    ),
    "health_check_period_s": (float, 1.0, "Agent heartbeat period"),
    "health_check_timeout_s": (float, 10.0, "Mark node dead after this long"),
    "resource_sync_period_s": (float, 0.2, "Resource view gossip period"),
    # -- scheduling --
    "scheduler_spread_threshold": (float, 0.5, "Pack until this utilization, then spread"),
    # -- multi-tenant arbitration --
    "sched_default_priority": (
        int, 100,
        "Priority assigned to jobs that register without one (higher = "
        "more important).  Serve deployments and other latency-critical "
        "work should register above it, batch/training below",
    ),
    "sched_preemption_enabled": (
        bool, True,
        "Checkpoint-then-evict preemption: a higher-priority bundle that "
        "cannot place may evict lower-priority placement groups (victims "
        "checkpoint via prepare_evict, are re-queued PENDING, and resume "
        "automatically when capacity frees)",
    ),
    "sched_preemption_burst": (
        int, 3,
        "Token-bucket capacity of each job's preemption budget: at most "
        "this many victim evictions in a burst, refilling one per "
        "sched_preemption_cooldown_s.  Bounds the damage a crash-looping "
        "high-priority job can do",
    ),
    "sched_preemption_cooldown_s": (
        float, 30.0, "Seconds to refill one preemption token"
    ),
    "sched_preemption_quarantine_s": (
        float, 600.0,
        "A job that drains its preemption budget is quarantined from "
        "preempting (not from running) for this long",
    ),
    "sched_evict_checkpoint_timeout_s": (
        float, 10.0,
        "Deadline for a victim's prepare_evict checkpoint fan-out; on "
        "expiry the eviction proceeds anyway (the restart path falls "
        "back to the last driver-side checkpoint)",
    ),
    "drain_timeout_s": (
        float, 60.0,
        "Deadline for a draining node to empty (residents evicted via "
        "prepare_evict, leases finished); on expiry the autoscaler "
        "terminates anyway — the restart machinery recovers whatever "
        "was still resident",
    ),
    "drain_poll_period_s": (
        float, 0.5,
        "How often the autoscaler polls drain_status for nodes it is "
        "retiring",
    ),
    "scheduler_top_k_fraction": (float, 0.2, "Top-k random choice fraction"),
    "lease_idle_timeout_s": (float, 0.3, "Return idle leased worker after"),
    "task_push_keepalive_s": (
        float, 60.0,
        "Re-send a task push if no reply within this window (dedup makes "
        "resends exactly-once; converts silent reply loss into a bounded "
        "delay instead of an infinite wait)",
    ),
    "lease_owner_grace_s": (
        float, 8.0,
        "Reconnect window before a disconnected owner's leases are reaped",
    ),
    "worker_startup_timeout_s": (float, 60.0, "Worker process start deadline"),
    "max_tasks_in_flight_per_worker": (int, 10, "Pipelined pushes per leased worker"),
    # -- object store --
    "max_inline_object_bytes": (int, 100 * 1024, "Inline small objects in RPCs"),
    "lineage_pinning": (int, 1, "Pin task args while returns live (reconstruction)"),
    "borrow_handoff_grace_s": (
        float, 10.0,
        "Keep escaped/borrowed refs alive this long past their last local "
        "ref so in-flight borrower increfs never race a free",
    ),
    "max_object_reconstructions": (int, 3, "Lineage re-execution attempts per get"),
    "object_store_memory_bytes": (int, 2 * 1024**3, "Per-node shm budget"),
    "object_store_prefault": (
        bool, False,
        "Fault in every arena page at creation (plasma preallocate analog): "
        "slower startup + committed tmpfs, full-bandwidth first-touch puts",
    ),
    "object_chunk_bytes": (int, 5 * 1024 * 1024, "Chunk size for node-to-node transfer"),
    "memory_store_fallback_bytes": (int, 512 * 1024 * 1024, "In-process store budget"),
    "object_spill_threshold_bytes": (
        int, 0,
        "Objects larger than this are written straight to the disk spill "
        "tier instead of shm (0 = auto: anything larger than the arena, "
        "object_store_memory_bytes — a put that can never fit shm must "
        "not gamble on tmpfs overcommit, whose failure mode is SIGBUS)",
    ),
    "object_spill_max_bytes": (
        int, 0,
        "Disk spill-tier capacity (0 = unlimited).  A put that would "
        "exceed it raises ObjectStoreFullError instead of filling the "
        "disk — spill exhaustion must be a clear error, never a hang",
    ),
    # -- submission backpressure --
    "task_queue_memory_cap_bytes": (
        int, 256 * 1024 * 1024,
        "Byte budget for pending task submissions (serialized args of "
        "tasks not yet completed).  Submitting threads block when a new "
        "submission would cross it, so a fast producer's queue cannot "
        "grow driver RSS without bound (0 = unlimited)",
    ),
    "task_queue_block_timeout_s": (
        float, 300.0,
        "How long a submission may block on the queue-memory cap before "
        "raising PendingTaskBackpressureTimeout",
    ),
    # -- workers --
    "num_workers_soft_limit": (int, 0, "0 = num_cpus"),
    "worker_niceness": (int, 0, "Nice level for spawned workers"),
    "prestart_workers": (int, 0, "Idle-pool floor per node (0 off, -1 = CPU count)"),
    # -- OOM defense --
    "memory_monitor_period_s": (float, 1.0, "0 disables the memory monitor"),
    "memory_monitor_threshold": (float, 0.95, "Kill workers above this usage"),
    "memory_monitor_fake_usage_file": (
        str, "", "Testing: read usage fraction from this file instead of /proc"
    ),
    # -- fault tolerance --
    "task_max_retries_default": (int, 3, "Default retries for idempotent tasks"),
    "actor_max_restarts_default": (int, 0, "Default actor restarts"),
    # -- isolation --
    "enable_resource_isolation": (
        bool, False,
        "Place workers in a cgroup-v2 subtree with cpu/memory limits "
        "(needs a writable /sys/fs/cgroup; silently disabled otherwise)",
    ),
    "worker_cgroup_memory_limit_bytes": (
        int, 0, "0 = no memory.max on the workers cgroup"
    ),
    # -- TPU --
    "tpu_visible_chips_env": (str, "TPU_VISIBLE_CHIPS", "Env var used for chip isolation"),
    # -- collectives --
    "collective_autotune": (
        bool, True,
        "Online per-bucket collective algorithm selection (flat/ring/"
        "tree/two-level by op, message size, world size, ICI-vs-DCN "
        "topology), fed by the flight recorder's achieved-bandwidth "
        "capture.  Off = the static heuristic table only",
    ),
    "collective_quantized_allreduce": (
        bool, False,
        "Process default for SUM-allreduce block quantization (int8 "
        "blocks + per-block scales, EQuARX-style) on float payloads — "
        "~4x fewer wire bytes on bandwidth-bound gradient exchange with "
        "a bounded per-block error.  OFF by default; per-call "
        "allreduce(..., quantized=True) overrides",
    ),
    "collective_quant_block_size": (
        int, 256, "Elements per quantization block (one fp32 scale each)"
    ),
    # -- data --
    "data_max_tasks_per_op": (int, 8, "Streaming executor in-flight cap per op"),
    "data_memory_budget_per_op_bytes": (
        int, 256 * 1024 * 1024, "Estimated in-flight output bytes cap per op"
    ),
    "data_memory_budget_total_bytes": (
        int, 0, "Pipeline-wide in-flight budget split across ops "
        "(0 = object_store_memory_bytes * data_memory_budget_fraction)"
    ),
    "data_memory_budget_fraction": (
        float, 0.5, "Fraction of the shm budget the data pipeline may hold"
    ),
    "data_output_queue_depth": (
        int, 16, "Completed-but-unconsumed blocks buffered per streaming "
        "op before its launches stall (scheduler output bound)"
    ),
    "data_target_block_size_bytes": (
        int, 0, "Dynamic block shaping target: map outputs above it are "
        "split, undersized runs coalesced before the next exchange "
        "(0 = shaping off; ExecutionOptions can override per-plan)"
    ),
    "data_autoscale_interval_s": (
        float, 0.1, "Min seconds between actor-pool autoscale decisions"
    ),
    "data_autoscale_idle_s": (
        float, 0.5, "Sustained starvation (idle actor, empty input queue) "
        "before an autoscaling pool kills an actor above min_size"
    ),
    "data_straggler_wait_slice_s": (
        float, 5.0, "Per-pass bound on the scheduler's blocking "
        "completion wait (straggler harvest loops, never parks unbounded)"
    ),
    # -- serve --
    "serve_health_check_timeout_s": (
        float, 10.0, "Per-sweep deadline for replica health replies"
    ),
    "serve_health_failure_threshold": (
        int, 3, "Consecutive health timeouts before a replica is replaced "
        "(a first-request jax compile can hold the GIL for tens of seconds)"
    ),
    # -- usage stats --
    "usage_stats_enabled": (bool, True, "Cluster-local usage recording"),
    # -- task events / observability --
    "enable_task_events": (bool, True, "Record task lifecycle events"),
    "enable_flight_recorder": (
        bool, True,
        "Runtime-internal telemetry: per-task phase timings, collective "
        "op/bytes/bandwidth capture, object-store and backpressure "
        "counters (ray_tpu_* metrics + timeline phase rows).  Guarded at "
        "<5% round-trip overhead by `bench.py obs_overhead`",
    ),
    "enable_obs_aggregator": (
        bool, True,
        "Node-agent pull of each local worker's span/task-event/metric "
        "deltas, ridden on the existing heartbeat (one obs_report RPC "
        "per beat; no new periodic loop).  Workers drop their own "
        "task-event flush to a slow backup cadence while pulled",
    ),
    "enable_remediation": (
        bool, False,
        "Auto-attach the SLO remediation controller (util/remediation.py) "
        "when the dashboard starts: findings are mapped to bounded "
        "actuator actions (serve scale-up, pipeline-stage respawn, "
        "tuner re-probe) each aggregation beat.  Off by default — "
        "explicit remediation.start() always works",
    ),
    "remediation_beat_s": (
        float, 0.0,
        "Remediation controller beat period; 0 follows the node-agent "
        "heartbeat (health_check_period_s), the cadence aggregated "
        "telemetry actually arrives on",
    ),
    "task_events_flush_period_s": (float, 0.5, "Worker buffer flush period"),
    "task_events_max_buffer": (int, 10000, "Per-worker unflushed event cap"),
    "task_events_max_stored": (int, 100000, "Control-plane stored task cap"),
    # -- logging --
    "log_level": (str, "INFO", "Python log level for system processes"),
    "session_dir": (str, "", "Session directory (default: /tmp/ray_tpu/session_*)"),
    "event_stats_print_period_s": (float, 0.0, "0 disables periodic handler-latency dumps"),
}


class Config:
    """Process-wide configuration singleton.

    Knob reads are hot-path (RPC timeouts, inline thresholds, event gates
    fire per task), so each knob is resolved once — env var consulted at
    first access, like the reference's process-start env parse — and cached
    in the instance ``__dict__`` where subsequent reads bypass
    ``__getattr__`` entirely.  ``override()`` updates the cache;
    ``reload()`` drops it (tests that mutate the environment)."""

    def __init__(self):
        self._overrides: Dict[str, Any] = {}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            typ, default, _doc = _KNOBS[name]
        except KeyError:
            raise AttributeError(f"unknown config knob {name!r}") from None
        if name in self._overrides:
            value = self._overrides[name]
        else:
            raw = os.environ.get(_ENV_PREFIX + name)
            value = _parse(typ, raw) if raw is not None else default
        self.__dict__[name] = value
        return value

    def override(self, **kwargs):
        for k, v in kwargs.items():
            if k not in _KNOBS:
                raise ValueError(f"unknown config knob {k!r}")
            self._overrides[k] = v
            self.__dict__[k] = v

    def reload(self):
        """Drop cached knob values so the next access re-reads the env."""
        for k in _KNOBS:
            self.__dict__.pop(k, None)

    def overrides_as_env(self) -> Dict[str, str]:
        """Serialize programmatic overrides as env vars to ship to child
        processes (the analog of passing _system_config through argv)."""
        env = {}
        for k, v in self._overrides.items():
            typ = _KNOBS[k][0]
            env[_ENV_PREFIX + k] = json.dumps(v) if typ in (dict, list) else str(v)
        return env

    def snapshot(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in _KNOBS}


GlobalConfig = Config()
