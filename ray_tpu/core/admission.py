"""Multi-tenant job arbitration: priority, quota admission, preemption budget.

The control plane owns one :class:`JobArbiter`.  It tracks, per job:

  - **priority** (int, higher = more important; default
    ``sched_default_priority``) — carried on job registration, resolved
    per actor/placement-group request (a request-level ``priority``
    overrides the job's), and consulted by the preemption path: a bundle
    may only evict strictly-lower-priority victims.
  - **quota** (resource → quantity; empty = unlimited) — enforced at
    admission time against the job's *durable* reservations (live actors
    and CREATED placement-group bundles).  Over-quota requests queue
    (stay PENDING) instead of failing, and are retried by the regular
    scheduling sweeps as usage drains.
  - **preemption budget** — a token bucket (capacity
    ``sched_preemption_burst``, one refill per
    ``sched_preemption_cooldown_s``) spent one token per evicted victim,
    with a quarantine (``sched_preemption_quarantine_s``) once drained:
    a crash-looping high-priority job can evict at most a burst's worth
    of victims, then loses the *privilege to preempt* (never the right
    to run) until the quarantine lapses.

Charges are **keyed and idempotent** (``("actor", id)`` / ``("pg", id)``)
so control-plane recovery can blindly re-charge everything it recovers
from sqlite — a charge replayed for a key already held is a no-op, which
is what makes quota accounting immune to double-counting across restart.

Pure bookkeeping, no IO — the control plane calls in under its own loop.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Set, Tuple

from .config import GlobalConfig
from .resources import ResourceSet
from ..util.remediation import _TokenBucket


class JobArbiter:
    def __init__(self):
        # job_id hex -> {"priority": int, "quota": {resource: float}}
        self._jobs: Dict[str, Dict[str, Any]] = {}
        # charge key -> (job hex, ResourceSet)
        self._charges: Dict[Tuple[str, str], Tuple[str, ResourceSet]] = {}
        # job hex -> aggregate charged usage
        self._usage: Dict[str, ResourceSet] = {}
        # admission queueing visibility: live set + cumulative counter
        self._queued_keys: Dict[Tuple[str, str], str] = {}
        self._queued_total: Dict[str, int] = {}
        # preemption budget
        self._buckets: Dict[str, _TokenBucket] = {}
        self._quarantined_until: Dict[str, float] = {}
        self.preemptions_total = 0
        self.victims_total = 0
        self.denied_total = 0

    # ------------------------------------------------------------------ jobs
    def register_job(self, job_hex: str, priority: Optional[int] = None,
                     quota: Optional[Dict[str, float]] = None) -> int:
        """Idempotent: re-registration (driver heartbeat re-register, CP
        recovery replay) updates priority/quota in place, keeps charges."""
        entry = self._jobs.setdefault(job_hex, {})
        if priority is not None or "priority" not in entry:
            entry["priority"] = (
                int(priority) if priority is not None
                else GlobalConfig.sched_default_priority
            )
        if quota is not None or "quota" not in entry:
            entry["quota"] = {
                k: float(v) for k, v in (quota or {}).items()
            }
        return entry["priority"]

    def forget_job(self, job_hex: str) -> None:
        self._jobs.pop(job_hex, None)
        self._buckets.pop(job_hex, None)
        self._quarantined_until.pop(job_hex, None)
        self._queued_total.pop(job_hex, None)
        for key in [k for k, j in self._queued_keys.items() if j == job_hex]:
            del self._queued_keys[key]
        for key in [
            k for k, (j, _r) in self._charges.items() if j == job_hex
        ]:
            self.release(key)

    def priority_of(self, job_hex: Optional[str],
                    override: Optional[int] = None) -> int:
        if override is not None:
            return int(override)
        if job_hex and job_hex in self._jobs:
            return self._jobs[job_hex]["priority"]
        return GlobalConfig.sched_default_priority

    def quota_of(self, job_hex: str) -> Dict[str, float]:
        entry = self._jobs.get(job_hex)
        return dict(entry["quota"]) if entry else {}

    # ------------------------------------------------------------- admission
    def admit(self, job_hex: Optional[str], request: ResourceSet) -> bool:
        """True when charging ``request`` would keep the job within quota.
        Only resources *named in the quota* are bounded; everything else
        is unlimited (quota is an allow-list of caps, not a full spec)."""
        if not job_hex:
            return True
        entry = self._jobs.get(job_hex)
        if not entry or not entry["quota"]:
            return True
        usage = self._usage.get(job_hex)
        used = usage.to_dict() if usage else {}
        want = request.to_dict()
        for resource, cap in entry["quota"].items():
            if used.get(resource, 0.0) + want.get(resource, 0.0) > cap + 1e-9:
                return False
        return True

    def charge(self, key: Tuple[str, str], job_hex: Optional[str],
               request: ResourceSet) -> None:
        """Idempotent by key: recovery replay cannot double-count."""
        if not job_hex or key in self._charges:
            return
        self._charges[key] = (job_hex, request)
        held = self._usage.get(job_hex)
        self._usage[job_hex] = request if held is None else held + request
        self.unmark_queued(key)

    def release(self, key: Tuple[str, str]) -> None:
        held = self._charges.pop(key, None)
        if held is None:
            return
        job_hex, request = held
        usage = self._usage.get(job_hex)
        if usage is not None:
            self._usage[job_hex] = usage - request

    def is_charged(self, key: Tuple[str, str]) -> bool:
        return key in self._charges

    def usage_of(self, job_hex: str) -> Dict[str, float]:
        usage = self._usage.get(job_hex)
        return usage.to_dict() if usage else {}

    def mark_queued(self, key: Tuple[str, str], job_hex: str) -> None:
        if key not in self._queued_keys:
            self._queued_keys[key] = job_hex
            self._queued_total[job_hex] = self._queued_total.get(job_hex, 0) + 1

    def unmark_queued(self, key: Tuple[str, str]) -> None:
        self._queued_keys.pop(key, None)

    def note_queued_event(self, job_hex: str) -> None:
        """Count a transient (keyless) admission queueing — task leases
        have no durable identity to mark/unmark."""
        self._queued_total[job_hex] = self._queued_total.get(job_hex, 0) + 1

    # ------------------------------------------------- preemption budget
    def can_preempt(self, job_hex: str, now: float) -> Tuple[bool, str]:
        """Non-spending probe: quarantine check only."""
        until = self._quarantined_until.get(job_hex, 0.0)
        if now < until:
            return False, f"quarantined for {until - now:.1f}s"
        return True, ""

    def spend_preemption(self, job_hex: str, victims: int,
                         now: float) -> Tuple[bool, str]:
        """Spend one token per victim, all-or-nothing.  A denial for an
        empty bucket starts the quarantine — the crash-loop signature is
        exactly 'drained the burst, immediately asking for more'."""
        ok, reason = self.can_preempt(job_hex, now)
        if not ok:
            self.denied_total += 1
            return False, reason
        bucket = self._buckets.get(job_hex)
        if bucket is None:
            cooldown = max(GlobalConfig.sched_preemption_cooldown_s, 1e-3)
            bucket = _TokenBucket(
                GlobalConfig.sched_preemption_burst, 1.0 / cooldown
            )
            self._buckets[job_hex] = bucket
        taken = 0
        for _ in range(max(1, victims)):
            if not bucket.take(now):
                # Refund the partial spend and quarantine.
                bucket.tokens = min(
                    float(bucket.capacity), bucket.tokens + taken
                )
                self._quarantined_until[job_hex] = (
                    now + GlobalConfig.sched_preemption_quarantine_s
                )
                self.denied_total += 1
                return False, "preemption budget exhausted (quarantined)"
            taken += 1
        self.preemptions_total += 1
        self.victims_total += victims
        return True, ""

    # ------------------------------------------------------------- surfacing
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-job arbitration state for cli status / /api/cluster."""
        jobs: Set[str] = set(self._jobs) | set(self._usage)
        jobs |= set(self._queued_keys.values())
        # Jobs known only through their preemption budget (e.g. the
        # remediation pseudo-job) must surface too — a quarantine nobody
        # can see cannot be diagnosed.
        jobs |= set(self._buckets) | set(self._quarantined_until)
        out: Dict[str, Dict[str, Any]] = {}
        for job_hex in sorted(jobs):
            entry = self._jobs.get(job_hex, {})
            bucket = self._buckets.get(job_hex)
            out[job_hex] = {
                "priority": entry.get(
                    "priority", GlobalConfig.sched_default_priority
                ),
                "quota": dict(entry.get("quota", {})),
                "usage": self.usage_of(job_hex),
                "queued_now": sum(
                    1 for j in self._queued_keys.values() if j == job_hex
                ),
                "queued_total": self._queued_total.get(job_hex, 0),
                "preempt_tokens": (
                    bucket.tokens if bucket is not None
                    else float(GlobalConfig.sched_preemption_burst)
                ),
                "quarantined_until": self._quarantined_until.get(job_hex, 0.0),
            }
        return out
