"""Opt-out usage reporting — cluster-local only.

Reference: ray ``python/ray/_private/usage/`` + the dashboard usage-stats
module.  Privacy-first differences: nothing ever leaves the cluster — the
head aggregates an anonymous feature-usage blob in the control-plane KV,
inspectable via ``usage_report()`` and exported nowhere.  Disable entirely
with ``RAY_TPU_usage_stats_enabled=false``.
"""

from __future__ import annotations

import time
from typing import Dict

_KV_NS = "_usage"


def _enabled() -> bool:
    from .config import GlobalConfig

    return GlobalConfig.usage_stats_enabled


def record_library_usage(library: str) -> None:
    """Called by library entry points (train/tune/serve/...); best-effort.
    Each process writes its OWN key so concurrent *processes* never clobber
    each other; ``usage_report`` sums.  Same-process concurrent threads can
    still lose an increment (acceptable for an approximate counter)."""
    if not _enabled():
        return
    try:
        from .core_worker import try_global_worker

        worker = try_global_worker()
        if worker is None:
            return
        key = f"lib:{library}:{worker.worker_id.hex()}"
        current = worker.kv_get(_KV_NS, key) or {"count": 0}
        current["count"] += 1
        current["last_used"] = time.time()
        worker.kv_put(_KV_NS, key, current)
    except Exception:  # raylint: waive[RTL003] usage stats must never break apps
        pass


def usage_report() -> Dict[str, dict]:
    """The head-local usage blob, summed per library (never exported
    off-cluster)."""
    from .core_worker import global_worker

    worker = global_worker()
    out: Dict[str, dict] = {}
    for key in worker.kv_keys(_KV_NS):
        entry = worker.kv_get(_KV_NS, key)
        if entry is None:
            continue
        lib = key.rsplit(":", 1)[0]  # "lib:train:<worker>" -> "lib:train"
        agg = out.setdefault(lib, {"count": 0, "last_used": 0.0})
        agg["count"] += entry.get("count", 0)
        agg["last_used"] = max(agg["last_used"], entry.get("last_used", 0.0))
    return out
