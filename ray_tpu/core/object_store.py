"""Per-process and per-node object stores.

Two tiers, mirroring the reference:
  - ``MemoryStore``: in-process store for small/inlined objects and futures;
    ``get`` blocks on async fill (Ray
    ``src/ray/core_worker/store_provider/memory_store/memory_store.h``).
  - ``ShmObjectStore``: node-local shared-memory store for large objects,
    zero-copy reads across processes on the same node (plasma analog).

The node agent hosts the authoritative index of sealed shm objects on its
node and serves chunked remote pulls; workers create/read segments directly
through this module (the plasma-client analog).
"""

from __future__ import annotations

import asyncio
import os
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from . import native, shm
from .config import GlobalConfig
from .ids import ObjectID
from .serialization import deserialize_from_bytes, serialize_to_bytes

# Flight-recorder metric names for the object plane (recorded here in
# whichever process hits the event — worker puts, agent evictions — and
# merged cluster-wide through the metrics registry).  Declared once in
# util/metric_registry.py (raylint RTL004).
from ..util.metric_registry import (
    OBJECT_STORE_CAPACITY_BYTES as _M_CAPACITY_BYTES,
    OBJECT_STORE_FULL_ERRORS_TOTAL as _M_FULL_ERRORS,
    OBJECT_STORE_LRU_EVICTIONS_TOTAL as _M_LRU_EVICTIONS,
    OBJECT_STORE_NUM_OBJECTS as _M_NUM_OBJECTS,
    OBJECT_STORE_SPILL_BYTES_TOTAL as _M_SPILL_WRITTEN,
    OBJECT_STORE_SPILL_RECLAIMED_TOTAL as _M_SPILL_RECLAIMED,
    OBJECT_STORE_SPILL_TIER_BYTES as _M_SPILL_TIER_BYTES,
    OBJECT_STORE_SPILL_TIER_OBJECTS as _M_SPILL_TIER_OBJECTS,
    OBJECT_STORE_USED_BYTES as _M_USED_BYTES,
)


def _fr():
    from ..util import flight_recorder

    return flight_recorder

# --------------------------------------------------------------------------
# Native arena tier.  When the C++ library is available every process on the
# node maps one shared arena (object table + allocator in shm) — the plasma
# analog, minus the store-server round trip.  Per-object tmpfs files remain
# the fallback tier (toolchain-less hosts, or arena-full overflow).
# --------------------------------------------------------------------------

_arena_cache: Dict[str, Optional["native.NativeArena"]] = {}


def arena_path(session_id: str) -> str:
    return os.path.join(shm.SHM_DIR, f"{shm._PREFIX}_{session_id}_arena")


def get_arena(
    session_id: str, create: bool = False
) -> Optional["native.NativeArena"]:
    """Per-process handle to the session's shared arena (None if the native
    library is unavailable).

    Only node agents pass ``create=True`` — they are the arena's sole
    creators (and the head agent its sole unlinker).  Everyone else
    attaches: a missing arena means the session is tearing down, and a
    late-booting worker that re-created it would leave an ownerless 2 GiB
    file in /dev/shm past session cleanup (no owner stamp, so the next
    session's orphan sweep must leave it forever)."""
    if session_id in _arena_cache:
        return _arena_cache[session_id]
    if not native.available():
        _arena_cache[session_id] = None
        return None
    try:
        if create:
            a = native.NativeArena.open_shared(
                arena_path(session_id),
                GlobalConfig.object_store_memory_bytes,
            )
        else:
            a = native.NativeArena.attach(arena_path(session_id))
    except OSError:
        a = None
    _arena_cache[session_id] = a
    return a


def drop_arena(session_id: str):
    a = _arena_cache.pop(session_id, None)
    if a is not None:
        a.close()


def delete_from_tiers(session_id: str, object_id: ObjectID):
    """Remove an object from whichever tier holds it — shm arena, tmpfs
    segment, or disk spill file (arena delete is deferred past live reader
    pins by the native layer)."""
    arena = get_arena(session_id)
    if arena is not None:
        arena.delete(object_id.binary())
    shm.unlink_by_name(shm.segment_name(session_id, object_id.hex()))
    try:
        os.unlink(spill_path(session_id, object_id))
    except OSError:
        pass


# --------------------------------------------------------------------------
# Disk spill tier (reference: plasma spilling to external storage;
# ``object_spilling_config`` in the reference).  Objects evicted from shm
# under memory pressure land here and remain directly readable — no lineage
# re-execution needed for spilled-but-wanted objects.
# --------------------------------------------------------------------------

def spill_dir(session_id: str, create: bool = False) -> str:
    d = os.path.join(
        tempfile.gettempdir(), "ray_tpu", f"session_{session_id}", "spill"
    )
    if create:
        os.makedirs(d, exist_ok=True)
    return d


def spill_path(session_id: str, object_id: ObjectID) -> str:
    return os.path.join(spill_dir(session_id), object_id.hex() + ".bin")


def spill_object(session_id: str, object_id: ObjectID, payload) -> int:
    spill_dir(session_id, create=True)
    path = spill_path(session_id, object_id)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)
    _fr().counter(_M_SPILL_WRITTEN, len(payload))
    return len(payload)


def spill_tier_used_bytes(session_id: str) -> int:
    """Bytes currently occupied by this session's disk spill tier.
    In-flight ``.tmp`` writes are excluded (they are either about to be
    renamed — and were already capacity-checked — or about to be
    unlinked)."""
    try:
        with os.scandir(spill_dir(session_id)) as it:
            return sum(
                e.stat().st_size
                for e in it
                if e.is_file() and not e.name.endswith(".tmp")
            )
    except OSError:
        return 0


def _check_spill_capacity(session_id: str, incoming: int):
    """Enforce ``object_spill_max_bytes`` before a spill write.

    The scan is one directory pass; spill writers live in several
    processes (workers spill their own oversized puts/returns, the agent
    spills evictions), so the filesystem is the one shared source of
    truth — a per-process counter would drift.  The cap is a soft bound
    under concurrency: two writers that check simultaneously can overshoot
    by one object, which is the accepted trade for not serializing every
    spill through the agent."""
    from .config import GlobalConfig
    from .exceptions import ObjectStoreFullError

    cap = GlobalConfig.object_spill_max_bytes
    if not cap:
        return
    used = spill_tier_used_bytes(session_id)
    if used + incoming > cap:
        _fr().counter(_M_FULL_ERRORS)
        raise ObjectStoreFullError(
            f"spill tier exhausted: {incoming} B object would exceed the "
            f"object_spill_max_bytes cap of {cap} B (used {used} B)"
        )


def spill_serialized(session_id: str, object_id: ObjectID, header: bytes,
                     views, total: int) -> int:
    """Write the flat serialized encoding (see serialize_to_bytes) straight
    to a spill file — the oversized-put path.  Streams each out-of-band
    buffer to disk without materializing the full payload in heap, and
    converts disk exhaustion (ENOSPC, or the object_spill_max_bytes cap)
    into a clear ObjectStoreFullError instead of a partial write."""
    from .exceptions import ObjectStoreFullError

    _check_spill_capacity(session_id, total)
    spill_dir(session_id, create=True)
    path = spill_path(session_id, object_id)
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(len(views).to_bytes(4, "little"))
            f.write(len(header).to_bytes(4, "little"))
            f.write(header)
            for v in views:
                b = memoryview(v).cast("B")
                f.write(b.nbytes.to_bytes(8, "little"))
                f.write(b)
        os.replace(tmp, path)
    except OSError as e:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        _fr().counter(_M_FULL_ERRORS)
        raise ObjectStoreFullError(
            f"spill write of {total} B object failed: {e}"
        ) from e
    _fr().counter(_M_SPILL_WRITTEN, total)
    return total


def read_spilled(session_id: str, object_id: ObjectID):
    try:
        with open(spill_path(session_id, object_id), "rb") as f:
            return f.read()
    except OSError:
        return None


def read_from_tiers(session_id: str, object_id: ObjectID):
    """Raw payload bytes from any tier, or None."""
    arena = get_arena(session_id)
    if arena is not None:
        mv = arena.acquire(object_id.binary())
        if mv is not None:
            data = bytes(mv)
            del mv
            return data
    try:
        seg = shm.ShmSegment.attach(
            shm.segment_name(session_id, object_id.hex())
        )
        data = bytes(seg.view())
        seg.close()
        return data
    except FileNotFoundError:
        pass
    return read_spilled(session_id, object_id)


class NeedsSpill(Exception):
    """Internal signal: the write must go to the disk spill tier, and the
    caller asked (``inline_spill_ok=False``) to perform disk IO off its
    current thread.  Never user-visible — callers retry on an executor."""

    def __init__(self, total: int):
        self.total = total
        super().__init__(total)


class _SpilledBlob:
    """In-memory copy of a spilled object, quacking like a ShmSegment so it
    can live in ``ShmObjectStore._attached``."""

    __slots__ = ("_data",)

    def __init__(self, data: bytes):
        self._data = data

    def view(self) -> memoryview:
        return memoryview(self._data)

    def close(self):
        self._data = b""


class _Entry:
    __slots__ = ("value", "event", "exception", "ts")

    def __init__(self):
        self.value = None
        self.exception = None
        self.event = asyncio.Event()
        self.ts = time.monotonic()


class MemoryStore:
    """In-process object store; values indexed by ObjectID.  All methods are
    called from the core-worker event loop."""

    def __init__(self):
        self._entries: Dict[ObjectID, _Entry] = {}

    def put(self, object_id: ObjectID, value: Any):
        entry = self._entries.setdefault(object_id, _Entry())
        entry.value = value
        entry.event.set()

    def put_exception(self, object_id: ObjectID, exc: BaseException):
        entry = self._entries.setdefault(object_id, _Entry())
        entry.exception = exc
        entry.event.set()

    def contains(self, object_id: ObjectID) -> bool:
        e = self._entries.get(object_id)
        return e is not None and e.event.is_set()

    def peek(self, object_id: ObjectID):
        e = self._entries.get(object_id)
        if e is None or not e.event.is_set():
            raise KeyError(object_id)
        if e.exception is not None:
            raise e.exception
        return e.value

    async def get(self, object_id: ObjectID, timeout: Optional[float] = None):
        entry = self._entries.setdefault(object_id, _Entry())
        if not entry.event.is_set():
            await asyncio.wait_for(entry.event.wait(), timeout=timeout)
        if entry.exception is not None:
            raise entry.exception
        return entry.value

    def free(self, object_id: ObjectID):
        self._entries.pop(object_id, None)

    def __len__(self):
        return len(self._entries)


class ShmObjectStore:
    """Client-side access to the node's shared-memory object tier.

    Objects are written by the creating worker directly into /dev/shm and
    *sealed* with the node agent (which indexes + size-accounts them).
    Readers attach by name — zero syscalls through the agent on the node-local
    read path, matching plasma's mmap fast path.
    """

    def __init__(self, session_id: str, create_arena: bool = False):
        self.session_id = session_id
        # Attachments are cached for the life of the process: numpy views
        # returned to user code borrow the mapping.
        self._attached: Dict[ObjectID, shm.ShmSegment] = {}
        self._arena = get_arena(session_id, create=create_arena)
        # Bounded LRU cache for spilled-object reads (see raw_bytes).
        from collections import OrderedDict

        self._spill_cache: "OrderedDict[ObjectID, _SpilledBlob]" = OrderedDict()

    # -- write path ---------------------------------------------------------
    @staticmethod
    def _spill_threshold() -> int:
        """Objects at or above this size skip shm and go straight to the
        disk spill tier.  Auto mode (0) uses the arena capacity: an object
        that can never fit the arena would land on a per-object tmpfs
        segment, where exceeding /dev/shm fails as SIGBUS on first write —
        a hard crash, not an error.  Routing it to disk up front keeps the
        oversized-put path a clear round trip (or a clear
        ObjectStoreFullError when the spill tier is exhausted too)."""
        return (
            GlobalConfig.object_spill_threshold_bytes
            or GlobalConfig.object_store_memory_bytes
        )

    def create(self, object_id: ObjectID, value: Any) -> Tuple[int, str]:
        """Serialize ``value`` into the shm tier.  Returns (size, tier)."""
        from .serialization import serialize

        header, views = serialize(value)
        return self.create_serialized(object_id, header, views)

    def create_serialized(self, object_id: ObjectID, header: bytes,
                          views, inline_spill_ok: bool = True,
                          ) -> Tuple[int, str]:
        """Zero-copy write: pickle-5 out-of-band buffers memcpy directly
        into the arena block (one copy per buffer — the plasma-style fast
        path; ~3x put bandwidth over flatten-then-copy on 64 MiB numpy
        payloads).  Returns (size, tier) where tier is "shm" or "spill" —
        arena-oversized objects route straight to the disk spill tier.

        ``inline_spill_ok=False`` makes a would-be disk write raise
        ``NeedsSpill`` instead: a caller on a latency-critical thread (the
        protocol loop) retries the call on an executor thread, so multi-
        hundred-MB disk IO never runs inline there."""
        from .serialization import serialized_nbytes, write_serialized

        total = serialized_nbytes(header, views)
        if total >= self._spill_threshold():
            if not inline_spill_ok:
                raise NeedsSpill(total)
            spill_serialized(self.session_id, object_id, header, views, total)
            return total, "spill"
        if self._arena is not None:
            buf = self._arena.alloc(object_id.binary(), total)
            if buf is None and self._arena.contains(object_id.binary()):
                self._arena.delete(object_id.binary())
                buf = self._arena.alloc(object_id.binary(), total)
            if buf is not None:
                write_serialized(header, views, buf)
                self._arena.seal(object_id.binary())
                return total, "shm"
        try:
            seg = shm.ShmSegment.create(
                shm.segment_name(self.session_id, object_id.hex()), total
            )
        except OSError:
            # tmpfs overflow tier unavailable (e.g. /dev/shm full):
            # degrade to the disk spill tier rather than failing the put.
            if not inline_spill_ok:
                raise NeedsSpill(total)
            spill_serialized(self.session_id, object_id, header, views, total)
            return total, "spill"
        write_serialized(header, views, seg.view())
        self._attached[object_id] = seg
        return total, "shm"

    def create_from_bytes(self, object_id: ObjectID,
                          payload: bytes) -> Tuple[int, str]:
        if len(payload) >= self._spill_threshold():
            _check_spill_capacity(self.session_id, len(payload))
            spill_object(self.session_id, object_id, payload)
            return len(payload), "spill"
        if self._arena is not None:
            buf = self._arena.alloc(object_id.binary(), len(payload))
            if buf is None and self._arena.contains(object_id.binary()):
                # Deterministic return-object names: a retried task re-creates
                # its return object (reference: plasma create-and-seal replace).
                self._arena.delete(object_id.binary())
                buf = self._arena.alloc(object_id.binary(), len(payload))
            if buf is not None:
                buf[: len(payload)] = payload
                self._arena.seal(object_id.binary())
                return len(payload), "shm"
            # Arena full: overflow to a per-object tmpfs file.
        try:
            seg = shm.ShmSegment.create(
                shm.segment_name(self.session_id, object_id.hex()),
                len(payload),
            )
        except OSError:
            # tmpfs tier unavailable too (e.g. /dev/shm full): degrade to
            # the disk spill tier — an inbound transfer must survive the
            # same exhaustion a local put does.
            _check_spill_capacity(self.session_id, len(payload))
            spill_object(self.session_id, object_id, payload)
            return len(payload), "spill"
        seg.view()[: len(payload)] = payload
        self._attached[object_id] = seg
        return len(payload), "shm"

    # -- read path ----------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        if self._arena is not None and self._arena.contains(object_id.binary()):
            return True
        if object_id in self._attached:
            return True
        try:
            self._attached[object_id] = shm.ShmSegment.attach(
                shm.segment_name(self.session_id, object_id.hex())
            )
            return True
        except FileNotFoundError:
            return os.path.exists(spill_path(self.session_id, object_id))

    def get(self, object_id: ObjectID) -> Any:
        return deserialize_from_bytes(self.raw_bytes(object_id))

    def raw_bytes(self, object_id: ObjectID) -> memoryview:
        if self._arena is not None:
            # Pinned view: eviction/delete of the block is deferred until the
            # returned view (and any numpy array built over it) is collected.
            mv = self._arena.acquire(object_id.binary())
            if mv is not None:
                return mv
        seg = self._attached.get(object_id)
        if seg is None:
            try:
                seg = shm.ShmSegment.attach(
                    shm.segment_name(self.session_id, object_id.hex())
                )
            except FileNotFoundError:
                # Last tier: the object was spilled to disk under pressure.
                # Small bounded LRU (chunked pulls read an object's chunks
                # back-to-back, possibly interleaved across a couple of
                # concurrent pulls): caching every blob in _attached would
                # re-accumulate in heap exactly what spilling evicted.
                blob = self._spill_cache.get(object_id)
                if blob is None:
                    data = read_spilled(self.session_id, object_id)
                    if data is None:
                        raise
                    blob = _SpilledBlob(data)
                    self._spill_cache[object_id] = blob
                    while len(self._spill_cache) > 2:
                        _, old = self._spill_cache.popitem(last=False)
                        old.close()
                else:
                    self._spill_cache.move_to_end(object_id)
                return blob.view()
            self._attached[object_id] = seg
        return seg.view()

    def release(self, object_id: ObjectID):
        seg = self._attached.pop(object_id, None)
        if seg is not None:
            seg.close()

    def delete(self, object_id: ObjectID):
        """Remove the object from whichever shm tier holds it."""
        self.release(object_id)
        delete_from_tiers(self.session_id, object_id)


class NodeObjectDirectory:
    """Node-agent-side index of sealed shm objects (sizes, LRU order) plus
    eviction.  The agent also answers chunked pulls from remote nodes."""

    def __init__(self, session_id: str, capacity_bytes: int):
        self.session_id = session_id
        self.capacity = capacity_bytes
        self.used = 0
        self._objects: Dict[ObjectID, Tuple[int, float]] = {}  # size, seal_ts
        self._pinned: Dict[ObjectID, int] = {}
        self.spilled_bytes = 0
        self.num_spilled = 0
        self._spilled: Dict[ObjectID, int] = {}  # oid -> size (disk tier)
        # Spill file IO runs off the agent's event loop; one worker keeps
        # spills ordered.  _spilling tracks sizes of in-flight victims (the
        # object is still in shm until its spill completes) and _freed
        # records frees that raced an in-flight spill.  _tier_lock guards
        # the spill-tier dicts against event-loop readers racing the spill
        # thread's mutations.
        from ..util.debug_locks import make_lock

        self._spill_pool = None
        self._spilling: Dict[ObjectID, int] = {}
        self._freed_while_spilling: set = set()
        self._tier_lock = make_lock("object_store.tier")

    def seal(self, object_id: ObjectID, size: int):
        if object_id not in self._objects:
            self._objects[object_id] = (size, time.monotonic())
            self.used += size
            if self.used > self.capacity:
                self._evict()

    def register_spilled(self, object_id: ObjectID, size: int):
        """Record an object born directly on the disk spill tier (an
        arena-oversized put) — it never occupied shm, so it must not enter
        the capacity-accounted LRU set (one seal would evict the whole
        arena), only the spilled index."""
        with self._tier_lock:
            if object_id not in self._spilled:
                self._spilled[object_id] = size
                self.spilled_bytes += size
                self.num_spilled += 1

    def contains(self, object_id: ObjectID) -> bool:
        return (
            object_id in self._objects
            or object_id in self._spilled
            or object_id in self._spilling
        )

    def size_of(self, object_id: ObjectID) -> Optional[int]:
        entry = self._objects.get(object_id)
        if entry is not None:
            return entry[0]
        return self._spilled.get(object_id) or self._spilling.get(object_id)

    def pin(self, object_id: ObjectID):
        self._pinned[object_id] = self._pinned.get(object_id, 0) + 1

    def unpin(self, object_id: ObjectID):
        n = self._pinned.get(object_id, 0) - 1
        if n <= 0:
            self._pinned.pop(object_id, None)
        else:
            self._pinned[object_id] = n

    def free(self, object_id: ObjectID):
        entry = self._objects.pop(object_id, None)
        if entry is not None:
            self.used -= entry[0]
        with self._tier_lock:
            spilled = self._spilled.pop(object_id, None)
            if object_id in self._spilling:
                self._freed_while_spilling.add(object_id)
        if spilled:
            # spilled_bytes stays CUMULATIVE (written-ever; the limits
            # suite reads it) — reclamation is its own counter.
            _fr().counter(_M_SPILL_RECLAIMED, spilled)
        # Delete from the storage tiers even when the directory has no
        # record: a seal whose oneway frame was lost (or is still in
        # flight on another connection — task-return seals ride the
        # executing worker's conn, frees the owner's) must not strand the
        # arena entry.  delete_from_tiers is idempotent.
        delete_from_tiers(self.session_id, object_id)

    def _evict(self):
        """LRU-evict unpinned sealed objects until under capacity,
        *spilling* each victim to the disk tier first (reference: plasma
        object spilling) so consumers read it back without lineage
        re-execution.  Accounting updates happen here (on the caller's
        loop); the file IO + shm removal run on a spill thread so large
        disk writes never stall the node agent."""
        victims = sorted(
            (oid for oid in self._objects if oid not in self._pinned),
            key=lambda oid: self._objects[oid][1],
        )
        n_evicted = 0
        for oid in victims:
            if self.used <= self.capacity:
                break
            entry = self._objects.pop(oid, None)
            if entry is None:
                continue
            n_evicted += 1
            self.used -= entry[0]
            self._spilling[oid] = entry[0]
            if self._spill_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._spill_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="rtpu-spill"
                )
            self._spill_pool.submit(self._spill_one, oid)
        _fr().counter(_M_LRU_EVICTIONS, n_evicted)

    def _spill_one(self, oid: ObjectID):
        """Runs on the spill thread.  Order matters: write the spill file
        BEFORE removing the shm copy so readers always find the object in
        at least one tier.  A failed spill (e.g. disk full) restores the
        object to the tracked set — losing track of a live shm copy would
        corrupt capacity accounting."""
        import logging

        try:
            try:
                payload = read_from_tiers(self.session_id, oid)
                if payload is not None:
                    # The spill-tier cap binds evictions too: a capped
                    # tier must not silently fill with LRU victims.  The
                    # raise lands in the except below — the object stays
                    # tracked in shm (accounting restored) and the miss is
                    # logged, exactly like a failed (ENOSPC) spill write.
                    _check_spill_capacity(self.session_id, len(payload))
                    spill_object(self.session_id, oid, payload)
                    self.spilled_bytes += len(payload)
                    self.num_spilled += 1
                    with self._tier_lock:
                        self._spilled[oid] = len(payload)
            except Exception as e:  # noqa: BLE001 — e.g. ENOSPC
                with self._tier_lock:
                    if oid in self._freed_while_spilling:
                        # Freed during the spill: nothing to restore — the
                        # finally block deletes whatever remains.
                        return
                    size = self._spilling.get(oid, 0)
                    self._objects[oid] = (size, time.monotonic())
                    self.used += size
                logging.getLogger(__name__).warning(
                    "spill of %s failed (%s); keeping shm copy", oid.hex(), e
                )
                return
            arena = get_arena(self.session_id)
            if arena is not None:
                arena.delete(oid.binary())
            shm.unlink_by_name(shm.segment_name(self.session_id, oid.hex()))
        finally:
            with self._tier_lock:
                self._spilling.pop(oid, None)
                freed = oid in self._freed_while_spilling
                if freed:
                    self._freed_while_spilling.discard(oid)
                    self._spilled.pop(oid, None)
            if freed:
                delete_from_tiers(self.session_id, oid)

    def record_telemetry(self):
        """Set the object-plane gauges from current directory state (called
        from the node agent's heartbeat — gauges off the seal/free hot
        path; counters are incremented at the event sites)."""
        fr = _fr()
        if not fr.enabled():
            return
        with self._tier_lock:
            disk_now = sum(self._spilled.values())
            n_disk = len(self._spilled)
        fr.gauge(_M_USED_BYTES, self.used)
        fr.gauge(_M_CAPACITY_BYTES, self.capacity)
        fr.gauge(_M_NUM_OBJECTS, len(self._objects))
        fr.gauge(_M_SPILL_TIER_BYTES, disk_now)
        fr.gauge(_M_SPILL_TIER_OBJECTS, n_disk)

    def object_ids(self) -> List[ObjectID]:
        return list(self._objects)

    def inventory(self) -> List[dict]:
        """Snapshot of every tracked object across tiers (state API); the
        lock also covers _objects, which the spill thread's failure path
        mutates."""
        with self._tier_lock:
            objects = list(self._objects.items())
            spilled = list(self._spilled.items())
            spilling = list(self._spilling.items())
        out = [
            {"object_id": oid.hex(), "size": entry[0], "tier": "shm"}
            for oid, entry in objects
        ]
        out.extend(
            {"object_id": oid.hex(), "size": size, "tier": "spilled"}
            for oid, size in spilled
        )
        out.extend(
            {"object_id": oid.hex(), "size": size, "tier": "spilling"}
            for oid, size in spilling
        )
        return out

    def cleanup(self):
        if self._spill_pool is not None:
            self._spill_pool.shutdown(wait=True)
            self._spill_pool = None
        for oid in list(self._objects):
            self.free(oid)
        for oid in list(self._spilled):
            self.free(oid)
