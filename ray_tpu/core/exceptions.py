"""User-visible exception types (reference: ray ``python/ray/exceptions.py``)."""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ``get``.  Carries the remote
    traceback so the user sees where the failure happened."""

    def __init__(self, cause: BaseException, remote_tb: str, task_name: str = ""):
        self.cause = cause
        self.remote_traceback = remote_tb
        self.task_name = task_name
        super().__init__(f"task {task_name!r} failed: {cause!r}\n{remote_tb}")

    @classmethod
    def from_exception(cls, exc: BaseException, task_name: str = "") -> "TaskError":
        return cls(exc, traceback.format_exc(), task_name)

    def __reduce__(self):
        try:
            import pickle

            pickle.dumps(self.cause)
            cause = self.cause
        except Exception:
            cause = RuntimeError(repr(self.cause))
        return (TaskError, (cause, self.remote_traceback, self.task_name))


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died (process exit / node loss)."""


class ActorDiedError(RayTpuError):
    def __init__(self, actor_id_hex: str, cause: str = ""):
        self.actor_id_hex = actor_id_hex
        super().__init__(f"actor {actor_id_hex[:12]} is dead: {cause}")


class ActorUnavailableError(RayTpuError):
    """Actor is restarting; the call may be retried."""


class ObjectLostError(RayTpuError):
    def __init__(self, object_hex: str, cause: str = ""):
        super().__init__(f"object {object_hex[:16]} lost: {cause}")


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectStoreFullError(RayTpuError):
    """Every storage tier (shm arena, tmpfs segments, disk spill) is
    exhausted or capped; the object cannot be stored anywhere.  Raised at
    ``put``/return-packaging time — overload must surface as an error at
    the call site, never as a hang."""


class PendingTaskBackpressureTimeout(RayTpuError, TimeoutError):
    """A submission blocked on the pending-task memory cap
    (``task_queue_memory_cap_bytes``) for longer than
    ``task_queue_block_timeout_s`` — the cluster is not draining queued
    work fast enough for this producer."""


class TaskCancelledError(RayTpuError):
    """The task producing this object was cancelled via ``ray_tpu.cancel``
    before it ran (owner-side dequeue or executor-side skip).  Raised at
    ``get`` on the cancelled task's return refs.  Cancellation is
    best-effort: a task already executing runs to completion and its
    returns resolve normally."""

    def __init__(self, task_name: str = ""):
        self.task_name = task_name
        super().__init__(f"task {task_name!r} was cancelled before execution")

    def __reduce__(self):
        return (TaskCancelledError, (self.task_name,))


class RuntimeEnvSetupError(RayTpuError):
    pass
