"""Live stack introspection for system processes (`ray-tpu stack`).

Reference: ray's ``ray stack`` (``scripts/scripts.py:2011``) shells out to
py-spy to dump every worker's native stack.  py-spy isn't available here,
and thread stacks miss the interesting state anyway — a wedged asyncio
process is *suspended at an await*, which only the coroutine chain shows.
So every system process (control plane, node agent, worker) installs two
handlers at startup:

* ``SIGABRT`` → ``faulthandler`` thread C-stacks (stdlib).
* ``SIGUSR1`` → this module's dump: every asyncio task's await-chain
  (walking ``cr_await``/``gi_yieldfrom``), plus the exec-pipeline cursor
  state for workers — the exact evidence needed for "it hangs" bugs.

``ray-tpu stack`` signals the session's processes and tails their logs.
"""

from __future__ import annotations

import asyncio
import faulthandler
import logging
import signal


def install_signal_dumpers(loop: asyncio.AbstractEventLoop) -> None:
    """Register SIGUSR1 → async-task dump on ``loop``.  faulthandler is
    enabled as a side effect so SIGABRT gives thread stacks too."""
    faulthandler.enable()
    try:
        loop.add_signal_handler(signal.SIGUSR1, dump_async_tasks)
    except (NotImplementedError, RuntimeError):  # non-main thread / wasi
        pass


def dump_async_tasks() -> None:
    """Log every asyncio task's coroutine await-chain."""
    log = logging.getLogger("stack_dump")
    pipe = _exec_pipeline()
    if pipe is not None:
        # Snapshot under the pipeline's lock — the drainer thread mutates
        # _items concurrently and a mid-resize iteration would kill this
        # handler exactly when it's needed.
        with pipe._cv:
            queued = sorted(pipe._items.keys())
            nt, ne = pipe._next_ticket, pipe._next_exec
        log.warning(
            "exec pipeline: next_ticket=%d next_exec=%d queued=%s",
            nt, ne, queued,
        )
    tasks = asyncio.all_tasks()
    log.warning("=== %d asyncio tasks ===", len(tasks))
    for t in tasks:
        log.warning("task %r:\n%s", t.get_name(), format_await_chain(t))


def format_await_chain(task: "asyncio.Task") -> str:
    """The task's coroutine await-chain, one frame per line.  get_stack()
    only shows the outermost frame; nested awaits need the
    ``cr_await``/``gi_yieldfrom`` walk."""
    lines = []
    obj = task.get_coro()
    for _ in range(24):
        if obj is None:
            break
        frame = getattr(obj, "cr_frame", getattr(obj, "gi_frame", None))
        if frame is not None:
            code = frame.f_code
            lines.append(
                f"  {code.co_filename}:{frame.f_lineno} {code.co_name}"
            )
        nxt = getattr(obj, "cr_await", getattr(obj, "gi_yieldfrom", None))
        if nxt is None and frame is None:
            lines.append(f"  <awaiting {obj!r}>")
            break
        obj = nxt
    return "\n".join(lines) or "  <no frames>"


def _exec_pipeline():
    try:
        from .core_worker import try_global_worker

        w = try_global_worker()
        return getattr(w, "_exec_pipeline", None) if w else None
    except Exception:  # noqa: BLE001 — diagnostics must never raise
        return None
