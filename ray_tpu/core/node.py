"""Node/process supervisor: starts and monitors the per-node system processes.

Equivalent of the reference's Node class + services (ray
``python/ray/_private/node.py``, ``services.py``): the head path spawns the
control plane, every node spawns a node agent; processes log to the session
directory and are killed as a group on shutdown.  Also provides the
in-process multi-node ``Cluster`` test fixture (the reference's key testing
trick, ray ``python/ray/cluster_utils.py:135``): multiple node agents on one
machine, each believing it is a distinct node.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

from . import shm
from .config import GlobalConfig
from .rpc import RpcClient, find_free_port

_HEAD_INFO_FILE = "/tmp/ray_tpu/head_info.json"


def _wait_for_server(address: str, timeout: float = 30.0) -> None:
    """Block until an RpcServer answers ping at address."""

    async def try_ping():
        client = RpcClient(address)
        await client.connect()
        reply = await client.call("ping", timeout=2)
        await client.close()
        return reply == "pong"

    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            if asyncio.run(try_ping()):
                return
        except Exception as e:  # noqa: BLE001
            last = e
        time.sleep(0.05)
    raise TimeoutError(f"server at {address} did not come up: {last}")


class ProcessGroup:
    def __init__(self):
        self.procs: List[subprocess.Popen] = []
        self.die_with_parent = False

    def spawn(self, argv: List[str], log_path: str, env: Optional[dict] = None):
        full_env = dict(os.environ)
        if env:
            full_env.update(env)
        full_env.update(GlobalConfig.overrides_as_env())
        # Log lines and `ray-tpu stack` dumps must reach the file when
        # they happen — block-buffered stdio leaves a killed process's
        # log empty.
        full_env["PYTHONUNBUFFERED"] = "1"
        if self.die_with_parent:
            # System processes watch this pid and self-exit when it dies —
            # a SIGKILLed driver must not leave an orphaned cluster behind
            # (reference precedent: ray's process reaper).
            full_env["RAY_TPU_PARENT_PID"] = str(os.getpid())
        else:
            full_env.pop("RAY_TPU_PARENT_PID", None)
        out = open(log_path, "ab")

        def ignore_usr1():
            # `ray-tpu stack` uses SIGUSR1; ignored dispositions survive
            # exec, so a signal during the child's import phase (before
            # its loop installs the dump handler) is dropped instead of
            # killing the starting process.
            import signal

            signal.signal(signal.SIGUSR1, signal.SIG_IGN)

        proc = subprocess.Popen(
            argv, stdout=out, stderr=subprocess.STDOUT, env=full_env,
            start_new_session=True, preexec_fn=ignore_usr1,
        )
        self.procs.append(proc)
        return proc

    def kill_all(self):
        for proc in self.procs:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        deadline = time.monotonic() + 3
        for proc in self.procs:
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        self.procs.clear()


class Node:
    """Manages the system processes for one logical node (and, on the head,
    the control plane)."""

    def __init__(
        self,
        head: bool,
        cp_address: Optional[str] = None,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        session_id: Optional[str] = None,
        num_cpus: Optional[float] = None,
        port: Optional[int] = None,
        die_with_parent: bool = False,
        ha_dir: Optional[str] = None,
    ):
        self.head = head
        self.port = port
        self.session_id = session_id or shm.new_session_id()
        self.log_dir = os.path.join(
            tempfile.gettempdir(), "ray_tpu", f"session_{self.session_id}"
        )
        os.makedirs(self.log_dir, exist_ok=True)
        self.pg = ProcessGroup()
        self.pg.die_with_parent = die_with_parent
        self.cp_address = cp_address
        self.agent_address: Optional[str] = None
        self._cp_argv: Optional[List[str]] = None
        self._cp_log: Optional[str] = None
        self._cp_env: Optional[dict] = None
        # HA (GlobalConfig.cp_ha): the shared lease/journal directory and
        # the CP candidate processes contending over it (head only; a
        # joining node receives ha_dir so its agent can follow failovers).
        self.ha_dir = ha_dir
        self._cp_candidates: List[dict] = []

        # Detection runs through the accelerator plugin registry (TPU is
        # built in; other vendors contribute by registering a manager).
        from .accelerators import all_accelerator_managers

        detected_res: Dict[str, float] = {}
        detected_labels: Dict[str, str] = {}
        for mgr in all_accelerator_managers():
            if mgr.resource_name == "CPU":
                continue  # CPU count is handled below (num_cpus override)
            n = mgr.get_current_node_num_accelerators()
            if n > 0:
                detected_res[mgr.resource_name] = float(n)
            detected_res.update(mgr.get_current_node_additional_resources())
            detected_labels.update(mgr.get_current_node_labels())
        res: Dict[str, float] = {
            "CPU": float(num_cpus if num_cpus is not None else (os.cpu_count() or 1)),
        }
        res.update(detected_res)
        if resources:
            res.update(resources)
        self.resources = res
        lbls = dict(detected_labels)
        if labels:
            lbls.update(labels)
        self.labels = lbls

    def start(self):
        env = {"RAY_TPU_LOG_DIR": self.log_dir}
        if self.head:
            if GlobalConfig.cp_ha:
                self._start_cp_candidates(env)
            else:
                cp_port = self.port or find_free_port()
                self.cp_address = f"127.0.0.1:{cp_port}"
                self._cp_argv = [
                    sys.executable, "-m", "ray_tpu.core.control_plane",
                    "--port", str(cp_port),
                    "--session-id", self.session_id,
                ]
                if GlobalConfig.cp_persistence:
                    self._cp_argv += [
                        "--store-path",
                        os.path.join(self.log_dir, "control_plane.sqlite"),
                    ]
                self._cp_log = os.path.join(self.log_dir, "control_plane.log")
                self._cp_env = dict(env)
                self.pg.spawn(self._cp_argv, self._cp_log, env)
                _wait_for_server(self.cp_address)
        assert self.cp_address
        if self.ha_dir:
            # Inherited by every child this node spawns (ProcessGroup
            # copies os.environ), so workers and the driver build their
            # CP clients with the leader-endpoint resolver.
            os.environ["RAY_TPU_CP_HA_DIR"] = self.ha_dir
        agent_port = find_free_port()
        self.agent_address = f"127.0.0.1:{agent_port}"
        agent_argv = [
            sys.executable, "-m", "ray_tpu.core.node_agent",
            "--port", str(agent_port),
            "--cp-address", self.cp_address,
            "--session-id", self.session_id,
            # The head's agent owns session-wide shm cleanup on
            # parent-death; worker/client agents must never delete the
            # shared arena (same ownership rule as Node.stop()).
            "--owns-session-shm", "1" if self.head else "0",
            "--resources", json.dumps(self.resources),
            "--labels", json.dumps(self.labels),
        ]
        if self.ha_dir:
            agent_argv += ["--cp-ha-dir", self.ha_dir]
        self.pg.spawn(
            agent_argv,
            os.path.join(self.log_dir, "node_agent.log"),
            env,
        )
        _wait_for_server(self.agent_address)
        if self.head:
            os.makedirs(os.path.dirname(_HEAD_INFO_FILE), exist_ok=True)
            with open(_HEAD_INFO_FILE, "w") as f:
                json.dump(
                    {
                        "cp_address": self.cp_address,
                        "session_id": self.session_id,
                        "ha_dir": self.ha_dir,
                    },
                    f,
                )
        return self

    # ------------------------------------------------------------ HA head
    def _start_cp_candidates(self, env: dict, count: int = 2):
        """Spawn ``count`` control-plane candidates over one shared HA
        directory; whichever wins the leader lease serves, the rest tail
        the journal as warm standbys."""
        self.ha_dir = os.path.join(self.log_dir, "cp_ha")
        os.makedirs(self.ha_dir, exist_ok=True)
        for i in range(count):
            self._spawn_cp_candidate(i, env)
        self.cp_address = self._wait_for_leader()

    def _spawn_cp_candidate(self, index: int, env: dict):
        port = find_free_port()
        argv = [
            sys.executable, "-m", "ray_tpu.core.control_plane",
            "--port", str(port),
            "--session-id", self.session_id,
            "--ha-dir", self.ha_dir,
        ]
        log = os.path.join(self.log_dir, f"control_plane_{index}.log")
        proc = self.pg.spawn(argv, log, env)
        cand = {
            "proc": proc,
            "address": f"127.0.0.1:{port}",
            "argv": argv,
            "log": log,
            "env": dict(env),
            "index": index,
        }
        if index < len(self._cp_candidates):
            self._cp_candidates[index] = cand
        else:
            self._cp_candidates.append(cand)
        return cand

    def _wait_for_leader(self, timeout: float = 30.0) -> str:
        """Block until a candidate published the leader endpoint AND
        answers ping there."""
        from .cp_ha import read_endpoint

        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            info = read_endpoint(self.ha_dir)
            if info and info.get("address"):
                try:
                    _wait_for_server(info["address"], timeout=2.0)
                    return info["address"]
                except TimeoutError as e:
                    last = e  # leader died between publish and now
            time.sleep(0.05)
        raise TimeoutError(f"no control-plane leader elected: {last}")

    def leader_epoch(self) -> int:
        from .cp_ha import read_endpoint

        info = read_endpoint(self.ha_dir) if self.ha_dir else None
        return info.get("epoch", 0) if info else 0

    def kill_leader(self) -> int:
        """``kill -9`` the current leader candidate; returns the epoch it
        served under (pass to ``wait_for_failover``)."""
        assert self.head and self._cp_candidates, "HA head required"
        from .cp_ha import read_endpoint

        info = read_endpoint(self.ha_dir) or {}
        leader_address = info.get("address")
        epoch = info.get("epoch", 0)
        for cand in self._cp_candidates:
            if cand["address"] == leader_address and cand["proc"].poll() is None:
                cand["proc"].kill()
                cand["proc"].wait(timeout=10)
                return epoch
        raise RuntimeError(f"no live candidate serves {leader_address}")

    def wait_for_failover(self, old_epoch: int, timeout: float = 30.0) -> str:
        """Block until a NEWER leader (epoch > old_epoch) serves; updates
        and returns ``cp_address``."""
        from .cp_ha import read_endpoint

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = read_endpoint(self.ha_dir)
            if info and info.get("epoch", 0) > old_epoch and info.get("address"):
                try:
                    _wait_for_server(info["address"], timeout=2.0)
                    self.cp_address = info["address"]
                    return info["address"]
                except TimeoutError:
                    pass
            time.sleep(0.05)
        raise TimeoutError(
            f"no failover past epoch {old_epoch} within {timeout}s"
        )

    def ensure_standby(self):
        """Respawn any dead candidate so the cluster regains a warm
        standby after a failover (the chaos injector's revert)."""
        assert self.head and self.ha_dir
        for cand in list(self._cp_candidates):
            if cand["proc"].poll() is not None:
                try:
                    self.pg.procs.remove(cand["proc"])
                except ValueError:
                    pass
                self._spawn_cp_candidate(cand["index"], cand["env"])

    def kill_control_plane(self):
        """Hard-kill the control-plane process (head nodes only) — the
        GCS-crash half of the restart-FT test story."""
        assert self.head, "control plane runs on the head node"
        assert not self._cp_candidates, "HA mode: use kill_leader()"
        proc = self.pg.procs[0]
        proc.kill()
        proc.wait(timeout=10)

    def restart_control_plane(self):
        """Restart the control plane on the same port; with persistence on,
        it reloads its tables and agents/drivers reconnect (reference:
        python/ray/tests/test_gcs_fault_tolerance.py)."""
        assert self.head and self._cp_argv is not None
        proc = self.pg.procs[0]
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        self.pg.spawn(self._cp_argv, self._cp_log, self._cp_env)
        # The new process replaces slot 0 so kill ordering stays stable.
        self.pg.procs[0] = self.pg.procs.pop()
        _wait_for_server(self.cp_address)

    def stop(self):
        # The HA discovery env var must die with the node that exported
        # it: a later non-HA init in this process would otherwise build
        # resolvers on this (now dead) session's endpoint record.
        if self.ha_dir and os.environ.get("RAY_TPU_CP_HA_DIR") == self.ha_dir:
            del os.environ["RAY_TPU_CP_HA_DIR"]
        self.pg.kill_all()
        from .object_store import drop_arena

        drop_arena(self.session_id)
        if self.head:
            # Session-wide shm (arena + segments) belongs to the HEAD's
            # lifetime: a worker/client node leaving must not delete the
            # store out from under every other node in the session.
            shm.cleanup_session(self.session_id)


class Cluster:
    """In-process multi-node test cluster: one control plane + N node agents
    on this machine (ray ``cluster_utils.Cluster`` analog).  Nodes can be
    added and killed freely to exercise fault-tolerance paths."""

    def __init__(self):
        self.head_node: Optional[Node] = None
        self.worker_nodes: List[Node] = []

    @property
    def cp_address(self) -> str:
        assert self.head_node is not None
        return self.head_node.cp_address  # type: ignore[return-value]

    def add_node(self, num_cpus: float = 1, resources=None, labels=None) -> Node:
        if self.head_node is None:
            node = Node(
                head=True, resources=resources, labels=labels,
                num_cpus=num_cpus, die_with_parent=True,
            )
            node.start()
            self.head_node = node
        else:
            node = Node(
                head=False,
                cp_address=self.cp_address,
                resources=resources,
                labels=labels,
                session_id=self.head_node.session_id,
                num_cpus=num_cpus,
                die_with_parent=True,
            )
            node.start()
            self.worker_nodes.append(node)
        return node

    def kill_node(self, node: Node):
        node.pg.kill_all()
        if node in self.worker_nodes:
            self.worker_nodes.remove(node)

    def shutdown(self):
        for node in self.worker_nodes:
            node.stop()
        self.worker_nodes.clear()
        if self.head_node:
            self.head_node.stop()
            self.head_node = None


def read_head_info() -> Optional[dict]:
    try:
        with open(_HEAD_INFO_FILE) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None
