"""Sharded owner table: the per-worker ``ObjectID -> OwnedObject`` map.

Role-equivalent of the reference's ``reference_counter`` + ownership
object-directory storage (Ray ``src/ray/core_worker/reference_count.h``),
partitioned by ObjectID so the owner's hot read paths —
``get_object_batch`` / ``probe_object_batch`` resolution from many
borrower connections — index independent shards instead of serializing
on one structure.  With the multi-lane RPC service (``rpc.py``), lane
threads consult shards directly for READY objects; anything that needs
loop-affine work (unset events, reconstruction, frees) still routes to
the primary loop, so mutation stays single-threaded while reads scale
out.

Thread model per shard: CPython dict get/set/pop are GIL-atomic, so
reads take no lock; the per-shard lock exists for compound
read-modify-write sequences by lane-side callers (none today — incref/
decref forward to the primary loop — but the accessor is the contract
new lane-side mutations must use).  Shard routing uses the tail bytes
of the ObjectID, which are random for every ID kind.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from ..util.debug_locks import make_lock
from ..util import debug_lanes


class OwnerTable:
    """Dict-compatible sharded map (the subset of the dict API the
    core worker uses), plus per-shard accessors and counters."""

    __slots__ = ("_shards", "_locks", "_mask", "num_shards", "lookups",
                 "_lane_tags")

    def __init__(self, num_shards: int = 16):
        # Power-of-two shard count so routing is a mask, not a modulo.
        n = 1
        while n < max(1, int(num_shards)):
            n <<= 1
        self.num_shards = n
        self._mask = n - 1
        self._shards: List[dict] = [{} for _ in range(n)]
        self._locks = [
            make_lock(f"core_worker.owner_table.shard{i}") for i in range(n)
        ]
        self.lookups = [0] * n  # per-shard get() count (hot-path telemetry)
        # RAY_TPU_DEBUG_LANES=1: per-shard lane tags.  Mutations from
        # registered lane threads must hold the shard lock; the user
        # thread and primary loop stay lock-free per the GIL-atomic
        # thread model above.  None when off — mutators pay one is-None
        # check, reads pay nothing.
        self._lane_tags = (
            [debug_lanes.LaneTag(f"owner_table.shard{i}") for i in range(n)]
            if debug_lanes.debug_lanes_enabled() else None
        )

    def shard_index(self, oid) -> int:
        # IDs precompute their hash at construction (ids.py __slots__
        # ``_hash``): routing is one attribute read + a mask, keeping the
        # table's overhead over a plain dict at nanoseconds on the
        # sync-get fast path.  Per-process stable (that's all routing
        # needs); NOT stable across processes under hash randomization.
        return oid._hash & self._mask

    def shard_lock(self, oid):
        """Lock guarding compound mutations of ``oid``'s shard from off
        the primary loop (lane-safe accessor contract).  Under
        ``RAY_TPU_DEBUG_LANES=1`` the lock comes back wrapped so holding
        it *registers* with the lane checker — mutations under it are
        sanctioned, mutations without it from a foreign thread trip the
        checker."""
        i = oid._hash & self._mask
        if self._lane_tags is not None:
            return debug_lanes.guarded(self._locks[i], self._lane_tags[i])
        return self._locks[i]

    # ----------------------------------------------------- dict-compatible
    # Bodies inline the shard routing (no self.shard_index call): get()
    # sits on the user-thread sync-get hot path where an extra Python
    # frame per lookup is measurable.
    def get(self, oid, default=None):
        i = oid._hash & self._mask
        self.lookups[i] += 1
        return self._shards[i].get(oid, default)

    def __getitem__(self, oid):
        i = oid._hash & self._mask
        self.lookups[i] += 1
        return self._shards[i][oid]

    def __setitem__(self, oid, obj):
        i = oid._hash & self._mask
        if self._lane_tags is not None:
            debug_lanes.check_lane_mutation(self._lane_tags[i], "__setitem__")
        self._shards[i][oid] = obj

    def __delitem__(self, oid):
        i = oid._hash & self._mask
        if self._lane_tags is not None:
            debug_lanes.check_lane_mutation(self._lane_tags[i], "__delitem__")
        del self._shards[i][oid]

    def pop(self, oid, default=None):
        i = oid._hash & self._mask
        if self._lane_tags is not None:
            debug_lanes.check_lane_mutation(self._lane_tags[i], "pop")
        return self._shards[i].pop(oid, default)

    def __contains__(self, oid) -> bool:
        return oid in self._shards[oid._hash & self._mask]

    def __len__(self) -> int:
        return sum(len(s) for s in self._shards)

    def __bool__(self) -> bool:
        return any(self._shards)

    def values(self) -> Iterator:
        for shard in self._shards:
            yield from shard.values()

    def items(self) -> Iterator[Tuple[object, object]]:
        for shard in self._shards:
            yield from shard.items()

    def keys(self) -> Iterator:
        for shard in self._shards:
            yield from shard.keys()

    def __iter__(self) -> Iterator:
        return self.keys()

    # ------------------------------------------------------------ telemetry
    def shard_sizes(self) -> List[int]:
        return [len(s) for s in self._shards]

    def stats(self) -> Dict[str, object]:
        sizes = self.shard_sizes()
        return {
            "num_shards": self.num_shards,
            "objects": sum(sizes),
            "max_shard": max(sizes) if sizes else 0,
            "lookups_total": sum(self.lookups),
        }
