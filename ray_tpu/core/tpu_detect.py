"""TPU chip / slice detection without initializing the runtime.

Equivalent of the reference's TPUAcceleratorManager detection path (ray
``python/ray/_private/accelerators/tpu.py:267-672``): chips are discovered
from device files and GCE metadata env vars — never by importing jax, which
would grab the chips.  Publishes:
  - ``TPU``: number of chips on this host
  - ``TPU-{version}`` resource (e.g. ``TPU-v5e``): same count, typed
  - ``TPU-{pod_name}-head``: 1 on worker 0 of a pod slice (gang anchor)
  - labels: accelerator type, topology, worker id — used for
    ICI-topology-aware label scheduling.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, Tuple


def num_local_chips() -> int:
    override = os.environ.get("RAY_TPU_NUM_CHIPS")
    if override is not None:
        return int(override)
    # TPU VM device files: /dev/accel* (older) or /dev/vfio/* (newer PCIe).
    chips = glob.glob("/dev/accel*")
    if chips:
        return len(chips)
    vfio = [p for p in glob.glob("/dev/vfio/*") if re.fullmatch(r".*/\d+", p)]
    if vfio:
        return len(vfio)
    return 0


def accelerator_type() -> str:
    env = os.environ.get("TPU_ACCELERATOR_TYPE", "")  # e.g. "v5litepod-16"
    if env:
        m = re.match(r"(v\d+[a-z]*)", env)
        if m:
            version = m.group(1)
            return {"v5litepod": "v5e", "v5p": "v5p"}.get(version, version)
    return os.environ.get("RAY_TPU_ACCELERATOR_VERSION", "")


def pod_name() -> str:
    return os.environ.get("TPU_NAME", os.environ.get("RAY_TPU_POD_NAME", ""))


def worker_id() -> int:
    return int(os.environ.get("TPU_WORKER_ID", "0"))


def topology() -> str:
    return os.environ.get("TPU_TOPOLOGY", os.environ.get("RAY_TPU_TOPOLOGY", ""))


VALID_TOPOLOGY_RE = re.compile(r"^\d+x\d+(x\d+)?$")


def validate_topology(topo: str) -> bool:
    return bool(VALID_TOPOLOGY_RE.match(topo))


def detect_resources_and_labels() -> Tuple[Dict[str, float], Dict[str, str]]:
    resources: Dict[str, float] = {}
    labels: Dict[str, str] = {}
    chips = num_local_chips()
    if chips > 0:
        resources["TPU"] = float(chips)
        version = accelerator_type()
        if version:
            resources[f"TPU-{version}"] = float(chips)
            labels["tpu-version"] = version
        pod = pod_name()
        if pod:
            labels["tpu-pod-name"] = pod
            labels["tpu-worker-id"] = str(worker_id())
            if worker_id() == 0:
                resources[f"TPU-{pod}-head"] = 1.0
        topo = topology()
        if topo:
            labels["tpu-topology"] = topo
    return resources, labels
