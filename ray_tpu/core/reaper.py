"""Parent-death reaper for spawned system processes.

When a driver spawns the control plane / node agents with
``die_with_parent`` (``ray_tpu.init`` and the in-process test ``Cluster``),
they receive ``RAY_TPU_PARENT_PID`` and self-exit once that process is gone
— a SIGKILLed driver must not orphan cluster processes (reference
precedent: ray's process reaper).  Detached starts (``ray-tpu start``) set
no parent pid and are unaffected.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional


def watch_parent_process(on_exit: Optional[Callable[[], None]] = None) -> None:
    """Start the reaper thread if ``RAY_TPU_PARENT_PID`` is set.

    ``on_exit`` runs (best-effort) just before the process exits — e.g. the
    node agent unlinks its session's shm arena.
    """
    ppid = int(os.environ.get("RAY_TPU_PARENT_PID", "0") or "0")
    if not ppid:
        return

    def loop():
        while True:
            time.sleep(1.0)
            try:
                os.kill(ppid, 0)
            except OSError:
                if on_exit is not None:
                    try:
                        on_exit()
                    except Exception:  # noqa: BLE001 — exiting anyway
                        pass
                os._exit(0)

    threading.Thread(target=loop, daemon=True, name="parent-watch").start()
