"""Parent-death reaper for spawned system processes.

When a driver spawns the control plane / node agents with
``die_with_parent`` (``ray_tpu.init`` and the in-process test ``Cluster``),
they receive ``RAY_TPU_PARENT_PID`` and self-exit once that process is gone
— a SIGKILLed driver must not orphan cluster processes (reference
precedent: ray's process reaper).  Detached starts (``ray-tpu start``) set
no parent pid and are unaffected.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional


def _proc_start_time(pid: int) -> Optional[str]:
    """Kernel start time of ``pid`` (field 22 of /proc/<pid>/stat) — the
    (pid, starttime) pair uniquely identifies a process, so PID reuse
    cannot masquerade as a live parent."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read()
        # comm may contain spaces/parens: split after the LAST ')'.
        rest = data[data.rindex(b")") + 2 :].split()
        return rest[19].decode()  # starttime is field 22 overall
    except (OSError, ValueError, IndexError):
        return None


def watch_parent_process(on_exit: Optional[Callable[[], None]] = None) -> None:
    """Start the reaper thread if ``RAY_TPU_PARENT_PID`` is set.

    ``on_exit`` runs (best-effort) just before the process exits — e.g. the
    node agent unlinks its session's shm arena.
    """
    ppid = int(os.environ.get("RAY_TPU_PARENT_PID", "0") or "0")
    if not ppid:
        return
    birth = _proc_start_time(ppid)

    def loop():
        while True:
            time.sleep(1.0)
            if birth is None:
                # No readable /proc for the parent (non-Linux or masked):
                # fall back to the portable signal-0 probe — keeps the
                # PID-reuse hardening on Linux without killing healthy
                # clusters elsewhere.
                try:
                    os.kill(ppid, 0)
                    alive = True
                except OSError:
                    alive = False
            else:
                alive = _proc_start_time(ppid) == birth
            if not alive:
                if on_exit is not None:
                    try:
                        on_exit()
                    except Exception:  # raylint: waive[RTL003] exiting anyway
                        pass
                os._exit(0)

    threading.Thread(target=loop, daemon=True, name="parent-watch").start()
