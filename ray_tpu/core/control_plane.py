"""Cluster control plane — the GCS equivalent.

One process per cluster (Ray ``src/ray/gcs/gcs_server.h``).  Owns:
  - node table + health checking (GcsNodeManager / GcsHealthCheckManager)
  - cluster-wide KV store (InternalKV) — function exports, named actors, user KV
  - actor directory + scheduling + restart FT (GcsActorManager/Scheduler)
  - placement groups with two-phase Prepare/Commit across node agents
    (GcsPlacementGroupManager/Scheduler)
  - job table
  - pubsub of node/actor state changes (long-poll-free: server-push over the
    subscriber's existing connection, Ray ``src/ray/pubsub/``)
  - the authoritative eventually-consistent resource view (ray_syncer analog:
    agents push snapshots on every heartbeat).

Storage is pluggable (``store_client.py``, the reference's
``gcs/store_client/`` hierarchy): in-memory, or an embedded sqlite journal
under the session directory for restart fault tolerance.  With the durable
store, the KV, actor, placement-group and job tables survive a
control-plane crash: the restarted process reloads them, node agents
re-register on their next heartbeat ("reregister" reply), drivers likewise,
and pending actors/PGs resume scheduling — the
``test_gcs_fault_tolerance.py`` story without the external Redis.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import logging
import pickle
import time
from typing import Dict, List, Optional, Set

from .config import GlobalConfig
from .ids import ActorID, JobID, NodeID, PlacementGroupID
from .resources import ResourceSet
from .rpc import ClientPool, RpcServer, ServerConnection
from .scheduler import ClusterScheduler, InfeasibleError
from .event_export import (
    ACTOR_DEFINITION,
    ACTOR_LIFECYCLE,
    JOB_LIFECYCLE,
    NODE_LIFECYCLE,
    PG_LIFECYCLE,
    EventRecorder,
)
from .store_client import make_store_client
from .task_events import TaskEventStore
from .task_spec import ActorSpec

logger = logging.getLogger(__name__)

# Actor lifecycle states (reference: rpc::ActorTableData::ActorState).
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class NodeEntry:
    def __init__(self, node_id: NodeID, agent_address: str, snapshot: dict):
        self.node_id = node_id
        self.agent_address = agent_address
        self.snapshot = snapshot
        self.last_heartbeat = time.monotonic()
        self.alive = True


class ActorEntry:
    def __init__(self, spec: ActorSpec):
        self.spec = spec
        self.state = PENDING_CREATION
        self.address: Optional[str] = None  # worker RPC address
        self.node_id: Optional[NodeID] = None
        self.num_restarts = 0
        self.incarnation = 0
        self.death_cause: Optional[str] = None

    def public_info(self) -> dict:
        return {
            "actor_id": self.spec.actor_id,
            "state": self.state,
            "address": self.address,
            "incarnation": self.incarnation,
            "name": self.spec.name,
            "death_cause": self.death_cause,
            "max_task_retries": self.spec.max_task_retries,
        }


class PlacementGroupEntry:
    def __init__(self, pg_id, bundles: List[dict], strategy: str, name: str):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = "PENDING"  # PENDING | CREATED | REMOVED
        self.bundle_nodes: Optional[List[NodeID]] = None

    def public_info(self) -> dict:
        return {
            "pg_id": self.pg_id,
            "state": self.state,
            "bundles": self.bundles,
            "strategy": self.strategy,
            "bundle_nodes": [n.hex() if n else None for n in (self.bundle_nodes or [])],
        }


class ControlPlane:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 session_id: str = "", store_path: Optional[str] = None):
        self.session_id = session_id
        self.server = RpcServer(self, host, port)
        self.scheduler = ClusterScheduler()
        self.nodes: Dict[NodeID, NodeEntry] = {}
        self.agent_clients = ClientPool()
        self._kv: Dict[str, Dict[str, bytes]] = {}  # namespace -> key -> value
        self.actors: Dict[ActorID, ActorEntry] = {}
        self.named_actors: Dict[tuple, ActorID] = {}  # (namespace, name) -> id
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupEntry] = {}
        self.jobs: Dict[JobID, dict] = {}
        # pubsub: channel -> set of subscriber connections
        self._subs: Dict[str, Set[ServerConnection]] = {}
        self._pending_actors: List[ActorID] = []
        self._schedule_tasks: set = set()
        self._pending_pgs: List[PlacementGroupID] = []
        self._bg_tasks: List[asyncio.Task] = []
        self.task_event_store = TaskEventStore()
        self._requested_resources: List[dict] = []
        self._recent_unplaceable: List[tuple] = []  # (monotonic ts, resources)
        self.store = make_store_client(store_path)
        export_path = None
        if store_path:
            export_path = os.path.join(
                os.path.dirname(store_path), "events.jsonl"
            )
        self.events = EventRecorder(export_path)
        self._recovered = self._recover()
        # Grace window after a recovery: ALIVE actors whose node never
        # re-registers are declared dead only after agents have had a full
        # health-check timeout to reconnect.
        self._recovery_deadline = (
            time.monotonic() + GlobalConfig.health_check_timeout_s
            if self._recovered
            else None
        )

    # ----------------------------------------------------------- persistence
    _KV_SEP = "\x00"

    def _persist_kv(self, namespace: str, key: str, value,
                    delete: bool = False) -> None:
        if not self.store.durable:
            return
        # KV values are arbitrary picklable objects (the job SDK stores
        # dicts), not only bytes — pickle for the blob store.
        skey = namespace + self._KV_SEP + key
        if delete:
            self.store.delete("kv", skey)
        else:
            self.store.put("kv", skey, pickle.dumps(value))

    def _persist_actor(self, entry: ActorEntry) -> None:
        if not self.store.durable:
            return
        self.store.put(
            "actors",
            entry.spec.actor_id.hex(),
            pickle.dumps(
                {
                    "spec": entry.spec,
                    "state": entry.state,
                    "address": entry.address,
                    "node_id": entry.node_id,
                    "num_restarts": entry.num_restarts,
                    "incarnation": entry.incarnation,
                    "death_cause": entry.death_cause,
                }
            ),
        )

    def _persist_pg(self, entry: PlacementGroupEntry) -> None:
        if not self.store.durable:
            return
        self.store.put(
            "pgs",
            entry.pg_id.hex(),
            pickle.dumps(
                {
                    "pg_id": entry.pg_id,
                    "bundles": entry.bundles,
                    "strategy": entry.strategy,
                    "name": entry.name,
                    "state": entry.state,
                    "bundle_nodes": entry.bundle_nodes,
                }
            ),
        )

    def _persist_job(self, job_id: JobID) -> None:
        if not self.store.durable:
            return
        job = self.jobs[job_id]
        self.store.put(
            "jobs",
            job_id.hex(),
            pickle.dumps(
                {k: v for k, v in job.items() if k != "last_heartbeat"}
            ),
        )

    def _recover(self) -> bool:
        """Rebuild in-memory state from the durable store (no-op for the
        in-memory backend).  Returns True if anything was loaded."""
        loaded = False
        for skey, value in self.store.scan("kv"):
            ns, key = skey.split(self._KV_SEP, 1)
            self._kv.setdefault(ns, {})[key] = pickle.loads(value)
            loaded = True
        for _key, blob in self.store.scan("actors"):
            d = pickle.loads(blob)
            entry = ActorEntry(d["spec"])
            entry.state = d["state"]
            entry.address = d["address"]
            entry.node_id = d["node_id"]
            entry.num_restarts = d["num_restarts"]
            entry.incarnation = d["incarnation"]
            entry.death_cause = d["death_cause"]
            self.actors[entry.spec.actor_id] = entry
            if entry.spec.name is not None and entry.state != DEAD:
                self.named_actors[(entry.spec.namespace, entry.spec.name)] = (
                    entry.spec.actor_id
                )
            if entry.state in (PENDING_CREATION, RESTARTING):
                self._pending_actors.append(entry.spec.actor_id)
            loaded = True
        for _key, blob in self.store.scan("pgs"):
            d = pickle.loads(blob)
            entry = PlacementGroupEntry(
                d["pg_id"], d["bundles"], d["strategy"], d["name"]
            )
            entry.state = d["state"]
            entry.bundle_nodes = d["bundle_nodes"]
            self.placement_groups[entry.pg_id] = entry
            if entry.state == "PENDING":
                self._pending_pgs.append(entry.pg_id)
            loaded = True
        now = time.monotonic()
        for key, blob in self.store.scan("jobs"):
            job = pickle.loads(blob)
            job["last_heartbeat"] = now  # grace: drivers re-heartbeat soon
            self.jobs[JobID.from_hex(key)] = job
            loaded = True
        if loaded:
            logger.info(
                "recovered state: %d actors, %d pgs, %d jobs, %d kv ns",
                len(self.actors), len(self.placement_groups), len(self.jobs),
                len(self._kv),
            )
        return loaded

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> str:
        addr = await self.server.start()
        loop = asyncio.get_running_loop()
        self._bg_tasks.append(loop.create_task(self._health_check_loop()))
        logger.info("control plane listening on %s", addr)
        return addr

    async def stop(self):
        for t in self._bg_tasks:
            t.cancel()
        await self.server.stop()
        await self.agent_clients.close_all()
        self.store.close()
        self.events.close()

    # ---------------------------------------------------------------- pubsub
    def _publish(self, channel: str, message: dict):
        dead = []
        for conn in self._subs.get(channel, ()):  # copy not needed; no await
            task = asyncio.get_running_loop().create_task(
                conn.push("pub", {"channel": channel, "message": message})
            )
            task.add_done_callback(lambda t: t.exception())  # swallow
        _ = dead

    def handle_subscribe(self, payload, conn: ServerConnection):
        for channel in payload["channels"]:
            self._subs.setdefault(channel, set()).add(conn)
        conn.metadata.setdefault("channels", set()).update(payload["channels"])
        return True

    def handle_unsubscribe(self, payload, conn: ServerConnection):
        for channel in payload["channels"]:
            self._subs.get(channel, set()).discard(conn)
        return True

    def on_connection_closed(self, conn: ServerConnection):
        for channel in conn.metadata.get("channels", ()):
            self._subs.get(channel, set()).discard(conn)
        # Job liveness is heartbeat-based (see _health_check_loop), NOT
        # connection-based: a transient TCP reset must not kill the job's
        # actors — the driver's RetryableRpcClient reconnects transparently.

    async def _cleanup_job(self, job_id: JobID):
        """Kill the job's non-detached actors."""
        for actor_id, entry in list(self.actors.items()):
            if entry.spec.job_id == job_id and not entry.spec.detached:
                await self._kill_actor_entry(entry, "job finished")

    # ----------------------------------------------------------------- nodes
    def handle_register_node(self, payload, conn):
        node_id = payload["node_id"]
        entry = NodeEntry(node_id, payload["agent_address"], payload["snapshot"])
        self.nodes[node_id] = entry
        self.scheduler.update_node(node_id, payload["snapshot"])
        logger.info(
            "node %s registered (%s) resources=%s",
            node_id.hex()[:8],
            payload["agent_address"],
            payload["snapshot"]["total"],
        )
        self._publish("nodes", {"event": "added", "node_id": node_id})
        self.events.record(
            NODE_LIFECYCLE, node_id.hex(), "ALIVE",
            agent_address=payload["agent_address"],
            resources=payload["snapshot"].get("total", {}),
        )
        self._kick_pending()
        return {"ok": True, "session_id": self.session_id}

    def handle_heartbeat(self, payload, conn):
        node_id = payload["node_id"]
        entry = self.nodes.get(node_id)
        if entry is None:
            return {"ok": False, "reregister": True}
        entry.last_heartbeat = time.monotonic()
        entry.snapshot = payload["snapshot"]
        self.scheduler.update_node(node_id, payload["snapshot"])
        self._kick_pending()
        return {"ok": True}

    def handle_get_cluster_view(self, payload, conn):
        return {
            "nodes": {
                nid: {
                    "agent_address": e.agent_address,
                    "snapshot": e.snapshot,
                    "alive": e.alive,
                }
                for nid, e in self.nodes.items()
                if e.alive
            }
        }

    async def _health_check_loop(self):
        period = GlobalConfig.health_check_period_s
        timeout = GlobalConfig.health_check_timeout_s
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            for node_id, entry in list(self.nodes.items()):
                if entry.alive and now - entry.last_heartbeat > timeout:
                    await self._on_node_dead(node_id)
            if (
                self._recovery_deadline is not None
                and now > self._recovery_deadline
            ):
                # Post-recovery reconciliation: ALIVE actors whose node
                # never re-registered are on lost nodes.
                self._recovery_deadline = None
                for actor_id, a in list(self.actors.items()):
                    if a.state == ALIVE and (
                        a.node_id not in self.nodes
                        or not self.nodes[a.node_id].alive
                    ):
                        await self._on_actor_worker_died(
                            actor_id, "node lost across control-plane restart"
                        )
            for job_id, job in list(self.jobs.items()):
                if (
                    job["state"] == "RUNNING"
                    and now - job.get("last_heartbeat", now) > timeout
                ):
                    job["state"] = "FINISHED"
                    self.events.record(JOB_LIFECYCLE, job_id.hex(), "FINISHED")
                    self._persist_job(job_id)
                    logger.info("job %s lost its driver; cleaning up",
                                job_id.hex())
                    await self._cleanup_job(job_id)

    async def _on_node_dead(self, node_id: NodeID):
        entry = self.nodes.get(node_id)
        if entry is None or not entry.alive:
            return
        entry.alive = False
        self.scheduler.remove_node(node_id)
        logger.warning("node %s marked dead", node_id.hex()[:8])
        self.events.record(NODE_LIFECYCLE, node_id.hex(), "DEAD")
        self._publish("nodes", {"event": "removed", "node_id": node_id})
        # Fail or restart actors that lived there.
        for actor_id, a in list(self.actors.items()):
            if a.node_id == node_id and a.state == ALIVE:
                await self._on_actor_worker_died(actor_id, "node died")

    # -------------------------------------------------------------------- kv
    def handle_kv_put(self, payload, conn):
        ns = self._kv.setdefault(payload.get("namespace", ""), {})
        overwrite = payload.get("overwrite", True)
        if not overwrite and payload["key"] in ns:
            return False
        ns[payload["key"]] = payload["value"]
        self._persist_kv(
            payload.get("namespace", ""), payload["key"], payload["value"]
        )
        return True

    def handle_kv_get(self, payload, conn):
        return self._kv.get(payload.get("namespace", ""), {}).get(payload["key"])

    def handle_kv_del(self, payload, conn):
        ns = self._kv.get(payload.get("namespace", ""), {})
        existed = ns.pop(payload["key"], None) is not None
        if existed:
            self._persist_kv(
                payload.get("namespace", ""), payload["key"], None, delete=True
            )
        return existed

    def handle_kv_keys(self, payload, conn):
        ns = self._kv.get(payload.get("namespace", ""), {})
        prefix = payload.get("prefix", "")
        return [k for k in ns if k.startswith(prefix)]

    def handle_kv_exists(self, payload, conn):
        return payload["key"] in self._kv.get(payload.get("namespace", ""), {})

    # ------------------------------------------------------------------ jobs
    def handle_register_job(self, payload, conn):
        job_id = payload["job_id"]
        self.jobs[job_id] = {
            "state": "RUNNING",
            "driver_address": payload.get("driver_address"),
            "start_time": time.time(),
            "last_heartbeat": time.monotonic(),
        }
        conn.metadata["job_id"] = job_id
        self.events.record(
            JOB_LIFECYCLE, job_id.hex(), "RUNNING",
            driver_address=payload.get("driver_address"),
        )
        self._persist_job(job_id)
        return {"ok": True, "session_id": self.session_id}

    def handle_job_heartbeat(self, payload, conn):
        job = self.jobs.get(payload["job_id"])
        if job is None:
            return {"ok": False, "reregister": True}
        job["last_heartbeat"] = time.monotonic()
        return {"ok": True}

    def handle_list_jobs(self, payload, conn):
        return {jid: dict(info) for jid, info in self.jobs.items()}

    # ---------------------------------------------------------------- actors
    async def handle_register_actor(self, payload, conn):
        spec: ActorSpec = payload["spec"]
        if spec.name is not None:
            key = (spec.namespace, spec.name)
            if key in self.named_actors:
                existing = self.actors.get(self.named_actors[key])
                if existing is not None and existing.state != DEAD:
                    if payload.get("get_if_exists"):
                        return existing.public_info()
                    raise ValueError(
                        f"actor name {spec.name!r} already taken in "
                        f"namespace {spec.namespace!r}"
                    )
            self.named_actors[key] = spec.actor_id
        entry = ActorEntry(spec)
        self.actors[spec.actor_id] = entry
        self.events.record(
            ACTOR_DEFINITION, spec.actor_id.hex(), "REGISTERED",
            name=spec.name or "", namespace=spec.namespace,
            resources=dict(spec.resources),
            max_restarts=spec.max_restarts,
        )
        self._persist_actor(entry)
        # Schedule in the background: registration replies immediately
        # (the reference's GCS actor registration is likewise async) so a
        # burst of .remote() creations pipelines instead of serializing on
        # worker spawn + __init__.  Callers' method submissions wait on
        # the PENDING_CREATION -> ALIVE state publish.
        self._schedule_actor_bg(entry)
        return entry.public_info()

    def _schedule_actor_bg(self, entry: ActorEntry):
        """Run _try_schedule_actor as a retained task: an escaping
        exception re-queues the actor for the next reconcile pass instead
        of silently stranding it in PENDING_CREATION."""
        task = asyncio.get_running_loop().create_task(
            self._try_schedule_actor(entry)
        )
        self._schedule_tasks.add(task)

        def done(t: asyncio.Task):
            self._schedule_tasks.discard(t)
            if t.cancelled():
                return
            exc = t.exception()
            if exc is not None:
                logger.warning(
                    "actor %s scheduling failed: %s; re-queueing",
                    entry.spec.actor_id, exc,
                )
                if entry.spec.actor_id not in self._pending_actors:
                    self._pending_actors.append(entry.spec.actor_id)

        task.add_done_callback(done)

    async def _try_schedule_actor(self, entry: ActorEntry):
        if entry.state == DEAD:
            return  # killed before scheduling got to it
        spec = entry.spec
        if spec.placement_group_id is not None:
            # PG-bound actor: its resources come from the bundle, which was
            # already carved OUT of the node's main pool — consulting
            # pick_node would wrongly demand the capacity twice (and fail
            # on a saturated node).  Target the bundle's node directly.
            pg = self.placement_groups.get(spec.placement_group_id)
            if pg is None or pg.state == "REMOVED":
                # Terminal: an actor bound to a gone PG can never schedule.
                entry.state = DEAD
                entry.death_cause = (
                    f"placement group {spec.placement_group_id} was removed"
                )
                self._publish_actor(entry)
                return
            if pg.state != "CREATED" or not pg.bundle_nodes:
                if spec.actor_id not in self._pending_actors:
                    self._pending_actors.append(spec.actor_id)
                return
            idx = spec.bundle_index if spec.bundle_index >= 0 else 0
            if idx >= len(pg.bundle_nodes):
                entry.state = DEAD
                entry.death_cause = (
                    f"bundle_index {idx} out of range for placement group "
                    f"with {len(pg.bundle_nodes)} bundles"
                )
                self._publish_actor(entry)
                return
            await self._create_actor_on_node(entry, pg.bundle_nodes[idx])
            return
        try:
            node_id = self.scheduler.pick_node(
                ResourceSet(spec.resources), spec.strategy
            )
        except InfeasibleError:
            # No current node shape fits — keep pending rather than fail:
            # the autoscaler may add a node that does (its load state
            # includes this actor's demand), and the reference likewise
            # queues infeasible actors indefinitely.
            if spec.actor_id not in self._pending_actors:
                self._pending_actors.append(spec.actor_id)
            return
        if node_id is None:
            if spec.actor_id not in self._pending_actors:
                self._pending_actors.append(spec.actor_id)
            return
        await self._create_actor_on_node(entry, node_id)

    async def _create_actor_on_node(self, entry: ActorEntry, node_id: NodeID):
        spec = entry.spec
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            if spec.actor_id not in self._pending_actors:
                self._pending_actors.append(spec.actor_id)
            return
        client = self.agent_clients.get(node.agent_address)
        try:
            # The agent's handler may wait for a worker spawn AND an
            # actor_init (each bounded by worker_startup_timeout_s) plus the
            # user __init__ runtime — our deadline must dominate both.
            reply = await client.call(
                "create_actor_worker",
                {"spec": spec, "incarnation": entry.incarnation},
                timeout=GlobalConfig.worker_startup_timeout_s * 2 + 30,
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("actor %s creation on node failed: %s", spec.actor_id, e)
            if spec.actor_id not in self._pending_actors:
                self._pending_actors.append(spec.actor_id)
            return
        if reply.get("init_error"):
            # User constructor raised: permanent failure, never retried.
            entry.state = DEAD
            entry.death_cause = f"actor __init__ failed: {reply['init_error']}"
            self._publish_actor(entry)
            return
        if entry.state == DEAD:
            # Killed while the (async) creation was in flight: the fresh
            # worker must not come up as a zombie holding its lease — kill
            # it and keep the DEAD state (the kill's worker-kill RPC was a
            # no-op because no worker existed yet).
            entry.node_id = node_id
            entry.address = reply["worker_address"]
            await self._kill_actor_worker(entry)
            entry.address = None
            return
        entry.node_id = node_id
        entry.address = reply["worker_address"]
        entry.state = ALIVE
        self._publish_actor(entry)

    def _publish_actor(self, entry: ActorEntry):
        # Every actor state transition publishes — persist + export events
        # at the same spot.
        self.events.record(
            ACTOR_LIFECYCLE, entry.spec.actor_id.hex(), entry.state,
            death_cause=entry.death_cause,
            num_restarts=entry.num_restarts,
        )
        self._persist_actor(entry)
        self._publish("actor:" + entry.spec.actor_id.hex(), entry.public_info())

    def handle_get_actor_info(self, payload, conn):
        entry = self.actors.get(payload["actor_id"])
        if entry is None:
            return None
        return entry.public_info()

    def handle_get_named_actor(self, payload, conn):
        key = (payload.get("namespace", ""), payload["name"])
        actor_id = self.named_actors.get(key)
        if actor_id is None:
            return None
        entry = self.actors[actor_id]
        info = entry.public_info()
        info["spec"] = entry.spec
        return info

    def handle_list_actors(self, payload, conn):
        return [e.public_info() for e in self.actors.values()]

    async def handle_actor_worker_died(self, payload, conn):
        await self._on_actor_worker_died(
            payload["actor_id"], payload.get("cause", "worker died")
        )
        return True

    async def _on_actor_worker_died(self, actor_id: ActorID, cause: str):
        entry = self.actors.get(actor_id)
        if entry is None or entry.state == DEAD:
            return
        restarts_allowed = (
            entry.spec.max_restarts == -1
            or entry.num_restarts < entry.spec.max_restarts
        )
        if restarts_allowed:
            entry.num_restarts += 1
            entry.incarnation += 1
            entry.state = RESTARTING
            entry.address = None
            self._publish_actor(entry)
            await self._try_schedule_actor(entry)
        else:
            entry.state = DEAD
            entry.death_cause = cause
            entry.address = None
            self._publish_actor(entry)

    async def handle_kill_actor(self, payload, conn):
        entry = self.actors.get(payload["actor_id"])
        if entry is None:
            return False
        if payload.get("no_restart", True):
            await self._kill_actor_entry(entry, "ray_tpu.kill")
        else:
            # Kill only the worker process; the death path restarts the
            # actor if restarts remain.
            await self._kill_actor_worker(entry)
            await self._on_actor_worker_died(
                entry.spec.actor_id, "ray_tpu.kill(no_restart=False)"
            )
        return True

    async def _kill_actor_worker(self, entry: ActorEntry):
        if entry.node_id is not None and entry.address is not None:
            node = self.nodes.get(entry.node_id)
            if node is not None and node.alive:
                client = self.agent_clients.get(node.agent_address)
                try:
                    await client.call(
                        "kill_worker", {"worker_address": entry.address}, retries=1
                    )
                except Exception as e:
                    logger.warning("kill_worker RPC to agent failed: %s", e)

    async def _kill_actor_entry(self, entry: ActorEntry, cause: str):
        await self._kill_actor_worker(entry)
        entry.state = DEAD
        entry.death_cause = cause
        entry.address = None
        self._publish_actor(entry)

    # ------------------------------------------------------- placement groups
    async def handle_create_placement_group(self, payload, conn):
        pg_id = payload["pg_id"]
        entry = PlacementGroupEntry(
            pg_id, payload["bundles"], payload["strategy"], payload.get("name", "")
        )
        self.placement_groups[pg_id] = entry
        self.events.record(PG_LIFECYCLE, pg_id.hex(), "PENDING")
        self._persist_pg(entry)
        await self._try_schedule_pg(entry)
        return entry.public_info()

    async def _try_schedule_pg(self, entry: PlacementGroupEntry):
        bundles = [ResourceSet(b) for b in entry.bundles]
        assignment = self.scheduler.pick_nodes_for_bundles(bundles, entry.strategy)
        if assignment is None:
            if entry.pg_id not in self._pending_pgs:
                self._pending_pgs.append(entry.pg_id)
            return
        # Phase 1: prepare on each involved agent.
        by_node: Dict[NodeID, List[int]] = {}
        for idx, nid in enumerate(assignment):
            by_node.setdefault(nid, []).append(idx)
        prepared: List[NodeID] = []
        ok = True
        for nid, idxs in by_node.items():
            client = self.agent_clients.get(self.nodes[nid].agent_address)
            try:
                res = await client.call(
                    "prepare_bundles",
                    {
                        "pg_id": entry.pg_id,
                        "bundles": {i: entry.bundles[i] for i in idxs},
                    },
                )
                if not res["ok"]:
                    ok = False
                    break
                prepared.append(nid)
            except Exception:
                ok = False
                break
        if not ok:
            for nid in prepared:
                client = self.agent_clients.get(self.nodes[nid].agent_address)
                try:
                    await client.call("cancel_bundles", {"pg_id": entry.pg_id})
                except Exception as e:
                    logger.warning("cancel_bundles to agent failed: %s", e)
            if entry.pg_id not in self._pending_pgs:
                self._pending_pgs.append(entry.pg_id)
            return
        # Phase 2: commit.
        for nid in by_node:
            client = self.agent_clients.get(self.nodes[nid].agent_address)
            await client.call("commit_bundles", {"pg_id": entry.pg_id})
        entry.bundle_nodes = list(assignment)
        entry.state = "CREATED"
        self.events.record(PG_LIFECYCLE, entry.pg_id.hex(), "CREATED")
        self._persist_pg(entry)
        self._publish("pg:" + entry.pg_id.hex(), entry.public_info())

    async def handle_remove_placement_group(self, payload, conn):
        entry = self.placement_groups.get(payload["pg_id"])
        if entry is None:
            return False
        if entry.bundle_nodes:
            for nid in set(entry.bundle_nodes):
                node = self.nodes.get(nid)
                if node is None or not node.alive:
                    continue
                client = self.agent_clients.get(node.agent_address)
                try:
                    await client.call("return_bundles", {"pg_id": entry.pg_id})
                except Exception as e:
                    logger.debug("return_bundles to agent failed: %s", e)
        entry.state = "REMOVED"
        self.events.record(PG_LIFECYCLE, entry.pg_id.hex(), "REMOVED")
        self._persist_pg(entry)
        if payload["pg_id"] in self._pending_pgs:
            self._pending_pgs.remove(payload["pg_id"])
        self._publish("pg:" + entry.pg_id.hex(), entry.public_info())
        return True

    def handle_get_placement_group(self, payload, conn):
        entry = self.placement_groups.get(payload["pg_id"])
        return entry.public_info() if entry else None

    def handle_list_placement_groups(self, payload, conn):
        return [e.public_info() for e in self.placement_groups.values()]

    # ------------------------------------------------------- pending retries
    def _kick_pending(self):
        if self._pending_actors or self._pending_pgs:
            asyncio.get_running_loop().create_task(self._drain_pending())

    async def _drain_pending(self):
        pending_actors, self._pending_actors = self._pending_actors, []
        for actor_id in pending_actors:
            entry = self.actors.get(actor_id)
            if entry is not None and entry.state in (PENDING_CREATION, RESTARTING):
                await self._try_schedule_actor(entry)
        pending_pgs, self._pending_pgs = self._pending_pgs, []
        for pg_id in pending_pgs:
            entry = self.placement_groups.get(pg_id)
            if entry is not None and entry.state == "PENDING":
                await self._try_schedule_pg(entry)

    # -------------------------------------------------------------- lookups
    def handle_pick_node_for_lease(self, payload, conn):
        """Spillback target selection for agents that can't fit a lease.
        Unplaceable demands are remembered briefly so the autoscaler's load
        state sees them (they live in no queue while the submitter backs
        off and retries)."""
        pg_id = payload.get("placement_group_id")
        if pg_id is not None:
            # PG-bound lease: the only valid target is the bundle's node
            # (its resources live in that node's bundle pool).
            entry = self.placement_groups.get(pg_id)
            if entry is None or entry.state == "REMOVED":
                # Fatal (not retry-until-autoscaled): the PG is gone.
                return {
                    "infeasible": True,
                    "fatal": True,
                    "error": f"placement group {pg_id} was removed",
                }
            if entry.state != "CREATED" or not entry.bundle_nodes:
                return {"node_id": None}  # PG pending; submitter retries
            idx = payload.get("bundle_index", -1)
            idx = idx if idx >= 0 else 0
            if idx >= len(entry.bundle_nodes):
                return {
                    "infeasible": True,
                    "fatal": True,
                    "error": (
                        f"bundle_index {idx} out of range for placement "
                        f"group with {len(entry.bundle_nodes)} bundles"
                    ),
                }
            node_id = entry.bundle_nodes[idx]
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return {"node_id": None}
            return {"node_id": node_id, "agent_address": node.agent_address}
        try:
            node_id = self.scheduler.pick_node(
                ResourceSet(payload["resources"]),
                payload.get("strategy"),
                preferred=payload.get("preferred"),
            )
        except InfeasibleError as e:
            self._note_unplaceable(payload["resources"])
            return {"infeasible": True, "error": str(e)}
        if node_id is None:
            self._note_unplaceable(payload["resources"])
            return {"node_id": None}
        return {
            "node_id": node_id,
            "agent_address": self.nodes[node_id].agent_address,
        }

    # ------------------------------------------------------------- autoscaler
    def handle_get_load_state(self, payload, conn):
        """Cluster load snapshot for the autoscaler (reference:
        ``GcsAutoscalerStateManager`` state consumed by
        ``autoscaler/v2/autoscaler.py:50``)."""
        pending_actors = []
        for actor_id in self._pending_actors:
            entry = self.actors.get(actor_id)
            if entry is not None and entry.state in (PENDING_CREATION, RESTARTING):
                pending_actors.append(dict(entry.spec.resources))
        pending_pgs = []
        for pg_id in self._pending_pgs:
            entry = self.placement_groups.get(pg_id)
            if entry is not None and entry.state == "PENDING":
                pending_pgs.append(
                    {
                        "strategy": entry.strategy,
                        "bundles": [dict(b) for b in entry.bundles],
                    }
                )
        return {
            "nodes": {
                nid.hex(): {
                    "alive": e.alive,
                    "total": e.snapshot.get("total", {}),
                    "available": e.snapshot.get("available", {}),
                    "labels": e.snapshot.get("labels", {}),
                    "pending_demands": e.snapshot.get("pending_demands", []),
                    "idle_s": e.snapshot.get("idle_s", 0.0),
                }
                for nid, e in self.nodes.items()
            },
            "pending_actors": pending_actors,
            "pending_pgs": pending_pgs,
            "requested_resources": list(self._requested_resources),
            "unplaceable_demands": [
                dict(r)
                for ts, r in self._recent_unplaceable
                if time.monotonic() - ts < 5.0
            ],
        }

    def _note_unplaceable(self, resources: dict, window_s: float = 5.0):
        now = time.monotonic()
        self._recent_unplaceable = [
            (ts, r) for ts, r in self._recent_unplaceable
            if now - ts < window_s
        ]
        self._recent_unplaceable.append((now, dict(resources)))

    def handle_request_resources(self, payload, conn):
        """Explicit autoscaling demand (``ray.autoscaler.sdk.
        request_resources`` analog): a standing list of resource bundles the
        cluster should be able to fit."""
        self._requested_resources = [
            dict(b) for b in payload.get("bundles", [])
        ]
        return True

    # ------------------------------------------------------------ task events
    def handle_task_events(self, payload, conn):
        """Worker task-event flush (GcsTaskManager::HandleAddTaskEventData
        analog)."""
        self.task_event_store.add_batch(
            payload.get("events", ()), payload.get("profile_events", ())
        )
        return True

    def handle_list_task_events(self, payload, conn):
        return {
            "tasks": self.task_event_store.list_tasks(
                payload.get("filters"), payload.get("limit", 1000)
            ),
            "profile_events": self.task_event_store.profile_events(),
            "num_dropped": self.task_event_store.num_dropped,
        }

    async def handle_list_objects(self, payload, conn):
        """Cluster-wide sealed-object listing: concurrent fan-out to every
        alive agent's directory (``ray list objects`` analog) — one wedged
        agent must not serialize the whole sweep."""

        async def one(address):
            try:
                return await self.agent_clients.get(address).call(
                    "list_objects", {}, timeout=10, retries=1
                )
            except Exception:  # noqa: BLE001 — agent racing shutdown
                return []

        replies = await asyncio.gather(
            *(
                one(entry.agent_address)
                for entry in list(self.nodes.values())
                if entry.alive
            )
        )
        return [row for reply in replies for row in reply]

    def handle_list_cluster_events(self, payload, conn):
        """Typed lifecycle events (reference: RayEventRecorder export)."""
        return self.events.list_events(
            payload.get("event_type"), payload.get("entity_id"),
            payload.get("limit", 1000),
        )

    def handle_ping(self, payload, conn):
        return "pong"

    def handle_get_state(self, payload, conn):
        """State-API snapshot (reference: ray.util.state / StateAggregator)."""
        return {
            "nodes": {
                nid.hex(): {"alive": e.alive, "snapshot": e.snapshot}
                for nid, e in self.nodes.items()
            },
            "actors": [e.public_info() for e in self.actors.values()],
            "placement_groups": [
                e.public_info() for e in self.placement_groups.values()
            ],
            "jobs": {jid.hex(): dict(j) for jid, j in self.jobs.items()},
        }



def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--session-id", required=True)
    parser.add_argument("--store-path", default=None)
    args = parser.parse_args()
    from .reaper import watch_parent_process

    watch_parent_process()
    logging.basicConfig(
        level=GlobalConfig.log_level,
        format="%(asctime)s %(levelname)s control_plane: %(message)s",
    )

    async def run():
        from .stack_dump import install_signal_dumpers

        install_signal_dumpers(asyncio.get_running_loop())
        cp = ControlPlane(
            args.host, args.port, args.session_id, store_path=args.store_path
        )
        await cp.start()
        await asyncio.Event().wait()  # serve forever

    asyncio.run(run())


if __name__ == "__main__":
    main()
