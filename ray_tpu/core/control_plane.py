"""Cluster control plane — the GCS equivalent.

One process per cluster (Ray ``src/ray/gcs/gcs_server.h``).  Owns:
  - node table + health checking (GcsNodeManager / GcsHealthCheckManager)
  - cluster-wide KV store (InternalKV) — function exports, named actors, user KV
  - actor directory + scheduling + restart FT (GcsActorManager/Scheduler)
  - placement groups with two-phase Prepare/Commit across node agents
    (GcsPlacementGroupManager/Scheduler)
  - job table
  - pubsub of node/actor state changes (long-poll-free: server-push over the
    subscriber's existing connection, Ray ``src/ray/pubsub/``)
  - the authoritative eventually-consistent resource view (ray_syncer analog:
    agents push snapshots on every heartbeat).

Storage is pluggable (``store_client.py``, the reference's
``gcs/store_client/`` hierarchy): in-memory, or an embedded sqlite journal
under the session directory for restart fault tolerance.  With the durable
store, the KV, actor, placement-group and job tables survive a
control-plane crash: the restarted process reloads them, node agents
re-register on their next heartbeat ("reregister" reply), drivers likewise,
and pending actors/PGs resume scheduling — the
``test_gcs_fault_tolerance.py`` story without the external Redis.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import logging
import pickle
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set

from .admission import JobArbiter
from .config import GlobalConfig
from .ids import ActorID, JobID, NodeID, PlacementGroupID
from .resources import ResourceSet
from .rpc import (
    ClientPool,
    NotLeaderError,
    RpcServer,
    ServerConnection,
    resolve_service_lanes,
)
from .scheduler import ClusterScheduler, InfeasibleError
from .event_export import (
    ACTOR_DEFINITION,
    ACTOR_LIFECYCLE,
    JOB_LIFECYCLE,
    NODE_LIFECYCLE,
    PG_LIFECYCLE,
    EventRecorder,
)
from .store_client import FencedWriteError, make_store_client
from .task_events import TaskEventStore
from .task_spec import ActorSpec

logger = logging.getLogger(__name__)

# Actor lifecycle states (reference: rpc::ActorTableData::ActorState).
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class NodeEntry:
    def __init__(self, node_id: NodeID, agent_address: str, snapshot: dict):
        self.node_id = node_id
        self.agent_address = agent_address
        self.snapshot = snapshot
        self.last_heartbeat = time.monotonic()
        self.alive = True
        # Drain state machine (autoscaler scale-down): a draining node is
        # unschedulable but still heartbeats; the autoscaler terminates it
        # once drain_status reports it empty.
        self.draining = False
        self.drain_cause = ""
        self.drain_started = 0.0


class ActorEntry:
    def __init__(self, spec: ActorSpec):
        self.spec = spec
        self.state = PENDING_CREATION
        self.address: Optional[str] = None  # worker RPC address
        self.node_id: Optional[NodeID] = None
        self.num_restarts = 0
        self.incarnation = 0
        self.death_cause: Optional[str] = None

    def public_info(self) -> dict:
        return {
            "actor_id": self.spec.actor_id,
            "state": self.state,
            "address": self.address,
            "incarnation": self.incarnation,
            "name": self.spec.name,
            "death_cause": self.death_cause,
            "max_task_retries": self.spec.max_task_retries,
        }


class PlacementGroupEntry:
    def __init__(self, pg_id, bundles: List[dict], strategy: str, name: str,
                 job_id: Optional[JobID] = None,
                 priority: Optional[int] = None, created_seq: int = 0):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = "PENDING"  # PENDING | CREATED | REMOVED
        self.bundle_nodes: Optional[List[NodeID]] = None
        # Arbitration: owning job, effective priority (resolved once at
        # creation), and a monotonic creation sequence — victim selection
        # is (priority asc, created_seq desc): lowest priority, newest
        # first, so the cheapest work (least sunk progress) dies first.
        self.job_id = job_id
        self.priority = (
            priority if priority is not None
            else GlobalConfig.sched_default_priority
        )
        self.created_seq = created_seq
        self.preemptions = 0

    def public_info(self) -> dict:
        return {
            "pg_id": self.pg_id,
            "state": self.state,
            "bundles": self.bundles,
            "strategy": self.strategy,
            "bundle_nodes": [n.hex() if n else None for n in (self.bundle_nodes or [])],
            "job_id": self.job_id.hex() if self.job_id else None,
            "priority": self.priority,
            "preemptions": self.preemptions,
        }


class ControlPlane:
    # Read-only SINGLE-KEY lookups the multi-lane RPC server may serve
    # directly on a lane thread: individual dict get/contains are
    # GIL-atomic and every mutation happens on the primary loop (see
    # rpc.RpcServer).  job_heartbeat's single timestamp store is likewise
    # atomic.  Handlers that ITERATE shared dicts (list_actors, kv_keys,
    # get_cluster_view, ...) are deliberately NOT here — iteration racing
    # a primary-loop insert raises "dict changed size during iteration" —
    # and everything stateful (node/actor/PG machines, KV writes, pubsub)
    # forwards to the primary loop.
    LANE_SAFE_METHODS = frozenset({
        "kv_get",
        "kv_exists",
        "get_actor_info",
        "get_named_actor",
        "get_placement_group",
        "job_heartbeat",
        "ping",
    })

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 session_id: str = "", store_path: Optional[str] = None,
                 store=None, ha_dir: Optional[str] = None, lease=None):
        self.session_id = session_id
        # HA mode (core/cp_ha.py): a pre-warmed journaled store and the
        # leader lease we serve under arrive from run_ha_candidate();
        # store_path keeps the plain single-CP sqlite path working.
        self.ha_dir = ha_dir
        self.lease = lease
        self._fenced = False
        self.server = RpcServer(self, host, port, lanes=resolve_service_lanes())
        self.scheduler = ClusterScheduler()
        self.arbiter = JobArbiter()
        self._pg_seq = 0
        # Actors being checkpoint-then-evicted: their worker-death reports
        # must not consume max_restarts (eviction is scheduler policy, not
        # a failure of the actor).
        self._evicting_actors: Set[ActorID] = set()
        self.nodes: Dict[NodeID, NodeEntry] = {}
        self.agent_clients = ClientPool()
        self._kv: Dict[str, Dict[str, bytes]] = {}  # namespace -> key -> value
        self.actors: Dict[ActorID, ActorEntry] = {}
        self.named_actors: Dict[tuple, ActorID] = {}  # (namespace, name) -> id
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupEntry] = {}
        self.jobs: Dict[JobID, dict] = {}
        # job_heartbeat is lane-safe (runs on lane threads, PR 6); this
        # lock covers its liveness-stamp write against primary-loop
        # readers/expirers of the same job dict.
        self._heartbeat_lock = threading.Lock()
        # pubsub: channel -> set of subscriber connections
        self._subs: Dict[str, Set[ServerConnection]] = {}
        self._pending_actors: List[ActorID] = []
        self._schedule_tasks: set = set()
        self._pending_pgs: List[PlacementGroupID] = []
        # Placement-group group-commit queue (see the placement-group
        # section): (kind, entry, future) ops drained by one sweep task.
        self._pg_ops: deque = deque()
        self._pg_drain_task: Optional[asyncio.Task] = None
        self.pg_batch_stats = {
            "batches": 0,          # drain sweeps executed
            "batched_creates": 0,  # creates that shared a sweep with others
            "batched_removes": 0,  # removes that shared a sweep with others
            "fused_commits": 0,    # single-node groups committed in one RPC
            "rollbacks": 0,        # whole-group rollbacks on partial failure
        }
        self._bg_tasks: List[asyncio.Task] = []
        self.task_event_store = TaskEventStore()
        self._obs_seen: Dict[str, int] = {}  # worker -> last obs batch id
        # Aggregation beats: obs_report arrivals.  The remediation
        # controller's beat thread reads this (debug_control_plane) to
        # evaluate once per beat instead of polling blind.
        self.obs_beats = 0
        self._requested_resources: List[dict] = []
        self._recent_unplaceable: List[tuple] = []  # (ts, key, resources)
        # Over-quota task-lease demand: unlike queued actors/PGs it lives in
        # no PENDING table (the submitter backs off and retries), so it is
        # remembered here briefly for the autoscaler's load state.
        self._recent_queued_tasks: List[tuple] = []  # (ts, key, resources)
        self.store = store if store is not None else make_store_client(store_path)
        export_path = None
        if store_path:
            export_path = os.path.join(
                os.path.dirname(store_path), "events.jsonl"
            )
        elif ha_dir:
            export_path = os.path.join(ha_dir, "events.jsonl")
        self.events = EventRecorder(export_path)
        self._recovered = self._recover()
        # Grace window after a recovery: ALIVE actors whose node never
        # re-registers are declared dead only after agents have had a full
        # health-check timeout to reconnect.
        self._recovery_deadline = (
            time.monotonic() + GlobalConfig.health_check_timeout_s
            if self._recovered
            else None
        )

    # ----------------------------------------------------------- persistence
    _KV_SEP = "\x00"

    def _store_put(self, table: str, key: str, value: bytes) -> None:
        try:
            self.store.put(table, key, value)
        except FencedWriteError as e:
            self._on_fenced(e)

    def _store_delete(self, table: str, key: str) -> None:
        try:
            self.store.delete(table, key)
        except FencedWriteError as e:
            self._on_fenced(e)

    def _on_fenced(self, exc: FencedWriteError) -> None:
        """A newer leader exists: stop mutating, redirect the in-flight
        caller (NotLeaderError is retried by every client against the
        published endpoint), and exit shortly — after the error reply
        has had a beat to flush."""
        from .cp_ha import read_endpoint

        hint = None
        if self.ha_dir:
            info = read_endpoint(self.ha_dir)
            hint = info.get("address") if info else None
        if not self._fenced:
            self._fenced = True
            logger.error("fenced by a newer leader (%s); exiting: %s",
                         hint, exc)
            try:
                asyncio.get_running_loop().call_later(
                    0.2, os._exit, 3
                )
            except RuntimeError:
                os._exit(3)
        raise NotLeaderError(hint) from exc

    def _persist_kv(self, namespace: str, key: str, value,
                    delete: bool = False) -> None:
        if not self.store.durable:
            return
        # KV values are arbitrary picklable objects (the job SDK stores
        # dicts), not only bytes — pickle for the blob store.
        skey = namespace + self._KV_SEP + key
        if delete:
            self._store_delete("kv", skey)
        else:
            self._store_put("kv", skey, pickle.dumps(value))

    def _persist_actor(self, entry: ActorEntry) -> None:
        if not self.store.durable:
            return
        self._store_put(
            "actors",
            entry.spec.actor_id.hex(),
            pickle.dumps(
                {
                    "spec": entry.spec,
                    "state": entry.state,
                    "address": entry.address,
                    "node_id": entry.node_id,
                    "num_restarts": entry.num_restarts,
                    "incarnation": entry.incarnation,
                    "death_cause": entry.death_cause,
                }
            ),
        )

    def _persist_pg(self, entry: PlacementGroupEntry) -> None:
        if not self.store.durable:
            return
        self._store_put(
            "pgs",
            entry.pg_id.hex(),
            pickle.dumps(
                {
                    "pg_id": entry.pg_id,
                    "bundles": entry.bundles,
                    "strategy": entry.strategy,
                    "name": entry.name,
                    "state": entry.state,
                    "bundle_nodes": entry.bundle_nodes,
                    "job_id": entry.job_id,
                    "priority": entry.priority,
                    "created_seq": entry.created_seq,
                    "preemptions": entry.preemptions,
                }
            ),
        )

    def _persist_job(self, job_id: JobID) -> None:
        if not self.store.durable:
            return
        job = self.jobs[job_id]
        self._store_put(
            "jobs",
            job_id.hex(),
            pickle.dumps(
                {k: v for k, v in job.items() if k != "last_heartbeat"}
            ),
        )

    def _persist_obs_seen(self, wid: str, bid: int) -> None:
        # The obs-report dedupe watermark must survive failover: the
        # agents' pull staging redelivers at-least-once, and a standby
        # that forgot the acked ids would double-count the redelivered
        # batches' task events (the PR-16 regression test).
        if not self.store.durable:
            return
        self._store_put("obs_seen", wid, pickle.dumps(bid))

    def _recover(self) -> bool:
        """Rebuild in-memory state from the durable store (no-op for the
        in-memory backend).  Returns True if anything was loaded."""
        loaded = False
        for skey, value in self.store.scan("kv"):
            ns, key = skey.split(self._KV_SEP, 1)
            self._kv.setdefault(ns, {})[key] = pickle.loads(value)
            loaded = True
        for _key, blob in self.store.scan("actors"):
            d = pickle.loads(blob)
            entry = ActorEntry(d["spec"])
            entry.state = d["state"]
            entry.address = d["address"]
            entry.node_id = d["node_id"]
            entry.num_restarts = d["num_restarts"]
            entry.incarnation = d["incarnation"]
            entry.death_cause = d["death_cause"]
            self.actors[entry.spec.actor_id] = entry
            if entry.spec.name is not None and entry.state != DEAD:
                self.named_actors[(entry.spec.namespace, entry.spec.name)] = (
                    entry.spec.actor_id
                )
            if entry.state in (PENDING_CREATION, RESTARTING):
                self._pending_actors.append(entry.spec.actor_id)
            loaded = True
        for _key, blob in self.store.scan("pgs"):
            d = pickle.loads(blob)
            # .get() defaults: blobs persisted before the arbitration
            # fields existed must still load.
            entry = PlacementGroupEntry(
                d["pg_id"], d["bundles"], d["strategy"], d["name"],
                job_id=d.get("job_id"), priority=d.get("priority"),
                created_seq=d.get("created_seq", 0),
            )
            entry.state = d["state"]
            entry.bundle_nodes = d["bundle_nodes"]
            entry.preemptions = d.get("preemptions", 0)
            self._pg_seq = max(self._pg_seq, entry.created_seq + 1)
            self.placement_groups[entry.pg_id] = entry
            if entry.state == "PENDING":
                self._pending_pgs.append(entry.pg_id)
            loaded = True
        now = time.monotonic()
        for key, blob in self.store.scan("jobs"):
            job = pickle.loads(blob)
            job["last_heartbeat"] = now  # grace: drivers re-heartbeat soon
            self.jobs[JobID.from_hex(key)] = job
            loaded = True
        for wid, blob in self.store.scan("obs_seen"):
            self._obs_seen[wid] = pickle.loads(blob)
        self._recharge_arbiter()
        if loaded:
            logger.info(
                "recovered state: %d actors, %d pgs, %d jobs, %d kv ns",
                len(self.actors), len(self.placement_groups), len(self.jobs),
                len(self._kv),
            )
        return loaded

    def _recharge_arbiter(self) -> None:
        """Rebuild quota accounting from recovered state.  Charges are
        keyed and idempotent, so replaying them over whatever the arbiter
        already holds can never double-count — the invariant the
        CP-restart × preemption tests pin."""
        for job_id, job in self.jobs.items():
            self.arbiter.register_job(
                job_id.hex(), job.get("priority"), job.get("quota")
            )
        for actor_id, entry in self.actors.items():
            # PG-bound actors draw from their bundle (charged under the
            # PG key); charging them too would double-count.
            if entry.spec.placement_group_id is not None:
                continue
            if entry.state in (ALIVE, RESTARTING):
                job = entry.spec.job_id
                self.arbiter.charge(
                    ("actor", actor_id.hex()),
                    job.hex() if job else None,
                    ResourceSet(entry.spec.resources),
                )
        for pg_id, entry in self.placement_groups.items():
            # A victim checkpointed-and-evicted before the crash is
            # PENDING here: it recovers un-charged and re-admits on the
            # next sweep, exactly like any queued group.
            if entry.state == "CREATED":
                total = ResourceSet(entry.bundles[0])
                for b in entry.bundles[1:]:
                    total = total + ResourceSet(b)
                self.arbiter.charge(
                    ("pg", pg_id.hex()),
                    entry.job_id.hex() if entry.job_id else None,
                    total,
                )

    # ------------------------------------------------------------- lifecycle
    async def start(self) -> str:
        addr = await self.server.start()
        loop = asyncio.get_running_loop()
        self._bg_tasks.append(loop.create_task(self._health_check_loop()))
        logger.info("control plane listening on %s", addr)
        return addr

    async def stop(self):
        for t in self._bg_tasks:
            t.cancel()
        if self._pg_drain_task is not None and not self._pg_drain_task.done():
            self._pg_drain_task.cancel()
        await self.server.stop()
        await self.agent_clients.close_all()
        self.store.close()
        self.events.close()

    # ---------------------------------------------------------------- pubsub
    def _publish(self, channel: str, message: dict):
        dead = []
        for conn in self._subs.get(channel, ()):  # copy not needed; no await
            task = asyncio.get_running_loop().create_task(
                conn.push("pub", {"channel": channel, "message": message})
            )
            task.add_done_callback(lambda t: t.exception())  # swallow
        _ = dead

    def handle_subscribe(self, payload, conn: ServerConnection):
        for channel in payload["channels"]:
            self._subs.setdefault(channel, set()).add(conn)
        conn.metadata.setdefault("channels", set()).update(payload["channels"])
        return True

    def handle_unsubscribe(self, payload, conn: ServerConnection):
        for channel in payload["channels"]:
            self._subs.get(channel, set()).discard(conn)
        return True

    def on_connection_closed(self, conn: ServerConnection):
        for channel in conn.metadata.get("channels", ()):
            self._subs.get(channel, set()).discard(conn)
        # Job liveness is heartbeat-based (see _health_check_loop), NOT
        # connection-based: a transient TCP reset must not kill the job's
        # actors — the driver's RetryableRpcClient reconnects transparently.

    async def _cleanup_job(self, job_id: JobID):
        """Kill the job's non-detached actors."""
        for actor_id, entry in list(self.actors.items()):
            if entry.spec.job_id == job_id and not entry.spec.detached:
                await self._kill_actor_entry(entry, "job finished")

    # ----------------------------------------------------------------- nodes
    def handle_register_node(self, payload, conn):
        node_id = payload["node_id"]
        entry = NodeEntry(node_id, payload["agent_address"], payload["snapshot"])
        prev = self.nodes.get(node_id)
        if prev is not None and prev.draining:
            # An agent restart must not re-open a node the autoscaler is
            # retiring: the drain decision outlives the registration.
            entry.draining = True
            entry.drain_cause = prev.drain_cause
            entry.drain_started = prev.drain_started
        self.nodes[node_id] = entry
        self.scheduler.update_node(node_id, payload["snapshot"])
        if entry.draining:
            self.scheduler.set_draining(node_id, True)
        logger.info(
            "node %s registered (%s) resources=%s",
            node_id.hex()[:8],
            payload["agent_address"],
            payload["snapshot"]["total"],
        )
        self._publish("nodes", {"event": "added", "node_id": node_id})
        self.events.record(
            NODE_LIFECYCLE, node_id.hex(), "ALIVE",
            agent_address=payload["agent_address"],
            resources=payload["snapshot"].get("total", {}),
        )
        self._kick_pending()
        # Reconcile the agent's held bundles against the PG table: a
        # group removed or evicted while this node (or this control
        # plane) was away must release its reservation — otherwise a
        # remove that raced the re-registration window leaks the
        # agent-side resources forever.
        stale = []
        for pg_id in payload.get("held_pgs", ()):
            pg = self.placement_groups.get(pg_id)
            if pg is None or pg.state != "CREATED":
                stale.append(pg_id)
        return {"ok": True, "session_id": self.session_id, "drop_pgs": stale}

    def handle_heartbeat(self, payload, conn):
        node_id = payload["node_id"]
        entry = self.nodes.get(node_id)
        if entry is None:
            return {"ok": False, "reregister": True}
        entry.last_heartbeat = time.monotonic()
        entry.snapshot = payload["snapshot"]
        self.scheduler.update_node(node_id, payload["snapshot"])
        self._kick_pending()
        return {"ok": True}

    def handle_get_cluster_view(self, payload, conn):
        return {
            "nodes": {
                nid: {
                    "agent_address": e.agent_address,
                    "snapshot": e.snapshot,
                    "alive": e.alive,
                }
                for nid, e in self.nodes.items()
                if e.alive
            }
        }

    def _publish_own_metrics(self):
        """The control plane has no CoreWorker to push its registry
        through — it IS the KV server: record lane/PG-batch telemetry
        and drop the snapshot straight into the metrics namespace (not
        via handle_kv_put: metric payloads need no sqlite persistence)."""
        try:
            from ray_tpu.util import flight_recorder
            from ray_tpu.util import metrics as _m

            flight_recorder.record_rpc_lanes(self.server, role="control_plane")
            flight_recorder.record_pg_batches(self.pg_batch_stats)
            flight_recorder.record_cp_ha(self._cp_ha_info())
            payload = _m.payload_snapshot()
            if payload is not None:
                self._kv.setdefault(_m._REGISTRY_NS, {})["controlplane"] = (
                    payload
                )
        except Exception as e:  # noqa: BLE001 — telemetry is best-effort
            logger.debug("control-plane metrics publish failed: %s", e)

    async def _health_check_loop(self):
        period = GlobalConfig.health_check_period_s
        timeout = GlobalConfig.health_check_timeout_s
        while True:
            await asyncio.sleep(period)
            self._publish_own_metrics()
            now = time.monotonic()
            for node_id, entry in list(self.nodes.items()):
                if entry.alive and now - entry.last_heartbeat > timeout:
                    await self._on_node_dead(node_id)
            if (
                self._recovery_deadline is not None
                and now > self._recovery_deadline
            ):
                # Post-recovery reconciliation: ALIVE actors whose node
                # never re-registered are on lost nodes.
                self._recovery_deadline = None
                for actor_id, a in list(self.actors.items()):
                    if a.state == ALIVE and (
                        a.node_id not in self.nodes
                        or not self.nodes[a.node_id].alive
                    ):
                        await self._on_actor_worker_died(
                            actor_id, "node lost across control-plane restart"
                        )
            for job_id, job in list(self.jobs.items()):
                if (
                    job["state"] == "RUNNING"
                    and now - job.get("last_heartbeat", now) > timeout
                ):
                    job["state"] = "FINISHED"
                    self.events.record(JOB_LIFECYCLE, job_id.hex(), "FINISHED")
                    self._persist_job(job_id)
                    logger.info("job %s lost its driver; cleaning up",
                                job_id.hex())
                    await self._cleanup_job(job_id)

    async def _on_node_dead(self, node_id: NodeID):
        entry = self.nodes.get(node_id)
        if entry is None or not entry.alive:
            return
        entry.alive = False
        self.scheduler.remove_node(node_id)
        logger.warning("node %s marked dead", node_id.hex()[:8])
        self.events.record(NODE_LIFECYCLE, node_id.hex(), "DEAD")
        self._publish("nodes", {"event": "removed", "node_id": node_id})
        # Fail or restart actors that lived there.
        for actor_id, a in list(self.actors.items()):
            if a.node_id == node_id and a.state == ALIVE:
                await self._on_actor_worker_died(actor_id, "node died")

    # -------------------------------------------------------------------- kv
    def handle_kv_put(self, payload, conn):
        ns = self._kv.setdefault(payload.get("namespace", ""), {})
        overwrite = payload.get("overwrite", True)
        if not overwrite and payload["key"] in ns:
            return False
        ns[payload["key"]] = payload["value"]
        self._persist_kv(
            payload.get("namespace", ""), payload["key"], payload["value"]
        )
        return True

    def handle_kv_get(self, payload, conn):
        return self._kv.get(payload.get("namespace", ""), {}).get(payload["key"])

    def handle_kv_del(self, payload, conn):
        ns = self._kv.get(payload.get("namespace", ""), {})
        existed = ns.pop(payload["key"], None) is not None
        if existed:
            self._persist_kv(
                payload.get("namespace", ""), payload["key"], None, delete=True
            )
        return existed

    def handle_kv_keys(self, payload, conn):
        ns = self._kv.get(payload.get("namespace", ""), {})
        prefix = payload.get("prefix", "")
        return [k for k in ns if k.startswith(prefix)]

    def handle_kv_exists(self, payload, conn):
        return payload["key"] in self._kv.get(payload.get("namespace", ""), {})

    # ------------------------------------------------------------------ jobs
    def handle_register_job(self, payload, conn):
        job_id = payload["job_id"]
        priority = self.arbiter.register_job(
            job_id.hex(), payload.get("priority"), payload.get("quota")
        )
        self.jobs[job_id] = {
            "state": "RUNNING",
            "driver_address": payload.get("driver_address"),
            "start_time": time.time(),
            "last_heartbeat": time.monotonic(),
            "priority": priority,
            "quota": self.arbiter.quota_of(job_id.hex()),
        }
        conn.metadata["job_id"] = job_id
        self.events.record(
            JOB_LIFECYCLE, job_id.hex(), "RUNNING",
            driver_address=payload.get("driver_address"),
        )
        self._persist_job(job_id)
        return {"ok": True, "session_id": self.session_id, "priority": priority}

    def handle_job_heartbeat(self, payload, conn):
        job = self.jobs.get(payload["job_id"])
        if job is None:
            return {"ok": False, "reregister": True}
        with self._heartbeat_lock:
            job["last_heartbeat"] = time.monotonic()
        return {"ok": True}

    def handle_list_jobs(self, payload, conn):
        return {jid: dict(info) for jid, info in self.jobs.items()}

    # ---------------------------------------------------------------- actors
    async def handle_register_actor(self, payload, conn):
        spec: ActorSpec = payload["spec"]
        if spec.name is not None:
            key = (spec.namespace, spec.name)
            if key in self.named_actors:
                existing = self.actors.get(self.named_actors[key])
                if existing is not None and existing.state != DEAD:
                    if payload.get("get_if_exists"):
                        return existing.public_info()
                    raise ValueError(
                        f"actor name {spec.name!r} already taken in "
                        f"namespace {spec.namespace!r}"
                    )
            self.named_actors[key] = spec.actor_id
        entry = ActorEntry(spec)
        self.actors[spec.actor_id] = entry
        self.events.record(
            ACTOR_DEFINITION, spec.actor_id.hex(), "REGISTERED",
            name=spec.name or "", namespace=spec.namespace,
            resources=dict(spec.resources),
            max_restarts=spec.max_restarts,
        )
        self._persist_actor(entry)
        # Schedule in the background: registration replies immediately
        # (the reference's GCS actor registration is likewise async) so a
        # burst of .remote() creations pipelines instead of serializing on
        # worker spawn + __init__.  Callers' method submissions wait on
        # the PENDING_CREATION -> ALIVE state publish.
        self._schedule_actor_bg(entry)
        return entry.public_info()

    def _schedule_actor_bg(self, entry: ActorEntry):
        """Run _try_schedule_actor as a retained task: an escaping
        exception re-queues the actor for the next reconcile pass instead
        of silently stranding it in PENDING_CREATION."""
        task = asyncio.get_running_loop().create_task(
            self._try_schedule_actor(entry)
        )
        self._schedule_tasks.add(task)

        def done(t: asyncio.Task):
            self._schedule_tasks.discard(t)
            if t.cancelled():
                return
            exc = t.exception()
            if exc is not None:
                logger.warning(
                    "actor %s scheduling failed: %s; re-queueing",
                    entry.spec.actor_id, exc,
                )
                if entry.spec.actor_id not in self._pending_actors:
                    self._pending_actors.append(entry.spec.actor_id)

        task.add_done_callback(done)

    async def _try_schedule_actor(self, entry: ActorEntry):
        if entry.state == DEAD:
            return  # killed before scheduling got to it
        spec = entry.spec
        if spec.placement_group_id is not None:
            # PG-bound actor: its resources come from the bundle, which was
            # already carved OUT of the node's main pool — consulting
            # pick_node would wrongly demand the capacity twice (and fail
            # on a saturated node).  Target the bundle's node directly.
            pg = self.placement_groups.get(spec.placement_group_id)
            if pg is None or pg.state == "REMOVED":
                # Terminal: an actor bound to a gone PG can never schedule.
                entry.state = DEAD
                entry.death_cause = (
                    f"placement group {spec.placement_group_id} was removed"
                )
                self._publish_actor(entry)
                return
            if pg.state != "CREATED" or not pg.bundle_nodes:
                if spec.actor_id not in self._pending_actors:
                    self._pending_actors.append(spec.actor_id)
                return
            idx = spec.bundle_index if spec.bundle_index >= 0 else 0
            if idx >= len(pg.bundle_nodes):
                entry.state = DEAD
                entry.death_cause = (
                    f"bundle_index {idx} out of range for placement group "
                    f"with {len(pg.bundle_nodes)} bundles"
                )
                self._publish_actor(entry)
                return
            await self._create_actor_on_node(entry, pg.bundle_nodes[idx])
            return
        request = ResourceSet(spec.resources)
        job_hex = spec.job_id.hex() if spec.job_id else None
        charge_key = ("actor", spec.actor_id.hex())
        if job_hex and not self.arbiter.is_charged(charge_key):
            if not self.arbiter.admit(job_hex, request):
                # Over quota: queue (stay pending), never fail — the
                # next drain re-admits once usage drains below the cap.
                self.arbiter.mark_queued(charge_key, job_hex)
                self._record_sched_event("admission_queued", job=job_hex)
                if spec.actor_id not in self._pending_actors:
                    self._pending_actors.append(spec.actor_id)
                return
        try:
            node_id = self.scheduler.pick_node(
                ResourceSet(spec.resources), spec.strategy
            )
        except InfeasibleError:
            # No current node shape fits — keep pending rather than fail:
            # the autoscaler may add a node that does (its load state
            # includes this actor's demand), and the reference likewise
            # queues infeasible actors indefinitely.
            if spec.actor_id not in self._pending_actors:
                self._pending_actors.append(spec.actor_id)
            return
        if node_id is None:
            if spec.actor_id not in self._pending_actors:
                self._pending_actors.append(spec.actor_id)
            return
        # Charge before dispatch (idempotent by key): a RESTARTING actor
        # keeps its charge across the respawn instead of re-admitting.
        self.arbiter.charge(charge_key, job_hex, request)
        await self._create_actor_on_node(entry, node_id)

    async def _create_actor_on_node(self, entry: ActorEntry, node_id: NodeID):
        spec = entry.spec
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            if spec.actor_id not in self._pending_actors:
                self._pending_actors.append(spec.actor_id)
            return
        client = self.agent_clients.get(node.agent_address)
        try:
            # The agent's handler may wait for a worker spawn AND an
            # actor_init (each bounded by worker_startup_timeout_s) plus the
            # user __init__ runtime — our deadline must dominate both.
            reply = await client.call(
                "create_actor_worker",
                {"spec": spec, "incarnation": entry.incarnation},
                timeout=GlobalConfig.worker_startup_timeout_s * 2 + 30,
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("actor %s creation on node failed: %s", spec.actor_id, e)
            if spec.actor_id not in self._pending_actors:
                self._pending_actors.append(spec.actor_id)
            return
        if reply.get("init_error"):
            # User constructor raised: permanent failure, never retried.
            entry.state = DEAD
            entry.death_cause = f"actor __init__ failed: {reply['init_error']}"
            self._publish_actor(entry)
            return
        if entry.state == DEAD:
            # Killed while the (async) creation was in flight: the fresh
            # worker must not come up as a zombie holding its lease — kill
            # it and keep the DEAD state (the kill's worker-kill RPC was a
            # no-op because no worker existed yet).
            entry.node_id = node_id
            entry.address = reply["worker_address"]
            await self._kill_actor_worker(entry)
            entry.address = None
            return
        entry.node_id = node_id
        entry.address = reply["worker_address"]
        entry.state = ALIVE
        self._publish_actor(entry)

    def _publish_actor(self, entry: ActorEntry):
        if entry.state == DEAD:
            self.arbiter.release(("actor", entry.spec.actor_id.hex()))
            self.arbiter.unmark_queued(("actor", entry.spec.actor_id.hex()))
        # Every actor state transition publishes — persist + export events
        # at the same spot.
        self.events.record(
            ACTOR_LIFECYCLE, entry.spec.actor_id.hex(), entry.state,
            death_cause=entry.death_cause,
            num_restarts=entry.num_restarts,
        )
        self._persist_actor(entry)
        self._publish("actor:" + entry.spec.actor_id.hex(), entry.public_info())

    def handle_get_actor_info(self, payload, conn):
        entry = self.actors.get(payload["actor_id"])
        if entry is None:
            return None
        return entry.public_info()

    def handle_get_named_actor(self, payload, conn):
        key = (payload.get("namespace", ""), payload["name"])
        actor_id = self.named_actors.get(key)
        if actor_id is None:
            return None
        entry = self.actors[actor_id]
        info = entry.public_info()
        info["spec"] = entry.spec
        return info

    def handle_list_actors(self, payload, conn):
        return [e.public_info() for e in self.actors.values()]

    async def handle_actor_worker_died(self, payload, conn):
        await self._on_actor_worker_died(
            payload["actor_id"], payload.get("cause", "worker died")
        )
        return True

    async def _on_actor_worker_died(self, actor_id: ActorID, cause: str):
        entry = self.actors.get(actor_id)
        if entry is None or entry.state == DEAD:
            return
        if actor_id in self._evicting_actors:
            # Checkpoint-then-evict already moved this actor to
            # RESTARTING; the agent's death report for the eviction kill
            # must not burn a num_restarts credit (eviction is scheduler
            # policy, not an actor failure).
            self._evicting_actors.discard(actor_id)
            return
        restarts_allowed = (
            entry.spec.max_restarts == -1
            or entry.num_restarts < entry.spec.max_restarts
        )
        if restarts_allowed:
            entry.num_restarts += 1
            entry.incarnation += 1
            entry.state = RESTARTING
            entry.address = None
            self._publish_actor(entry)
            await self._try_schedule_actor(entry)
        else:
            entry.state = DEAD
            entry.death_cause = cause
            entry.address = None
            self._publish_actor(entry)

    async def handle_kill_actor(self, payload, conn):
        entry = self.actors.get(payload["actor_id"])
        if entry is None:
            return False
        if payload.get("no_restart", True):
            await self._kill_actor_entry(entry, "ray_tpu.kill")
        else:
            # Kill only the worker process; the death path restarts the
            # actor if restarts remain.
            await self._kill_actor_worker(entry)
            await self._on_actor_worker_died(
                entry.spec.actor_id, "ray_tpu.kill(no_restart=False)"
            )
        return True

    async def _kill_actor_worker(self, entry: ActorEntry):
        if entry.node_id is not None and entry.address is not None:
            node = self.nodes.get(entry.node_id)
            if node is not None and node.alive:
                client = self.agent_clients.get(node.agent_address)
                try:
                    await client.call(
                        "kill_worker", {"worker_address": entry.address}, retries=1
                    )
                except Exception as e:
                    logger.warning("kill_worker RPC to agent failed: %s", e)

    async def _kill_actor_entry(self, entry: ActorEntry, cause: str):
        await self._kill_actor_worker(entry)
        entry.state = DEAD
        entry.death_cause = cause
        entry.address = None
        self._publish_actor(entry)

    # ------------------------------------------------------- placement groups
    #
    # Group commit: create/remove requests enqueue on one ops queue and a
    # single drain task sweeps it.  A lone request drains immediately (no
    # batching timer — serial latency is untouched), while requests
    # arriving during an in-flight sweep coalesce into the next one: ONE
    # bundle-reservation sweep and one batched RPC per node per batch
    # instead of a prepare+commit round-trip pair per group.  Single-node
    # groups fuse prepare+commit into one ``reserve_bundles_batch`` agent
    # RPC (two-phase commit only pays for itself across nodes); multi-node
    # groups keep the classic two-phase protocol with per-node batched
    # prepare/commit/cancel.  Atomicity is per placement group: a group
    # whose bundles can't all be reserved rolls back every node it touched
    # and re-queues as PENDING; other groups in the same sweep are
    # unaffected (independent clients must not fate-share a batch).

    async def handle_create_placement_group(self, payload, conn):
        pg_id = payload["pg_id"]
        job_id = payload.get("job_id")
        entry = PlacementGroupEntry(
            pg_id, payload["bundles"], payload["strategy"],
            payload.get("name", ""),
            job_id=job_id,
            priority=self.arbiter.priority_of(
                job_id.hex() if job_id else None, payload.get("priority")
            ),
            created_seq=self._pg_seq,
        )
        self._pg_seq += 1
        self.placement_groups[pg_id] = entry
        self.events.record(PG_LIFECYCLE, pg_id.hex(), "PENDING")
        self._persist_pg(entry)
        await self._enqueue_pg_op("create", entry)
        # The reply carries the post-sweep state: CREATED in the common
        # case, so the client's ready() needs no follow-up poll.
        return entry.public_info()

    async def handle_remove_placement_group(self, payload, conn):
        entry = self.placement_groups.get(payload["pg_id"])
        if entry is None:
            return False
        # Through the ops queue so a remove can never overtake the create
        # sweep that is still reserving this group's bundles.
        await self._enqueue_pg_op("remove", entry)
        return True

    def _enqueue_pg_op(self, kind: str, entry: PlacementGroupEntry):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pg_ops.append((kind, entry, fut))
        if self._pg_drain_task is None or self._pg_drain_task.done():
            self._pg_drain_task = loop.create_task(self._drain_pg_ops())
        return fut

    async def _drain_pg_ops(self):
        while self._pg_ops:
            batch = []
            cap = max(1, GlobalConfig.pg_commit_batch_max)
            while self._pg_ops and len(batch) < cap:
                batch.append(self._pg_ops.popleft())
            creates = [(e, f) for k, e, f in batch if k == "create"]
            removes = [(e, f) for k, e, f in batch if k == "remove"]
            self.pg_batch_stats["batches"] += 1
            if len(creates) > 1:
                self.pg_batch_stats["batched_creates"] += len(creates)
            if len(removes) > 1:
                self.pg_batch_stats["batched_removes"] += len(removes)
            if creates:
                try:
                    await self._schedule_pg_batch([e for e, _f in creates])
                except Exception:  # noqa: BLE001 — a sweep bug fails its waiters, not the drain loop
                    logger.exception("placement-group commit sweep failed")
                for _e, fut in creates:
                    if not fut.done():
                        fut.set_result(None)
            if removes:
                try:
                    await self._remove_pg_batch([e for e, _f in removes])
                except Exception:  # noqa: BLE001
                    logger.exception("placement-group removal sweep failed")
                for _e, fut in removes:
                    if not fut.done():
                        fut.set_result(None)

    async def _try_schedule_pg(self, entry: PlacementGroupEntry):
        await self._schedule_pg_batch([entry])

    async def _schedule_pg_batch(self, entries: List[PlacementGroupEntry]):
        """One reservation sweep over a batch of pending groups.

        Node picks within a sweep don't see each other's reservations (the
        scheduler view is heartbeat-synced; agents are authoritative), so
        an over-packed pick simply fails its reservation and re-queues —
        the same convergence the serial path had."""
        placeable: List[tuple] = []  # (entry, assignment)
        # Highest priority first (oldest first within a band): when the
        # sweep covers more demand than fits — e.g. right after a
        # preemption freed capacity — the most important group places
        # first instead of whichever happened to enqueue first.
        entries = sorted(entries, key=lambda e: (-e.priority, e.created_seq))
        for entry in entries:
            if entry.state != "PENDING":
                continue
            bundles = [ResourceSet(b) for b in entry.bundles]
            total = bundles[0]
            for b in bundles[1:]:
                total = total + b
            job_hex = entry.job_id.hex() if entry.job_id else None
            charge_key = ("pg", entry.pg_id.hex())
            if job_hex and not self.arbiter.is_charged(charge_key):
                if not self.arbiter.admit(job_hex, total):
                    # Over quota: stay PENDING and retry on later sweeps
                    # (admission queues, never fails).
                    self.arbiter.mark_queued(charge_key, job_hex)
                    self._record_sched_event("admission_queued", job=job_hex)
                    self._pg_requeue(entry)
                    continue
            assignment = self.scheduler.pick_nodes_for_bundles(
                bundles, entry.strategy
            )
            if assignment is None:
                assignment = await self._try_preempt_for(entry, bundles)
            if assignment is None:
                self._pg_requeue(entry)
                continue
            # Charge before the reservation RPCs: co-admitted groups of
            # one job in the same sweep see each other's usage.  A failed
            # reservation re-queues through _pg_requeue, which releases.
            self.arbiter.charge(charge_key, job_hex, total)
            placeable.append((entry, assignment))
        if not placeable:
            return
        single_by_node: Dict[NodeID, List[tuple]] = {}
        multi: List[tuple] = []
        for entry, assignment in placeable:
            if len(set(assignment)) == 1:
                single_by_node.setdefault(assignment[0], []).append(
                    (entry, assignment)
                )
            else:
                multi.append((entry, assignment))
        tasks = [
            self._reserve_single_node(nid, items)
            for nid, items in single_by_node.items()
        ]
        if multi:
            tasks.append(self._two_phase_multi(multi))
        await asyncio.gather(*tasks)

    async def _reserve_single_node(self, nid: NodeID, items: List[tuple]):
        """Fused prepare+commit for groups placed wholly on one node —
        one agent round trip for the whole sub-batch."""
        node = self.nodes.get(nid)
        if node is None or not node.alive:
            for entry, _a in items:
                self._pg_requeue(entry)
            return
        client = self.agent_clients.get(node.agent_address)
        groups = [
            {
                "pg_id": entry.pg_id,
                "bundles": {i: b for i, b in enumerate(entry.bundles)},
            }
            for entry, _a in items
        ]
        try:
            res = await client.call("reserve_bundles_batch", {"groups": groups})
            results = res["results"]
        except Exception as e:  # noqa: BLE001 — agent racing shutdown/death
            logger.warning("reserve_bundles_batch to agent failed: %s", e)
            for entry, _a in items:
                self._pg_requeue(entry)
            return
        for entry, assignment in items:
            if results.get(entry.pg_id):
                self.pg_batch_stats["fused_commits"] += 1
                self._pg_created(entry, assignment)
            else:
                self._pg_requeue(entry)

    async def _two_phase_multi(self, multi: List[tuple]):
        """Classic two-phase commit for groups spanning nodes, with the
        per-node prepare/commit/cancel RPCs batched across groups."""
        # node -> pg_id -> {bundle_index: spec}
        by_node: Dict[NodeID, Dict] = {}
        for entry, assignment in multi:
            for idx, nid in enumerate(assignment):
                by_node.setdefault(nid, {}).setdefault(entry.pg_id, {})[idx] = (
                    entry.bundles[idx]
                )
        prepare_ok: Dict[NodeID, Dict] = {}

        async def prepare(nid):
            node = self.nodes.get(nid)
            if node is None or not node.alive:
                prepare_ok[nid] = {}
                return
            client = self.agent_clients.get(node.agent_address)
            groups = [
                {"pg_id": pg_id, "bundles": bundles}
                for pg_id, bundles in by_node[nid].items()
            ]
            try:
                res = await client.call(
                    "prepare_bundles_batch", {"groups": groups}
                )
                prepare_ok[nid] = res["results"]
            except Exception as e:  # noqa: BLE001
                logger.warning("prepare_bundles_batch to agent failed: %s", e)
                prepare_ok[nid] = {}

        await asyncio.gather(*(prepare(nid) for nid in by_node))
        committed: List[tuple] = []
        cancels: Dict[NodeID, List] = {}
        for entry, assignment in multi:
            nodes = set(assignment)
            if all(prepare_ok.get(nid, {}).get(entry.pg_id) for nid in nodes):
                committed.append((entry, assignment))
            else:
                # Whole-group rollback: every node that DID reserve this
                # group's bundles releases them before the group re-queues.
                self.pg_batch_stats["rollbacks"] += 1
                for nid in nodes:
                    if prepare_ok.get(nid, {}).get(entry.pg_id):
                        cancels.setdefault(nid, []).append(entry.pg_id)
                self._pg_requeue(entry)

        async def cancel(nid, pg_ids):
            node = self.nodes.get(nid)
            if node is None or not node.alive:
                return
            client = self.agent_clients.get(node.agent_address)
            try:
                await client.call("cancel_bundles_batch", {"pg_ids": pg_ids})
            except Exception as e:  # noqa: BLE001
                logger.warning("cancel_bundles_batch to agent failed: %s", e)

        commit_ok: Dict[NodeID, bool] = {}

        async def commit(nid, pg_ids):
            node = self.nodes.get(nid)
            if node is None or not node.alive:
                commit_ok[nid] = False
                return
            client = self.agent_clients.get(node.agent_address)
            try:
                await client.call("commit_bundles_batch", {"pg_ids": pg_ids})
                commit_ok[nid] = True
            except Exception as e:  # noqa: BLE001
                logger.warning("commit_bundles_batch to agent failed: %s", e)
                commit_ok[nid] = False

        commit_by_node: Dict[NodeID, List] = {}
        for entry, assignment in committed:
            for nid in set(assignment):
                commit_by_node.setdefault(nid, []).append(entry.pg_id)
        await asyncio.gather(
            *(cancel(nid, pg_ids) for nid, pg_ids in cancels.items()),
            *(commit(nid, pg_ids) for nid, pg_ids in commit_by_node.items()),
        )
        for entry, assignment in committed:
            nodes = set(assignment)
            if all(commit_ok.get(nid) for nid in nodes):
                self._pg_created(entry, assignment)
            else:
                # A node died (or its commit RPC failed) between prepare
                # and commit: the group must NOT claim CREATED with only
                # part of its bundles live.  Release whatever this group
                # holds on its surviving nodes and re-queue it.
                self.pg_batch_stats["rollbacks"] += 1
                self._release_bundles(entry.pg_id, nodes)
                self._pg_requeue(entry)

    def _release_bundles(self, pg_id: PlacementGroupID, node_ids):
        """Best-effort fire-and-forget release of one group's bundles on
        the given (surviving) nodes — the rollback half of a partial
        commit or a reservation whose group was removed mid-flight."""

        async def release():
            for nid in node_ids:
                node = self.nodes.get(nid)
                if node is None or not node.alive:
                    continue
                client = self.agent_clients.get(node.agent_address)
                try:
                    await client.call(
                        "return_bundles_batch", {"pg_ids": [pg_id]}
                    )
                except Exception as e:  # noqa: BLE001 — node racing death
                    logger.debug("rollback return_bundles failed: %s", e)

        task = asyncio.get_running_loop().create_task(release())
        self._bg_tasks.append(task)
        task.add_done_callback(self._bg_tasks.remove)

    def _pg_created(self, entry: PlacementGroupEntry, assignment):
        if entry.state != "PENDING":
            # A remove raced this group's reservation sweep: the group
            # stays REMOVED — release what the sweep just reserved
            # instead of resurrecting it.
            self._release_bundles(entry.pg_id, set(assignment))
            return
        entry.bundle_nodes = list(assignment)
        entry.state = "CREATED"
        self.events.record(PG_LIFECYCLE, entry.pg_id.hex(), "CREATED")
        self._persist_pg(entry)
        self._publish("pg:" + entry.pg_id.hex(), entry.public_info())
        # Actors parked on this group while it was PENDING (an evicted
        # group's survivors waiting to resume) must not wait out a full
        # heartbeat interval before re-placing.
        self._kick_pending()

    def _pg_requeue(self, entry: PlacementGroupEntry):
        # A re-queued group holds no quota: it re-admits on its next sweep
        # (release is idempotent — a never-charged group is a no-op).
        self.arbiter.release(("pg", entry.pg_id.hex()))
        if entry.state == "PENDING" and entry.pg_id not in self._pending_pgs:
            self._pending_pgs.append(entry.pg_id)

    async def _remove_pg_batch(self, entries: List[PlacementGroupEntry]):
        by_node: Dict[NodeID, List] = {}
        for entry in entries:
            if entry.state == "REMOVED":
                continue
            for nid in set(entry.bundle_nodes or ()):
                node = self.nodes.get(nid)
                if node is None or not node.alive:
                    continue
                by_node.setdefault(nid, []).append(entry.pg_id)

        async def return_node(nid, pg_ids):
            client = self.agent_clients.get(self.nodes[nid].agent_address)
            try:
                await client.call("return_bundles_batch", {"pg_ids": pg_ids})
            except Exception as e:  # noqa: BLE001
                logger.debug("return_bundles_batch to agent failed: %s", e)

        await asyncio.gather(
            *(return_node(nid, pg_ids) for nid, pg_ids in by_node.items())
        )
        for entry in entries:
            if entry.state == "REMOVED":
                continue
            entry.state = "REMOVED"
            self.arbiter.release(("pg", entry.pg_id.hex()))
            self.arbiter.unmark_queued(("pg", entry.pg_id.hex()))
            self.events.record(PG_LIFECYCLE, entry.pg_id.hex(), "REMOVED")
            self._persist_pg(entry)
            if entry.pg_id in self._pending_pgs:
                self._pending_pgs.remove(entry.pg_id)
            self._publish("pg:" + entry.pg_id.hex(), entry.public_info())
        # Freed bundles may unblock evicted (PENDING) groups and their
        # parked actors; don't make them wait out a heartbeat.  The
        # retry sweep may still see a stale (heartbeat-synced) view and
        # re-queue — the next heartbeat's kick then lands it.
        self._kick_pending()

    # ------------------------------------------------------------- preemption
    #
    # Checkpoint-then-evict: when a higher-priority group cannot place,
    # pick victim groups (lowest priority first, newest first within a
    # priority — least sunk progress dies first), simulate feasibility
    # with the victims' resources added back to the scheduler view, and
    # only if the demand would then fit: fan out ``prepare_evict``
    # through the node agents (workloads checkpoint via their existing
    # restart machinery), kill the victim's actors WITHOUT consuming
    # max_restarts, reclaim the bundles, and re-queue the victim as
    # PENDING — it resumes automatically when capacity frees.  Every
    # eviction spends the demanding job's token-bucket preemption budget,
    # so a crash-looping high-priority job drains its burst, quarantines,
    # and provably cannot evict the world.

    def _record_sched_event(self, kind: str, **tags) -> None:
        try:
            from ray_tpu.util import flight_recorder

            flight_recorder.record_sched_event(kind, **tags)
        except Exception as e:  # noqa: BLE001 — telemetry is best-effort
            logger.debug("sched event record failed: %s", e)

    def _select_victims(
        self,
        priority: int,
        bundles: List[ResourceSet],
        strategy: str,
    ) -> Optional[tuple]:
        """Pure simulation, no side effects: the smallest prefix of the
        victim ordering whose eviction would make ``bundles`` placeable.
        Returns (victims, assignment) or None when no set suffices.
        Victims must be STRICTLY lower priority — same-job victims are
        allowed (priority is per-group: a driver's latency burst evicting
        its own batch-training group is the single-driver sharing story),
        and the strict inequality is what prevents eviction cycles."""
        cands = [
            e
            for e in self.placement_groups.values()
            if e.state == "CREATED"
            and e.bundle_nodes
            and e.priority < priority
        ]
        cands.sort(key=lambda e: (e.priority, -e.created_seq))
        extra: Dict[NodeID, ResourceSet] = {}
        chosen: List[PlacementGroupEntry] = []
        for victim in cands:
            for idx, nid in enumerate(victim.bundle_nodes):
                r = ResourceSet(victim.bundles[idx])
                extra[nid] = extra[nid] + r if nid in extra else r
            chosen.append(victim)
            assignment = self.scheduler.pick_nodes_for_bundles(
                bundles, strategy, extra_available=extra
            )
            if assignment is not None:
                return chosen, assignment
        return None

    async def _try_preempt_for(
        self, entry: PlacementGroupEntry, bundles: List[ResourceSet]
    ) -> Optional[List[NodeID]]:
        """Preemption attempt on behalf of a PENDING group that cannot
        place.  Returns the post-eviction assignment, or None."""
        if not GlobalConfig.sched_preemption_enabled:
            return None
        sel = self._select_victims(entry.priority, bundles, entry.strategy)
        if sel is None:
            return None
        victims, assignment = sel
        job_hex = entry.job_id.hex() if entry.job_id else ""
        ok, reason = self.arbiter.spend_preemption(
            job_hex, len(victims), time.monotonic()
        )
        if not ok:
            self._record_sched_event("preemption_denied", job=job_hex)
            logger.warning(
                "preemption for pg %s denied: %s",
                entry.pg_id.hex()[:8], reason,
            )
            return None
        self._record_sched_event("preemption", job=job_hex,
                                 victims=len(victims))
        cause = (
            f"preempted by pg {entry.pg_id.hex()[:8]} "
            f"(priority {entry.priority} > {victims[0].priority})"
        )
        for victim in victims:
            await self._preempt_pg(victim, cause)
        return assignment

    async def _preempt_pg(self, victim: PlacementGroupEntry,
                          cause: str) -> int:
        """Checkpoint-then-evict one CREATED group.  Returns the number
        of workers that acked the checkpoint fan-out."""
        victim.preemptions += 1
        timeout = GlobalConfig.sched_evict_checkpoint_timeout_s
        nodes = set(victim.bundle_nodes or ())

        async def prep(nid):
            node = self.nodes.get(nid)
            if node is None or not node.alive:
                return 0
            client = self.agent_clients.get(node.agent_address)
            try:
                reply = await client.call(
                    "prepare_evict",
                    {"pg_id": victim.pg_id, "timeout": timeout,
                     "cause": cause},
                    timeout=timeout + 5, retries=1,
                )
                return int(reply.get("acks", 0))
            except Exception as e:  # noqa: BLE001 — evict proceeds anyway
                logger.warning("prepare_evict to agent failed: %s", e)
                return 0

        acks = sum(await asyncio.gather(*(prep(nid) for nid in nodes)))
        # Kill the victim's actors through the eviction guard: they go
        # RESTARTING (incarnation bumped, num_restarts untouched) and
        # re-park as pending until their group re-creates.
        for actor_id, a in list(self.actors.items()):
            if a.spec.placement_group_id == victim.pg_id and a.state == ALIVE:
                self._evicting_actors.add(actor_id)
                a.incarnation += 1
                a.state = RESTARTING
                await self._kill_actor_worker(a)
                a.address = None
                self._publish_actor(a)
                if actor_id not in self._pending_actors:
                    self._pending_actors.append(actor_id)

        async def ret(nid):
            node = self.nodes.get(nid)
            if node is None or not node.alive:
                return
            client = self.agent_clients.get(node.agent_address)
            try:
                await client.call(
                    "return_bundles_batch", {"pg_ids": [victim.pg_id]}
                )
            except Exception as e:  # noqa: BLE001 — node racing death
                logger.warning("preemption bundle return failed: %s", e)

        await asyncio.gather(*(ret(nid) for nid in nodes))
        victim.state = "PENDING"
        victim.bundle_nodes = None
        self.events.record(
            PG_LIFECYCLE, victim.pg_id.hex(), "PREEMPTED", cause=cause
        )
        self._record_sched_event(
            "preemption_victim",
            pg=victim.pg_id.hex(), priority=victim.priority, acks=acks,
        )
        # Crash consistency across tables: the group's PENDING flip and
        # its evicted actors' RESTARTING records land as ONE commit — a
        # crash here can never recover a CREATED group whose actors were
        # already evicted (which would leak phantom bundle charges).
        with self.store.transaction():
            self._persist_pg(victim)
            for _aid, a in list(self.actors.items()):
                if a.spec.placement_group_id == victim.pg_id:
                    self._persist_actor(a)
        self._pg_requeue(victim)  # releases the victim's quota charge
        self._publish("pg:" + victim.pg_id.hex(), victim.public_info())
        logger.info(
            "preempted pg %s (priority %d, %d checkpoint acks): %s",
            victim.pg_id.hex()[:8], victim.priority, acks, cause,
        )
        return acks

    async def handle_request_preemption(self, payload, conn):
        """Explicit preemption on behalf of a high-priority demand that
        is not itself a pending placement group — the remediation
        controller's fair-share actuator (queue pressure on a
        high-priority serve deployment frees training capacity here
        instead of declining at max_replicas)."""
        if not GlobalConfig.sched_preemption_enabled:
            return {"preempted": [], "reason": "preemption disabled"}
        bundles = [ResourceSet(b) for b in payload["bundles"]]
        priority = int(
            payload.get("priority") or GlobalConfig.sched_default_priority
        )
        job_id = payload.get("job_id")
        sel = self._select_victims(
            priority, bundles, payload.get("strategy", "PACK")
        )
        if sel is None:
            return {
                "preempted": [],
                "reason": "no lower-priority victim set frees enough capacity",
            }
        victims, _assignment = sel
        max_victims = payload.get("max_victims")
        if max_victims is not None and len(victims) > int(max_victims):
            return {
                "preempted": [],
                "reason": (
                    f"needs {len(victims)} victims > max_victims {max_victims}"
                ),
            }
        job_hex = job_id.hex() if job_id else "__remediation__"
        ok, reason = self.arbiter.spend_preemption(
            job_hex, len(victims), time.monotonic()
        )
        if not ok:
            self._record_sched_event("preemption_denied", job=job_hex)
            return {"preempted": [], "reason": reason}
        self._record_sched_event("preemption", job=job_hex,
                                 victims=len(victims))
        cause = payload.get("cause") or "remediation request_preemption"
        out = []
        for victim in victims:
            await self._preempt_pg(victim, cause)
            out.append(victim.pg_id.hex())
        self._kick_pending()
        return {"preempted": out, "reason": ""}

    def handle_get_placement_group(self, payload, conn):
        entry = self.placement_groups.get(payload["pg_id"])
        return entry.public_info() if entry else None

    def handle_list_placement_groups(self, payload, conn):
        return [e.public_info() for e in self.placement_groups.values()]

    # ------------------------------------------------------- pending retries
    def _actor_priority(self, actor_id) -> int:
        """Effective drain priority of a pending actor: its spec override
        if set, else the owning job's registered priority."""
        entry = self.actors.get(actor_id)
        if entry is None:
            return GlobalConfig.sched_default_priority
        spec = entry.spec
        job_hex = spec.job_id.hex() if spec.job_id else None
        return self.arbiter.priority_of(
            job_hex, getattr(spec, "priority", None)
        )

    def _kick_pending(self):
        if self._pending_actors or self._pending_pgs:
            asyncio.get_running_loop().create_task(self._drain_pending())

    async def _drain_pending(self):
        pending_actors, self._pending_actors = self._pending_actors, []
        # Highest effective priority first (stable, so FIFO within a
        # priority band): freed capacity after an eviction or node join
        # goes to the most important waiter, not the oldest one.
        pending_actors.sort(key=self._actor_priority, reverse=True)
        for actor_id in pending_actors:
            entry = self.actors.get(actor_id)
            if entry is not None and entry.state in (PENDING_CREATION, RESTARTING):
                await self._try_schedule_actor(entry)
        pending_pgs, self._pending_pgs = self._pending_pgs, []
        retry = [
            entry
            for entry in (self.placement_groups.get(p) for p in pending_pgs)
            if entry is not None and entry.state == "PENDING"
        ]
        if retry:
            # Through the ops queue, not a direct sweep: retries must
            # serialize with concurrent removes exactly like fresh
            # creates (a direct sweep racing a remove could resurrect a
            # REMOVED group with leaked bundles).
            await asyncio.gather(
                *(self._enqueue_pg_op("create", e) for e in retry)
            )

    # -------------------------------------------------------------- lookups
    def handle_pick_node_for_lease(self, payload, conn):
        """Spillback target selection for agents that can't fit a lease.
        Unplaceable demands are remembered briefly so the autoscaler's load
        state sees them (they live in no queue while the submitter backs
        off and retries)."""
        pg_id = payload.get("placement_group_id")
        if pg_id is not None:
            # PG-bound lease: the only valid target is the bundle's node
            # (its resources live in that node's bundle pool).
            entry = self.placement_groups.get(pg_id)
            if entry is None or entry.state == "REMOVED":
                # Fatal (not retry-until-autoscaled): the PG is gone.
                return {
                    "infeasible": True,
                    "fatal": True,
                    "error": f"placement group {pg_id} was removed",
                }
            if entry.state != "CREATED" or not entry.bundle_nodes:
                return {"node_id": None}  # PG pending; submitter retries
            idx = payload.get("bundle_index", -1)
            idx = idx if idx >= 0 else 0
            if idx >= len(entry.bundle_nodes):
                return {
                    "infeasible": True,
                    "fatal": True,
                    "error": (
                        f"bundle_index {idx} out of range for placement "
                        f"group with {len(entry.bundle_nodes)} bundles"
                    ),
                }
            node_id = entry.bundle_nodes[idx]
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return {"node_id": None}
            return {"node_id": node_id, "agent_address": node.agent_address}
        job_hex = payload.get("job_id")
        if job_hex and not self.arbiter.admit(
            job_hex, ResourceSet(payload["resources"])
        ):
            # Over-quota task lease: queue (submitter backs off and
            # retries), surfaced as a queued-by-admission count and as
            # autoscaler demand (a quota raise or freed capacity elsewhere
            # may admit it — the cluster should be ABLE to run it).
            self.arbiter.note_queued_event(job_hex)
            self._record_sched_event("admission_queued", job=job_hex)
            self._note_queued_task(
                payload["resources"], owner=payload.get("owner_id")
            )
            return {"node_id": None}
        try:
            node_id = self.scheduler.pick_node(
                ResourceSet(payload["resources"]),
                payload.get("strategy"),
                preferred=payload.get("preferred"),
            )
        except InfeasibleError as e:
            self._note_unplaceable(
                payload["resources"], owner=payload.get("owner_id")
            )
            return {"infeasible": True, "error": str(e)}
        if node_id is None:
            self._note_unplaceable(
                payload["resources"], owner=payload.get("owner_id")
            )
            return {"node_id": None}
        # Satisfied demand must stop driving scale-up: a granted lease
        # retires its own window entries, or the autoscaler would keep
        # seeing a phantom pending task for up to the window length
        # (and launch a replacement the moment the hosting node drains).
        self._clear_demand(payload["resources"], payload.get("owner_id"))
        return {
            "node_id": node_id,
            "agent_address": self.nodes[node_id].agent_address,
        }

    # ------------------------------------------------------------- autoscaler
    #
    # Drain state machine (scale-down): mark unschedulable -> evict
    # residents through the prepare_evict checkpoint protocol -> the
    # autoscaler polls drain_status until the node is empty -> provider
    # terminate -> drain_complete retires the entry.  Drain flags are
    # in-memory only: after a control-plane failover the autoscaler's
    # next status poll sees draining=False and simply re-issues the mark
    # (drain_node is idempotent).

    def _resolve_node_id(self, raw) -> Optional[NodeID]:
        if isinstance(raw, NodeID):
            return raw
        try:
            return NodeID.from_hex(raw)
        except Exception:  # noqa: BLE001 — malformed client input
            return None

    async def handle_drain_node(self, payload, conn):
        """Mark a node unschedulable and evict its residents (autoscaler
        scale-down; reference: ray ``DrainNode`` GCS RPC).  Idempotent;
        ``cancel`` reverses a drain that has not terminated yet."""
        node_id = self._resolve_node_id(payload.get("node_id"))
        entry = self.nodes.get(node_id) if node_id is not None else None
        if entry is None:
            return {"ok": False, "error": "unknown node"}
        if payload.get("cancel"):
            if entry.draining:
                entry.draining = False
                entry.drain_cause = ""
                self.scheduler.set_draining(node_id, False)
                self.events.record(
                    NODE_LIFECYCLE, node_id.hex(), "DRAIN_CANCELLED"
                )
                self._kick_pending()
            return {"ok": True, "draining": False}
        cause = payload.get("cause") or "autoscaler scale-down"
        already = entry.draining
        if not already:
            entry.draining = True
            entry.drain_cause = cause
            entry.drain_started = time.monotonic()
            self.scheduler.set_draining(node_id, True)
            self.events.record(
                NODE_LIFECYCLE, node_id.hex(), "DRAINING", cause=cause
            )
            logger.info("draining node %s: %s", node_id.hex()[:8], cause)
        # Evict resident placement groups through the checkpoint-then-
        # evict protocol.  No preemption-budget spend: drain is cluster
        # policy, not one tenant demanding another's chips.
        evicted = []
        for pg in list(self.placement_groups.values()):
            if (
                pg.state == "CREATED"
                and pg.bundle_nodes
                and node_id in pg.bundle_nodes
            ):
                await self._preempt_pg(pg, f"node drain: {cause}")
                evicted.append(pg.pg_id.hex())
        migrated = 0
        for actor_id, a in list(self.actors.items()):
            if (
                a.node_id == node_id
                and a.state == ALIVE
                and not a.spec.placement_group_id
            ):
                # Same guard as preemption: the kill must not consume
                # max_restarts — the actor re-places on another node.
                self._evicting_actors.add(actor_id)
                a.incarnation += 1
                a.state = RESTARTING
                await self._kill_actor_worker(a)
                a.address = None
                self._persist_actor(a)
                self._publish_actor(a)
                if actor_id not in self._pending_actors:
                    self._pending_actors.append(actor_id)
                migrated += 1
        if evicted or migrated:
            self._record_sched_event(
                "drain_evict", node=node_id.hex()[:8],
                pgs=len(evicted), actors=migrated,
            )
        self._kick_pending()
        return {
            "ok": True,
            "draining": True,
            "already_draining": already,
            "evicted_pgs": evicted,
            "migrated_actors": migrated,
        }

    def handle_drain_status(self, payload, conn):
        """Is this draining node empty yet?  The autoscaler polls this
        until ``drained`` before calling the provider's terminate."""
        node_id = self._resolve_node_id(payload.get("node_id"))
        entry = self.nodes.get(node_id) if node_id is not None else None
        if entry is None:
            # Gone entirely — nothing left to wait for.
            return {"known": False, "draining": False, "drained": True}
        resident_pgs = sum(
            1
            for pg in self.placement_groups.values()
            if pg.state == "CREATED"
            and pg.bundle_nodes
            and node_id in pg.bundle_nodes
        )
        resident_actors = sum(
            1
            for a in self.actors.values()
            if a.node_id == node_id and a.state == ALIVE
        )
        snap = entry.snapshot or {}
        busy = (
            bool(snap.get("pending_demands"))
            or snap.get("available", {}) != snap.get("total", {})
        )
        drained = not entry.alive or (
            resident_pgs == 0 and resident_actors == 0 and not busy
        )
        return {
            "known": True,
            "alive": entry.alive,
            "draining": entry.draining,
            "drained": drained,
            "resident_pgs": resident_pgs,
            "resident_actors": resident_actors,
            "busy": busy,
            "cause": entry.drain_cause,
            "age_s": (
                time.monotonic() - entry.drain_started
                if entry.draining else 0.0
            ),
        }

    async def handle_drain_complete(self, payload, conn):
        """Provider terminate happened: retire the node entry now instead
        of waiting out the health-check timeout."""
        node_id = self._resolve_node_id(payload.get("node_id"))
        entry = self.nodes.get(node_id) if node_id is not None else None
        if entry is None:
            return {"ok": True, "known": False}
        if entry.alive:
            self.events.record(
                NODE_LIFECYCLE, node_id.hex(), "DRAINED",
                cause=entry.drain_cause,
            )
            await self._on_node_dead(node_id)
        return {"ok": True, "known": True}

    def handle_get_load_state(self, payload, conn):
        """Cluster load snapshot for the autoscaler (reference:
        ``GcsAutoscalerStateManager`` state consumed by
        ``autoscaler/v2/autoscaler.py:50``)."""
        pending_actors = []
        for actor_id in self._pending_actors:
            entry = self.actors.get(actor_id)
            if entry is not None and entry.state in (PENDING_CREATION, RESTARTING):
                pending_actors.append(dict(entry.spec.resources))
        pending_pgs = []
        for pg_id in self._pending_pgs:
            entry = self.placement_groups.get(pg_id)
            if entry is not None and entry.state == "PENDING":
                pending_pgs.append(
                    {
                        "strategy": entry.strategy,
                        "bundles": [dict(b) for b in entry.bundles],
                    }
                )
        return {
            "nodes": {
                nid.hex(): {
                    "alive": e.alive,
                    "draining": e.draining,
                    "total": e.snapshot.get("total", {}),
                    "available": e.snapshot.get("available", {}),
                    "labels": e.snapshot.get("labels", {}),
                    "pending_demands": e.snapshot.get("pending_demands", []),
                    "idle_s": e.snapshot.get("idle_s", 0.0),
                }
                for nid, e in self.nodes.items()
            },
            "pending_actors": pending_actors,
            "pending_pgs": pending_pgs,
            "requested_resources": list(self._requested_resources),
            "unplaceable_demands": [
                dict(r)
                for ts, _k, r in self._recent_unplaceable
                if time.monotonic() - ts < 5.0
            ],
            # Over-quota task leases queued by admission (JobArbiter): no
            # PENDING table holds them, so they ride a short recency
            # window like unplaceable demand.
            "queued_task_demands": [
                dict(r)
                for ts, _k, r in self._recent_queued_tasks
                if time.monotonic() - ts < 5.0
            ],
            "queued_by_admission": {
                job: info.get("queued_now", 0)
                for job, info in self.arbiter.snapshot().items()
                if info.get("queued_now")
            },
        }

    @staticmethod
    def _demand_key(resources: dict, owner) -> tuple:
        return (owner, tuple(sorted(resources.items())))

    def _note_queued_task(self, resources: dict, owner=None,
                          window_s: float = 5.0):
        # Keyed by requester identity: a lease pool retrying the same
        # over-quota request every backoff must read as ONE pending task,
        # not one per retry — or the autoscaler overshoots.
        now = time.monotonic()
        key = self._demand_key(resources, owner)
        self._recent_queued_tasks = [
            (ts, k, r) for ts, k, r in self._recent_queued_tasks
            if now - ts < window_s and k != key
        ]
        self._recent_queued_tasks.append((now, key, dict(resources)))

    def _note_unplaceable(self, resources: dict, owner=None,
                          window_s: float = 5.0):
        now = time.monotonic()
        key = self._demand_key(resources, owner)
        self._recent_unplaceable = [
            (ts, k, r) for ts, k, r in self._recent_unplaceable
            if now - ts < window_s and k != key
        ]
        self._recent_unplaceable.append((now, key, dict(resources)))

    def _clear_demand(self, resources: dict, owner):
        """Retire a requester's window entries once its lease is granted."""
        key = self._demand_key(resources, owner)
        self._recent_queued_tasks = [
            e for e in self._recent_queued_tasks if e[1] != key
        ]
        self._recent_unplaceable = [
            e for e in self._recent_unplaceable if e[1] != key
        ]

    def handle_request_resources(self, payload, conn):
        """Explicit autoscaling demand (``ray.autoscaler.sdk.
        request_resources`` analog): a standing list of resource bundles the
        cluster should be able to fit."""
        self._requested_resources = [
            dict(b) for b in payload.get("bundles", [])
        ]
        return True

    # ------------------------------------------------------------ task events
    def handle_task_events(self, payload, conn):
        """Worker task-event flush (GcsTaskManager::HandleAddTaskEventData
        analog)."""
        self.task_event_store.add_batch(
            payload.get("events", ()), payload.get("profile_events", ())
        )
        if payload.get("worker_id"):
            self.task_event_store.report_span_drops(
                payload["worker_id"], payload.get("span_drops", 0)
            )
        return True

    def handle_obs_report(self, payload, conn):
        """Node-agent aggregated observability delivery: one RPC per
        heartbeat carrying every pulled worker's task events, spans,
        span-drop totals, and metrics-registry snapshot.  Metrics land
        under the same per-worker KV key the worker's own flush uses, so
        the two delivery paths overwrite instead of double counting.
        Batches carry per-worker ids (the pull staging's at-least-once
        redelivery): an id seen before is a duplicate of a batch that
        DID land — only its idempotent span-drop total is merged."""
        self.obs_beats += 1
        metrics_ns = self._kv.setdefault("metrics", {})
        for batch in payload.get("batches") or ():
            wid = batch.get("worker_id")
            if wid and batch.get("span_drops"):
                self.task_event_store.report_span_drops(
                    wid, batch["span_drops"]
                )
            bid = batch.get("batch_id")
            if bid is not None and wid and self._obs_seen.get(wid) == bid:
                continue
            self.task_event_store.add_batch(
                batch.get("events") or (), batch.get("profile_events") or ()
            )
            if batch.get("metrics") and batch.get("metrics_key"):
                metrics_ns[batch["metrics_key"]] = batch["metrics"]
            if bid is not None and wid:
                self._obs_seen[wid] = bid
                self._persist_obs_seen(wid, bid)
        return True

    def handle_list_task_events(self, payload, conn):
        return {
            "tasks": self.task_event_store.list_tasks(
                payload.get("filters"), payload.get("limit", 1000)
            ),
            "profile_events": self.task_event_store.profile_events(),
            "num_dropped": self.task_event_store.num_dropped,
            "num_span_drops": self.task_event_store.span_drop_total(),
        }

    async def handle_list_objects(self, payload, conn):
        """Cluster-wide sealed-object listing: concurrent fan-out to every
        alive agent's directory (``ray list objects`` analog) — one wedged
        agent must not serialize the whole sweep."""

        async def one(address):
            try:
                return await self.agent_clients.get(address).call(
                    "list_objects", {}, timeout=10, retries=1
                )
            except Exception:  # noqa: BLE001 — agent racing shutdown
                return []

        replies = await asyncio.gather(
            *(
                one(entry.agent_address)
                for entry in list(self.nodes.values())
                if entry.alive
            )
        )
        return [row for reply in replies for row in reply]

    def handle_list_cluster_events(self, payload, conn):
        """Typed lifecycle events (reference: RayEventRecorder export)."""
        return self.events.list_events(
            payload.get("event_type"), payload.get("entity_id"),
            payload.get("limit", 1000),
        )

    def handle_ping(self, payload, conn):
        return "pong"

    # ------------------------------------------------------------------ HA
    def _cp_ha_info(self) -> dict:
        """Role/lease/journal summary for cli status, /api/cluster, and
        the ``ray_tpu_cp_*`` metrics."""
        info = {
            "role": "leader",
            "ha": bool(self.ha_dir),
            "epoch": self.lease.epoch if self.lease is not None else 0,
        }
        stats_fn = getattr(self.store, "journal_stats", None)
        if stats_fn is not None:
            info["journal"] = stats_fn()
        if self.ha_dir:
            from .cp_ha import read_standby_statuses

            leader_seq = getattr(self.store, "applied_seq", 0)
            standbys = []
            for s in read_standby_statuses(self.ha_dir):
                standbys.append({
                    "holder": s.get("holder"),
                    "address": s.get("address"),
                    "applied_seq": s.get("applied_seq", 0),
                    "lag_records": max(
                        0, leader_seq - s.get("applied_seq", 0)
                    ),
                    "updated_at": s.get("updated_at"),
                })
            info["standbys"] = standbys
        return info

    def handle_cp_role(self, payload, conn):
        return self._cp_ha_info()

    def handle_debug_control_plane(self, payload, conn):
        """Control-plane self-diagnosis: group-commit accounting + per-lane
        RPC dispatch stats (tests and the many-client limits stage)."""
        return {
            "pg_batch_stats": dict(self.pg_batch_stats),
            "rpc_lanes": self.server.lane_stats(),
            "nodes": len(self.nodes),
            "placement_groups": len(self.placement_groups),
            "obs_beats": self.obs_beats,
            "sched": {
                "preemptions_total": self.arbiter.preemptions_total,
                "victims_total": self.arbiter.victims_total,
                "denied_total": self.arbiter.denied_total,
            },
            "cp": self._cp_ha_info(),
        }

    def handle_get_state(self, payload, conn):
        """State-API snapshot (reference: ray.util.state / StateAggregator)."""
        autoscaler = self._kv.get("autoscaler", {}).get("status")
        return {
            "nodes": {
                nid.hex(): {
                    "alive": e.alive,
                    "draining": e.draining,
                    "snapshot": e.snapshot,
                }
                for nid, e in self.nodes.items()
            },
            "actors": [e.public_info() for e in self.actors.values()],
            "placement_groups": [
                e.public_info() for e in self.placement_groups.values()
            ],
            "jobs": {jid.hex(): dict(j) for jid, j in self.jobs.items()},
            "scheduling": self.arbiter.snapshot(),
            "cp": self._cp_ha_info(),
            # Published by the autoscaler each reconcile round (KV
            # namespace "autoscaler"): last decision, per-type counts,
            # draining nodes, pending-demand summary, launch backoff.
            "autoscaler": autoscaler if isinstance(autoscaler, dict) else {},
        }



async def run_ha_candidate(host: str, port: int, session_id: str,
                           ha_dir: str) -> None:
    """One control-plane CANDIDATE: tail the journal as a warm standby
    while contending for the leader lease; on winning, replay the tail,
    bump the fencing epoch (promote), and serve as the leader on the SAME
    port — renewing the lease on the heartbeat cadence and exiting hard
    the moment renewal fails (a standby is about to take over)."""
    from .cp_ha import (
        LeaderLease,
        StandbyControlPlane,
        publish_endpoint,
        read_endpoint,
        clear_standby_status,
        write_standby_status,
    )
    from .store_client import JournaledStoreClient

    holder = f"cp-{os.getpid()}-{port}"
    journal_dir = os.path.join(ha_dir, "journal")
    store = JournaledStoreClient(journal_dir)
    lease = LeaderLease(ha_dir, holder)
    standby = StandbyControlPlane(
        lambda: (read_endpoint(ha_dir) or {}).get("address")
    )
    standby_server = RpcServer(standby, host, port, lanes=1)
    address = await standby_server.start()
    logger.info("cp candidate %s standing by on %s", holder, address)
    poll = max(0.02, GlobalConfig.cp_lease_poll_s)
    while True:
        store.tail()
        write_standby_status(ha_dir, holder, address, store.applied_seq)
        if lease.try_acquire(address):
            break
        await asyncio.sleep(poll)
    # Leader: free the port the standby rejector held, promote the
    # journal under the new epoch, and serve the real control plane.
    await standby_server.stop()
    clear_standby_status(ha_dir, holder)
    store.promote(lease)
    cp = ControlPlane(
        host, port, session_id=session_id,
        store=store, ha_dir=ha_dir, lease=lease,
    )
    await cp.start()
    publish_endpoint(ha_dir, cp.server.address, lease.epoch)
    logger.info(
        "cp candidate %s is LEADER (epoch %d) on %s",
        holder, lease.epoch, cp.server.address,
    )
    renew_period = min(
        GlobalConfig.health_check_period_s, max(0.05, lease.ttl / 3.0)
    )
    while True:
        await asyncio.sleep(renew_period)
        if not lease.renew():
            logger.error(
                "cp %s lost the leader lease; exiting for failover", holder
            )
            os._exit(3)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--session-id", required=True)
    parser.add_argument("--store-path", default=None)
    parser.add_argument("--ha-dir", default=None)
    args = parser.parse_args()
    from .reaper import watch_parent_process

    watch_parent_process()
    logging.basicConfig(
        level=GlobalConfig.log_level,
        format="%(asctime)s %(levelname)s control_plane: %(message)s",
    )

    async def run():
        from .stack_dump import install_signal_dumpers

        install_signal_dumpers(asyncio.get_running_loop())
        if args.ha_dir:
            await run_ha_candidate(
                args.host, args.port, args.session_id, args.ha_dir
            )
            return
        cp = ControlPlane(
            args.host, args.port, args.session_id, store_path=args.store_path
        )
        await cp.start()
        await asyncio.Event().wait()  # serve forever

    asyncio.run(run())


if __name__ == "__main__":
    main()
