"""Task/actor specifications and object references.

Equivalent of the reference's ``TaskSpecification`` (Ray
``src/ray/common/task/task_spec.h``) and ``ObjectRef``.  Specs are plain
picklable structs; function bodies are NOT embedded — they are exported once
per job to the control-plane KV store keyed by a content hash (the
function-manager pattern, Ray ``python/ray/_private/function_manager.py``)
and fetched+cached by workers.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID
from .scheduler import SchedulingStrategy


def function_key(pickled_fn: bytes) -> str:
    return "fn:" + hashlib.sha256(pickled_fn).hexdigest()[:32]


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    function_id: str  # KV key of the exported function
    name: str  # human-readable, for errors/state API
    # Serialized positional/keyword args.  ObjectRefs inside are replaced by
    # _RefMarker sentinels during serialization (see core_worker).  Either a
    # flat bytes encoding or a serialization.SerializedPayload whose header
    # and buffers ride the push frame out of band (framing v2 fast path).
    args_payload: Any
    num_returns: int = 1
    # Streaming-generator task: yields push to the owner as produced and
    # num_returns is 0 (the executor streams ONLY when the owner opted in
    # and registered a stream — a generator return without this flag is an
    # ordinary value).
    streaming: bool = False
    resources: Dict[str, float] = field(default_factory=dict)
    strategy: Optional[SchedulingStrategy] = None
    max_retries: int = 0
    retry_exceptions: bool = False
    owner_address: str = ""  # core-worker RPC address of the owner
    # Actor fields
    actor_id: Optional[ActorID] = None  # set for actor tasks
    actor_creation: bool = False
    sequence_number: int = -1  # per-(caller, actor) ordering
    # Placement group
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    # Runtime env (round-1: env vars only)
    env_vars: Dict[str, str] = field(default_factory=dict)
    # Distributed tracing: (trace_id, span_id) of the submitting span
    # (reference: tracing_helper.py injects the OTel context here).
    trace_ctx: Optional[Tuple[str, str]] = None
    # Actor method to dispatch (actor tasks; falls back to ``name``).
    method_name: str = ""
    # Per-worker push pipelining cap for this task's lease pool (0 = the
    # max_tasks_in_flight_per_worker knob).  Coarse-grained tasks (data
    # block transforms) set 1: a straggler pipelined ahead of them on a
    # shared worker would serialize execution at the worker — exactly the
    # head-of-line blocking the streaming scheduler exists to avoid.
    pipeline_depth: int = 0

    # Wire-pickled once per task push: tuple state instead of the default
    # dataclass ``__dict__`` (which re-pickles every field-name string per
    # frame) — measurably cheaper on the per-call hot path and smaller on
    # the wire.  Owner-local bookkeeping attrs (``_held_refs``,
    # ``_queue_charge``, ``_lineage_outstanding``, ...) deliberately do
    # not travel; the executor re-derives what it needs (``_attempt``,
    # ``_recv_ts``) from the push payload.  Evolution rule: only APPEND
    # fields here (zip() tolerates a shorter peer tuple on neither side —
    # same-version processes only, enforced by the RPC handshake).
    def __getstate__(self):
        return (
            self.task_id, self.job_id, self.function_id, self.name,
            self.args_payload, self.num_returns, self.streaming,
            self.resources, self.strategy, self.max_retries,
            self.retry_exceptions, self.owner_address, self.actor_id,
            self.actor_creation, self.sequence_number,
            self.placement_group_id, self.bundle_index, self.env_vars,
            self.trace_ctx, self.method_name, self.pipeline_depth,
        )

    def __setstate__(self, state):
        (
            self.task_id, self.job_id, self.function_id, self.name,
            self.args_payload, self.num_returns, self.streaming,
            self.resources, self.strategy, self.max_retries,
            self.retry_exceptions, self.owner_address, self.actor_id,
            self.actor_creation, self.sequence_number,
            self.placement_group_id, self.bundle_index, self.env_vars,
            self.trace_ctx, self.method_name, self.pipeline_depth,
        ) = state

    @property
    def scheduling_class(self) -> Tuple:
        """Tasks with equal scheduling class can share leased workers."""
        return (
            tuple(sorted(self.resources.items())),
            self.placement_group_id,
            tuple(sorted(self.env_vars.items())),
            self.pipeline_depth,
        )

    def return_ids(self) -> List[ObjectID]:
        return [ObjectID.for_task_return(self.task_id, i) for i in range(self.num_returns)]


@dataclass
class ActorSpec:
    actor_id: ActorID
    job_id: JobID
    class_id: str  # KV key of exported class
    name: Optional[str]  # named actor (None = anonymous)
    namespace: str
    ctor_args_payload: Any  # bytes or serialization.SerializedPayload
    resources: Dict[str, float]
    max_restarts: int
    max_task_retries: int
    max_concurrency: int
    strategy: Optional[SchedulingStrategy] = None
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    env_vars: Dict[str, str] = field(default_factory=dict)
    detached: bool = False
    owner_address: str = ""
    # "" = plain object plane; "device" keeps jax.Array returns resident in
    # HBM and hands out DeviceRefs (the reference's tensor_transport="nccl"
    # RDT analog; ray ``experimental/gpu_object_manager``).
    tensor_transport: str = ""
    # Per-actor override of the owning job's priority (None = inherit);
    # orders the control plane's pending-actor drain when freed capacity
    # is contended (docs/scheduling.md).
    priority: Optional[int] = None


class ObjectRef:
    """Distributed future.  Owner-based: carries the address of the worker
    that owns the object's metadata and value (ownership model from the
    reference's NSDI'21 design — Ray ``src/ray/core_worker/reference_counter.h``).

    Picklable; when deserialized inside a worker, the local core worker
    registers a borrow so the owner keeps the object alive.
    """

    __slots__ = ("id", "owner_address", "_worker", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: str, _worker=None):
        self.id = object_id
        self.owner_address = owner_address
        self._worker = _worker
        if _worker is not None:
            _worker.on_ref_created(self)

    def hex(self) -> str:
        return self.id.hex()

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:16]}, owner={self.owner_address})"

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __del__(self):
        worker = self._worker
        if worker is not None:
            try:
                worker.on_ref_deleted(self.id, self.owner_address)
            except Exception:  # raylint: waive[RTL003] decref from __del__ races interpreter teardown
                pass

    def __reduce__(self):
        # Deserializing side re-binds to its local core worker (borrow).
        # If WE own the object, serialization means the ref is escaping to
        # another process: take a grace-period escape hold so the object
        # survives the window between our last local ref dying and the
        # receiver's incref arriving (reference: borrower registration in
        # reply metadata, reference_counter.cc).
        w = self._worker
        if w is not None:
            if self.owner_address == w.address:
                w.on_ref_escaped(self.id)
            else:
                # A borrower re-lending the ref: remember it so this
                # process's eventual decref is grace-delayed (the
                # sub-borrower's incref must reach the owner first).
                w.on_ref_relent(self.id)
        return (_rehydrate_ref, (self.id, self.owner_address))

    # Allow `await ref` inside async actors / driver coroutines.
    def __await__(self):
        from .core_worker import global_worker

        w = global_worker()
        return w.get_async(self).__await__()


def _rehydrate_ref(object_id: ObjectID, owner_address: str) -> ObjectRef:
    from .core_worker import try_global_worker

    w = try_global_worker()
    return ObjectRef(object_id, owner_address, _worker=w)


class _RefMarker:
    """Placeholder for an ObjectRef inside serialized task args; the executor
    resolves markers to values (or back to refs for nested refs) before
    invoking user code."""

    __slots__ = ("object_id", "owner_address", "nested")

    def __init__(self, object_id: ObjectID, owner_address: str, nested: bool = False):
        self.object_id = object_id
        self.owner_address = owner_address
        self.nested = nested
