"""Process-local eviction hooks for checkpoint-then-evict preemption.

When the control plane preempts a placement group, the victim's workers
receive a ``prepare_evict`` RPC (node agent fan-out).  Actors expose a
``prepare_evict()`` method for this; everything else in the process —
data actor-pool state, buffered writers, anything that wants a final
flush before the bundle is reclaimed — registers a hook here.

Hooks are a stack per process (newest first), each registered under a
token so two components never clobber each other's registration —
the same discipline as ``util.remediation``'s actuator registry.
Hook signature: ``fn(cause: str) -> None``; a hook that raises is
skipped (eviction is never blocked on a checkpoint).
"""

from __future__ import annotations

import itertools
import logging
import threading
from typing import Callable, Dict

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_hooks: Dict[int, Callable[[str], None]] = {}
_tokens = itertools.count(1)


def register_eviction_hook(fn: Callable[[str], None]) -> int:
    """Register a pre-eviction checkpoint hook; returns an unregister
    token.  Live for the component's lifetime, not the process's."""
    with _lock:
        token = next(_tokens)
        _hooks[token] = fn
        return token


def unregister_eviction_hook(token: int) -> None:
    with _lock:
        _hooks.pop(token, None)


def run_eviction_hooks(cause: str) -> int:
    """Run every registered hook (newest first); returns how many
    completed without raising."""
    with _lock:
        items = sorted(_hooks.items(), reverse=True)
    done = 0
    for _token, fn in items:
        try:
            fn(cause)
            done += 1
        except Exception as e:  # noqa: BLE001 — evict proceeds regardless
            logger.warning("eviction hook failed: %s", e)
    return done
