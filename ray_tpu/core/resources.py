"""Resource accounting data model.

Equivalent of the reference's scheduling data model
(Ray ``src/ray/common/scheduling/fixed_point.h``, ``resource_set.h``,
``cluster_resource_data.h``): fixed-point arithmetic (no float drift when
repeatedly acquiring/releasing 0.1 CPU), per-node totals/availables, and
instance-granular accounting for accelerator chips so a task holding
``TPU: 2`` knows *which* chips it holds (drives TPU_VISIBLE_CHIPS isolation).
"""

from __future__ import annotations

from typing import Dict, List, Optional

PRECISION = 10000  # fixed-point denominator


def to_fixed(v: float) -> int:
    return int(round(v * PRECISION))


def from_fixed(v: int) -> float:
    return v / PRECISION


class ResourceSet:
    """Immutable-ish mapping resource-name -> fixed-point amount."""

    __slots__ = ("_amounts",)

    def __init__(self, amounts: Optional[Dict[str, float]] = None, _fixed=None):
        if _fixed is not None:
            self._amounts = {k: v for k, v in _fixed.items() if v != 0}
        else:
            self._amounts = {
                k: to_fixed(v) for k, v in (amounts or {}).items() if v != 0
            }

    @classmethod
    def _from_fixed(cls, fixed: Dict[str, int]) -> "ResourceSet":
        return cls(_fixed=fixed)

    def to_dict(self) -> Dict[str, float]:
        return {k: from_fixed(v) for k, v in self._amounts.items()}

    def get(self, name: str) -> float:
        return from_fixed(self._amounts.get(name, 0))

    def is_empty(self) -> bool:
        return not self._amounts

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(other._amounts.get(k, 0) >= v for k, v in self._amounts.items())

    def __add__(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._amounts)
        for k, v in other._amounts.items():
            out[k] = out.get(k, 0) + v
        return ResourceSet._from_fixed(out)

    def __sub__(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._amounts)
        for k, v in other._amounts.items():
            out[k] = out.get(k, 0) - v
        return ResourceSet._from_fixed(out)

    def non_negative(self) -> bool:
        return all(v >= 0 for v in self._amounts.values())

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._amounts == other._amounts

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __getstate__(self):
        return self._amounts

    def __setstate__(self, state):
        self._amounts = state


class ResourceInstanceSet:
    """Instance-granular accounting for discrete resources (TPU chips).

    For a node with 4 TPU chips, ``instances['TPU'] == [1.0, 1.0, 1.0, 1.0]``
    (fixed-point).  Acquiring ``TPU: 2`` returns the indices of the chips
    granted, which the worker-pool turns into TPU_VISIBLE_CHIPS env isolation
    (reference precedent: ray ``python/ray/_private/accelerators/tpu.py``).
    """

    UNIT_RESOURCES = ("TPU", "GPU")

    def __init__(self, totals: Dict[str, float]):
        self.instances: Dict[str, List[int]] = {}
        for name, amount in totals.items():
            if name in self.UNIT_RESOURCES and amount == int(amount):
                self.instances[name] = [PRECISION] * int(amount)

    def acquire(self, name: str, amount: float) -> Optional[List[int]]:
        """Greedy-pack instances; returns granted instance ids or None.
        Mixed requests (e.g. 1.5 chips) take whole chips for the integer part
        and pack the remainder onto a partially-free instance."""
        insts = self.instances.get(name)
        if insts is None:
            return None
        need = to_fixed(amount)
        whole, frac = divmod(need, PRECISION)
        granted: List[int] = []
        for i, avail in enumerate(insts):
            if len(granted) >= whole:
                break
            if avail == PRECISION:
                granted.append(i)
        if len(granted) < whole:
            return None
        frac_idx = None
        if frac > 0:
            # Pack the fraction onto the instance with least (but enough) room
            # among instances not already claimed whole.
            for i, avail in enumerate(insts):
                if i in granted:
                    continue
                if avail >= frac and (frac_idx is None or avail < insts[frac_idx]):
                    frac_idx = i
            if frac_idx is None:
                return None
        for i in granted:
            insts[i] = 0
        if frac_idx is not None:
            insts[frac_idx] -= frac
            granted.append(frac_idx)
        return granted

    def release(self, name: str, amount: float, instance_ids: List[int]):
        """Inverse of acquire: whole-chip ids come first in instance_ids, the
        fractional id (if any) last — mirror that layout when releasing."""
        insts = self.instances.get(name)
        if insts is None or not instance_ids:
            return
        whole, frac = divmod(to_fixed(amount), PRECISION)
        for i in instance_ids[:whole]:
            insts[i] = PRECISION
        if frac > 0:
            i = instance_ids[-1]
            insts[i] = min(PRECISION, insts[i] + frac)


class NodeResources:
    """A node's total + available resources, plus labels (ICI topology etc.)."""

    def __init__(self, total: Dict[str, float], labels: Optional[Dict[str, str]] = None):
        self.total = ResourceSet(total)
        self.available = ResourceSet(total)
        self.labels = labels or {}

    def can_fit(self, request: ResourceSet) -> bool:
        return request.is_subset_of(self.available)

    def could_ever_fit(self, request: ResourceSet) -> bool:
        return request.is_subset_of(self.total)

    def acquire(self, request: ResourceSet) -> bool:
        if not self.can_fit(request):
            return False
        self.available = self.available - request
        return True

    def release(self, request: ResourceSet):
        self.available = self.available + request
        # Clamp against accounting bugs.
        for k, v in self.available._amounts.items():
            cap = self.total._amounts.get(k, 0)
            if v > cap:
                self.available._amounts[k] = cap

    def utilization(self) -> float:
        """Max utilization across resource kinds (drives hybrid policy)."""
        best = 0.0
        for k, tot in self.total._amounts.items():
            if tot <= 0:
                continue
            used = tot - self.available._amounts.get(k, 0)
            best = max(best, used / tot)
        return best

    def snapshot(self) -> dict:
        return {
            "total": self.total.to_dict(),
            "available": self.available.to_dict(),
            "labels": dict(self.labels),
        }
