"""Public API frontends: @remote functions, actor classes, handles.

Equivalent of the reference's ``remote_function.py`` + ``actor.py`` (ray
``python/ray/remote_function.py:41``, ``python/ray/actor.py:1190``): thin
declarative wrappers that translate ``.remote()`` / ``.options()`` calls into
core-worker submissions.  Resource options are TPU-first: ``num_tpus=`` is a
first-class option next to ``num_cpus=``.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from .core_worker import global_worker
from .ids import ActorID
from .runtime_env import resolve_runtime_env
from .scheduler import (
    NodeAffinityStrategy,
    NodeLabelStrategy,
    PlacementGroupStrategy,
    SpreadStrategy,
)
from .task_spec import ObjectRef


def _normalize_options(opts: Dict[str, Any]) -> Dict[str, Any]:
    resources = dict(opts.get("resources") or {})
    if "num_cpus" in opts and opts["num_cpus"] is not None:
        resources["CPU"] = float(opts["num_cpus"])
    if "num_tpus" in opts and opts["num_tpus"] is not None:
        resources["TPU"] = float(opts["num_tpus"])
    if "num_gpus" in opts and opts["num_gpus"] is not None:
        resources["GPU"] = float(opts["num_gpus"])
    # Tasks and actors both default to one CPU slot (actors hold it for
    # their lifetime; declare num_cpus=0 for pure-TPU actors).
    resources.setdefault("CPU", 1.0)
    # Any resource kind with a registered accelerator manager validates its
    # requested quantity (reference: per-vendor validate_resource_request_
    # quantity).
    from .accelerators import get_accelerator_manager

    for kind, quantity in resources.items():
        if kind == "CPU" or not quantity:
            continue
        mgr = get_accelerator_manager(kind)
        if mgr is not None:
            ok, reason = mgr.validate_resource_request_quantity(quantity)
            if not ok:
                raise ValueError(reason)
    strategy = opts.get("scheduling_strategy")
    pg_id = None
    bundle_index = -1
    if isinstance(strategy, PlacementGroupStrategy):
        from .ids import PlacementGroupID

        pg_id = PlacementGroupID.from_hex(strategy.pg_id_hex)
        bundle_index = strategy.bundle_index
        strategy = None
    elif strategy == "SPREAD":
        strategy = SpreadStrategy()
    elif strategy == "DEFAULT" or strategy is None:
        strategy = None
    transport = opts.get("tensor_transport", "")
    if transport not in ("", "device"):
        # "nccl" (the reference's value) or typos must not silently no-op.
        raise ValueError(
            f"unknown tensor_transport {transport!r}: the TPU-native "
            f"transport is 'device'"
        )
    if transport and not opts.get("_actor"):
        raise ValueError(
            "tensor_transport is an actor option (device objects live in "
            "the owning actor's HBM)"
        )
    out = {
        "resources": resources,
        "strategy": strategy,
        "placement_group_id": pg_id,
        "bundle_index": bundle_index,
        "env_vars": resolve_runtime_env(opts.get("runtime_env")),
    }
    return out


class RemoteFunction:
    def __init__(self, fn, default_opts: Optional[dict] = None):
        import inspect

        self._fn = fn
        self._opts = default_opts or {}
        # Memoized: the streaming decision is a constant per function and
        # .remote() is the submission hot path.
        self._is_generator = inspect.isgeneratorfunction(
            fn
        ) or inspect.isasyncgenfunction(fn)
        # Export cache keyed by the worker that exported it: a new
        # ray_tpu.init() means a fresh control-plane KV, so the function must
        # be re-exported there.
        self._export_cache = (None, None)  # (worker, function_id)
        # Options are immutable after .options(); normalize (incl. runtime-env
        # packaging, which hashes directory trees) once, not per .remote().
        self._norm_cache: Optional[dict] = None
        functools.update_wrapper(self, fn)

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._opts)
        merged.update(opts)
        rf = RemoteFunction(self._fn, merged)
        rf._export_cache = self._export_cache
        return rf

    def remote(self, *args, **kwargs):
        worker = global_worker()
        cached_worker, function_id = self._export_cache
        if cached_worker is not worker:
            function_id = worker._export_function(self._fn)
            self._export_cache = (worker, function_id)
        if self._norm_cache is None:
            self._norm_cache = _normalize_options(self._opts)
        norm = self._norm_cache
        num_returns = self._opts.get("num_returns", 1)
        if "num_returns" not in self._opts and self._is_generator:
            # Generator tasks stream their yields (reference: streaming
            # generator returns).  An EXPLICIT num_returns=N keeps the old
            # materialize-N-values behavior.
            num_returns = "streaming"
        refs = worker.submit_task(
            self._fn,
            args,
            kwargs,
            name=self._opts.get("name") or self._fn.__name__,
            num_returns=num_returns,
            resources=norm["resources"],
            strategy=norm["strategy"],
            max_retries=self._opts.get(
                "max_retries", 0
            ),
            placement_group_id=norm["placement_group_id"],
            bundle_index=norm["bundle_index"],
            env_vars=norm["env_vars"],
            function_id=function_id,
            pipeline_depth=self._opts.get("pipeline_depth", 0),
        )
        if num_returns == "streaming":
            return refs  # an ObjectRefGenerator
        if self._opts.get("num_returns", 1) == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Lazily bind this function into a DAG node (ray DAG .bind analog)."""
        from ..dag.nodes import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._fn.__name__!r} cannot be called directly; "
            f"use {self._fn.__name__}.remote()"
        )


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def options(self, num_returns: int = 1) -> "ActorMethod":
        return ActorMethod(self._handle, self._method_name, num_returns)

    def remote(self, *args, **kwargs):
        worker = global_worker()
        refs = worker.submit_actor_task(
            self._handle._actor_id,
            self._method_name,
            args,
            kwargs,
            num_returns=self._num_returns,
        )
        if self._num_returns == "streaming":
            return refs  # an ObjectRefGenerator
        return refs[0] if self._num_returns == 1 else refs

    def bind(self, *args, **kwargs):
        """Lazily bind this method into a DAG node (ray DAG .bind analog)."""
        from ..dag.nodes import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)


def execute_on_actor(handle: "ActorHandle", fn, *args, **kwargs):
    """Run an arbitrary callable inside an actor's process with the actor
    instance as first argument (ray's ``actor.__ray_call__`` analog) —
    the hook out-of-band protocols (collective group init, device-object
    transfers) use to reach inside user actors."""
    from .serialization import dumps_function

    return ActorMethod(handle, "__rtpu_exec__").remote(
        dumps_function(fn), *args, **kwargs
    )


class ActorHandle:
    def __init__(self, actor_id: ActorID):
        self._actor_id = actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        method = ActorMethod(self, name)
        # Cache: repeated `handle.m.remote()` calls skip __getattr__ and the
        # per-call ActorMethod allocation.  __reduce__ ignores the cache.
        self.__dict__[name] = method
        return method

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id,))


class ActorClass:
    def __init__(self, cls, default_opts: Optional[dict] = None):
        self._cls = cls
        self._opts = default_opts or {}
        self._norm_cache: Optional[dict] = None

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._opts)
        merged.update(opts)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker = global_worker()
        opts = dict(self._opts)
        opts["_actor"] = True
        if self._norm_cache is None:
            self._norm_cache = _normalize_options(opts)
        norm = self._norm_cache
        actor_id, _spec = worker.create_actor(
            self._cls,
            args,
            kwargs,
            name=opts.get("name"),
            namespace=opts.get("namespace", ""),
            resources=norm["resources"],
            max_restarts=opts.get("max_restarts", 0),
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            strategy=norm["strategy"],
            placement_group_id=norm["placement_group_id"],
            bundle_index=norm["bundle_index"],
            env_vars=norm["env_vars"],
            detached=opts.get("lifetime") == "detached",
            get_if_exists=opts.get("get_if_exists", False),
            tensor_transport=opts.get("tensor_transport", ""),
            priority=opts.get("priority"),
        )
        return ActorHandle(actor_id)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__!r} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()"
        )


def remote(*args, **kwargs):
    """``@remote`` decorator for functions and classes, with options:
    ``@remote(num_cpus=2, num_tpus=4, max_retries=3, ...)``."""

    def decorate(obj, opts):
        if isinstance(obj, type):
            return ActorClass(obj, opts)
        return RemoteFunction(obj, opts)

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return decorate(args[0], {})
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_tpus=1)")

    def wrapper(obj):
        return decorate(obj, dict(kwargs))

    return wrapper
