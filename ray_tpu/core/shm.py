"""Shared-memory segments backed by mmap'd files in /dev/shm.

Equivalent of the plasma store's memory substrate (Ray
``src/ray/object_manager/plasma/``: dlmalloc over mmap'd /dev/shm with fd
passing).  We use one named file per object instead of a single arena +
allocator: the kernel's tmpfs is the allocator, segments are named by object
id so any process on the node can attach without fd passing, and unlinking is
the eviction primitive.  A C++ arena allocator can replace this under the same
interface later without touching callers.
"""

from __future__ import annotations

import mmap
import os
import secrets
from typing import Optional

SHM_DIR = "/dev/shm"
_PREFIX = "rtpu"


def segment_name(session_id: str, object_hex: str) -> str:
    # FULL 32-char object hex: the id's last 4 bytes are the return-object
    # index — truncating them collapses all return/stream objects of one
    # task onto a single segment file (observed: stream-item replay wrote
    # three items into one file, every read saw the last value).
    return f"{_PREFIX}_{session_id}_{object_hex}"


def _path(name: str) -> str:
    return os.path.join(SHM_DIR, name)


class ShmSegment:
    """A single mmap'd shared-memory segment."""

    def __init__(self, name: str, mm: mmap.mmap, size: int, created: bool):
        self.name = name
        self.mm = mm
        self.size = size
        self.created = created
        self._closed = False

    @classmethod
    def create(cls, name: str, size: int) -> "ShmSegment":
        """Create (or atomically replace) a segment.  Replacement matters for
        task retries: return-object names are deterministic per task id, and
        a crashed attempt may have left a sealed segment behind."""
        path = _path(name)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        except FileExistsError:
            os.unlink(path)
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, size)
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        return cls(name, mm, size, created=True)

    @classmethod
    def attach(cls, name: str) -> "ShmSegment":
        path = _path(name)
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        return cls(name, mm, size, created=False)

    def view(self) -> memoryview:
        return memoryview(self.mm)

    def close(self):
        if not self._closed:
            self._closed = True
            try:
                self.mm.close()
            except (BufferError, ValueError):
                # Exported numpy views still alive; leave the mapping in
                # place — the OS reclaims it when the process exits.
                self._closed = False

    def unlink(self):
        try:
            os.unlink(_path(self.name))
        except FileNotFoundError:
            pass


def unlink_by_name(name: str):
    try:
        os.unlink(_path(name))
    except FileNotFoundError:
        pass


def cleanup_session(session_id: str):
    """Remove all segments belonging to a session (called on shutdown)."""
    prefix = f"{_PREFIX}_{session_id}_"
    try:
        for entry in os.listdir(SHM_DIR):
            if entry.startswith(prefix):
                unlink_by_name(entry)
    except FileNotFoundError:
        pass


def new_session_id() -> str:
    return secrets.token_hex(4)
