"""Accelerator plugin system — one manager per vendor.

Reference: the ``AcceleratorManager`` ABC (ray
``python/ray/_private/accelerators/accelerator.py:18``) with per-vendor
implementations; here TPU is the first-class citizen
(``TPUAcceleratorManager``, reference ``accelerators/tpu.py:267``) and CPU
is the trivial fallback.  The node agent uses the active manager for
resource detection and per-lease chip isolation; new vendors plug in by
registering a manager.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from . import tpu_detect


class AcceleratorManager:
    """ABC (reference interface, ray ``accelerator.py:43-111``)."""

    # Resource string, e.g. "TPU".
    resource_name: str = ""

    def get_current_node_num_accelerators(self) -> int:
        raise NotImplementedError

    def get_current_node_accelerator_type(self) -> Optional[str]:
        raise NotImplementedError

    def get_current_node_additional_resources(self) -> Dict[str, float]:
        return {}

    def get_current_node_labels(self) -> Dict[str, str]:
        return {}

    def validate_resource_request_quantity(
        self, quantity: float
    ) -> Tuple[bool, Optional[str]]:
        return True, None

    def get_visible_accelerator_ids_env_var(self) -> Optional[str]:
        return None

    def get_current_process_visible_accelerator_ids(
        self,
    ) -> Optional[List[str]]:
        var = self.get_visible_accelerator_ids_env_var()
        if var is None:
            return None
        raw = os.environ.get(var)
        if raw is None:
            return None
        return [v for v in raw.split(",") if v != ""]

    def set_current_process_visible_accelerator_ids(
        self, ids: List[str]
    ) -> None:
        var = self.get_visible_accelerator_ids_env_var()
        if var is not None:
            os.environ[var] = ",".join(str(i) for i in ids)


class TPUAcceleratorManager(AcceleratorManager):
    """TPU chips + slice topology (reference ``accelerators/tpu.py``)."""

    resource_name = "TPU"

    def get_current_node_num_accelerators(self) -> int:
        return tpu_detect.num_local_chips()

    def get_current_node_accelerator_type(self) -> Optional[str]:
        return tpu_detect.accelerator_type() or None

    def get_current_node_additional_resources(self) -> Dict[str, float]:
        res, _labels = tpu_detect.detect_resources_and_labels()
        return {k: v for k, v in res.items() if k != "TPU"}

    def get_current_node_labels(self) -> Dict[str, str]:
        _res, labels = tpu_detect.detect_resources_and_labels()
        return labels

    def validate_resource_request_quantity(
        self, quantity: float
    ) -> Tuple[bool, Optional[str]]:
        # Reference rule (tpu.py:92-105): fractional chips are not
        # schedulable, and multi-chip requests must be 1, 2, 4, or a
        # multiple of 4 (ICI connectivity).
        if quantity != int(quantity):
            return False, "TPU requests must be whole chips"
        q = int(quantity)
        if q in (1, 2, 4) or (q > 4 and q % 4 == 0):
            return True, None
        return False, (
            f"invalid TPU chip count {q}: must be 1, 2, 4, or a multiple "
            f"of 4"
        )

    def get_visible_accelerator_ids_env_var(self) -> str:
        from .config import GlobalConfig

        return GlobalConfig.tpu_visible_chips_env  # TPU_VISIBLE_CHIPS


class CPUAcceleratorManager(AcceleratorManager):
    resource_name = "CPU"

    def get_current_node_num_accelerators(self) -> int:
        return os.cpu_count() or 1

    def get_current_node_accelerator_type(self) -> Optional[str]:
        return None


_REGISTRY: Dict[str, AcceleratorManager] = {
    "TPU": TPUAcceleratorManager(),
    "CPU": CPUAcceleratorManager(),
}


def register_accelerator_manager(mgr: AcceleratorManager) -> None:
    _REGISTRY[mgr.resource_name] = mgr


def get_accelerator_manager(resource_name: str) -> Optional[AcceleratorManager]:
    return _REGISTRY.get(resource_name)


def all_accelerator_managers() -> List[AcceleratorManager]:
    return list(_REGISTRY.values())
