from .backend import (  # noqa: F401
    AccelerateBackend,
    Backend,
    JaxBackend,
    TensorflowBackend,
    TorchBackend,
)
from .checkpoint import Checkpoint  # noqa: F401
from .config import (  # noqa: F401
    CheckpointConfig,
    CollectiveConfig,
    FailureConfig,
    PipelineConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from .pipeline import (  # noqa: F401
    PipelinedTrainer,
    StageModule,
    build_1f1b_schedule,
    gpt2_stage_modules,
)
from .session import (  # noqa: F401
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
    should_stop,
)
from .trainer import (  # noqa: F401
    DataParallelTrainer,
    JaxTrainer,
    TensorflowTrainer,
    TorchTrainer,
)
