from .checkpoint import Checkpoint  # noqa: F401
from .config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from .session import get_checkpoint, get_context, report  # noqa: F401
from .trainer import DataParallelTrainer, JaxTrainer  # noqa: F401
