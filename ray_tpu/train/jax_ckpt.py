"""Async sharded checkpointing for jax.Array pytrees.

The orbax-style save hook (reference role: ray Train's torch/lightning
checkpoint utilities; on TPU the ecosystem answer is orbax
``AsyncCheckpointer``): device arrays transfer to host and write as one
``.npy`` per leaf plus a pytree manifest, with the disk writes running on
a background thread so the train step resumes as soon as device→host
transfer finishes (the async-checkpoint overlap that matters at pod
scale).  Restore optionally re-places leaves with a sharding tree.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Optional

_MANIFEST = "pytree.json"


def _flatten_with_paths(tree):
    import jax

    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in leaves_with_paths:
        name = "_".join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path
        )
        out.append((name or "leaf", leaf))
    return out


def save_sharded(tree, directory: str) -> None:
    """Synchronous save: one .npy per leaf + manifest."""
    import jax
    import numpy as np

    os.makedirs(directory, exist_ok=True)
    named = _flatten_with_paths(tree)
    treedef = jax.tree_util.tree_structure(tree)
    manifest = {"leaves": [n for n, _ in named], "treedef": str(treedef)}
    # Device→host first (this is the part the caller must wait for).
    host = [(n, np.asarray(l)) for n, l in named]
    for name, arr in host:
        np.save(os.path.join(directory, f"{name}.npy"), arr)
    with open(os.path.join(directory, _MANIFEST), "w") as f:
        json.dump(manifest, f)


class AsyncSave:
    """Handle for an in-flight background save; ``wait()`` before
    committing the checkpoint directory."""

    def __init__(self, thread: threading.Thread):
        self._thread = thread
        self.error: Optional[BaseException] = None

    def wait(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("async checkpoint save still running")
        if self.error is not None:
            raise self.error


def async_save_sharded(tree, directory: str) -> AsyncSave:
    """Device→host transfer happens NOW (so training may mutate the donated
    buffers immediately after return); the .npy writes run on a thread."""
    import jax
    import numpy as np

    named = _flatten_with_paths(tree)
    host = [(n, np.asarray(l)) for n, l in named]  # sync transfer
    treedef = jax.tree_util.tree_structure(tree)
    manifest = {"leaves": [n for n, _ in host], "treedef": str(treedef)}

    handle_box = {}

    def write():
        try:
            os.makedirs(directory, exist_ok=True)
            for name, arr in host:
                np.save(os.path.join(directory, f"{name}.npy"), arr)
            with open(os.path.join(directory, _MANIFEST), "w") as f:
                json.dump(manifest, f)
        except BaseException as e:  # noqa: BLE001 — surfaced via wait()
            handle_box["handle"].error = e

    thread = threading.Thread(target=write, daemon=True, name="ckpt-write")
    handle = AsyncSave(thread)
    handle_box["handle"] = handle
    thread.start()
    return handle


def restore_sharded(tree_like, directory: str, shardings=None):
    """Restore into the structure of ``tree_like``; with ``shardings`` (a
    matching pytree of NamedShardings) leaves are placed sharded."""
    import jax
    import numpy as np

    named = _flatten_with_paths(tree_like)
    arrays = [
        np.load(os.path.join(directory, f"{name}.npy")) for name, _ in named
    ]
    treedef = jax.tree_util.tree_structure(tree_like)
    restored = jax.tree_util.tree_unflatten(treedef, arrays)
    if shardings is not None:
        restored = jax.tree.map(
            lambda a, s: jax.device_put(a, s), restored, shardings
        )
    return restored
