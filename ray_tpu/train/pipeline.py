"""Cross-slice MPMD pipeline parallelism: stage actors + 1F1B microbatch
streaming.

Where ``parallel/pipeline.py`` expresses a pipeline as one SPMD program
(GPipe over the ``stage`` mesh axis, single slice), this module is the
MPMD design of "Scaling Deep Learning Training with MPMD Pipeline
Parallelism" (arxiv 2412.14374): each pipeline stage is a **long-lived
actor** pinned to its own placement-group bundle (one stage per TPU
slice), activations and gradients stream between adjacent stages as
microbatches over the zero-copy p2p path
(``collective.p2p.StageChannel`` → ``SerializedPayload`` out-of-band
framing), and an interleaved 1F1B schedule bounds the pipeline bubble.
DP composes *within* a stage (``PipelineConfig.dp_devices_per_stage``:
XLA SPMD shards each microbatch over the stage's local mesh and inserts
the gradient psum), PP composes *across* stages — exactly the paper's
PP-outside / DP-inside split.

The model is declared as a list of virtual-stage **modules** produced by
a ``module_builder(virtual_idx, total_virtual) -> StageModule`` callable
(cloudpickled to the stage actors).  Virtual stage ``v`` lives on actor
``v % num_stages`` (Megatron-style interleaving); module 0 consumes the
raw per-microbatch input, the last module computes the scalar loss.

Failure semantics: the driver checkpoints all stages synchronously
(initially and every ``checkpoint_every_n_steps``); a stage-actor death
is detected by the step deadline, the dead actor is restarted into the
same bundle, every stage reloads the last synchronized checkpoint, and
training resumes from that step (bounded by ``FailureConfig.max_failures``).

Self-instrumentation (flight recorder): per-stage forward/backward/stall
histograms, a computed bubble-fraction gauge, inter-stage activation
bytes + achieved bandwidth — all under the ``ray_tpu_pipeline_*`` names
documented in docs/observability.md.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.serialization import dumps_function

from .config import FailureConfig, PipelineConfig, Result, RunConfig

logger = logging.getLogger(__name__)


# ------------------------------------------------- quantized grad exchange
@dataclasses.dataclass
class _QuantizedLeaf:
    """One block-quantized tensor riding a B-edge push: int8 payload,
    per-block fp32 scales, and enough metadata to restore the original
    array (dtype kept as the numpy dtype OBJECT — ``np.dtype("bfloat16")``
    does not parse, the ml_dtypes instance does)."""

    q: Any
    scales: Any
    size: int
    shape: tuple
    dtype: Any


def _quantize_grad_tree(tree, block_size: int):
    """Quantize every float leaf of a gradient pytree for the wire
    (non-float leaves pass through untouched)."""
    import jax
    import numpy as np

    from ray_tpu.collective import algorithms as alg

    def q(leaf):
        a = np.asarray(leaf)
        if not alg.quantizable_dtype(a.dtype):
            return a
        qv, scales, size = alg.quantize_blocks_np(a, block_size)
        return _QuantizedLeaf(qv, scales, size, a.shape, a.dtype)

    return jax.tree.map(q, tree)


def _dequantize_grad_tree(tree):
    import jax

    from ray_tpu.collective import algorithms as alg

    def d(leaf):
        if isinstance(leaf, _QuantizedLeaf):
            return alg.dequantize_blocks_np(
                leaf.q, leaf.scales, leaf.size, leaf.shape, leaf.dtype
            )
        return leaf

    return jax.tree.map(
        d, tree, is_leaf=lambda x: isinstance(x, _QuantizedLeaf)
    )


# --------------------------------------------------------------- schedule
@dataclasses.dataclass(frozen=True)
class PipeOp:
    """One slot of a stage's static schedule: run ``kind`` ("F"/"B") for
    ``microbatch`` through local model chunk ``chunk``."""

    kind: str
    chunk: int
    microbatch: int


def build_1f1b_schedule(
    num_stages: int, num_microbatches: int, interleave: int = 1
) -> List[List[PipeOp]]:
    """Per-stage op order for (interleaved) 1F1B.

    Non-interleaved (``interleave == 1``): stage ``s`` runs
    ``min(M, S-1-s)`` warmup forwards, then alternates F/B (the steady
    1F1B window), then drains backwards — at most ``S - s`` microbatches
    are ever in flight on a stage.  Interleaved: the Megatron-LM virtual
    -stage schedule; microbatches advance in groups of ``num_stages``
    per chunk, warmup grows by ``(V-1)·S``, and the bubble shrinks by
    ``1/V``.  Returns ``schedules[stage] -> [PipeOp, ...]``.
    """
    S, M, V = num_stages, num_microbatches, interleave
    if S < 1 or M < 1 or V < 1:
        raise ValueError("num_stages, num_microbatches, interleave >= 1")
    if V > 1 and M % S != 0:
        raise ValueError(
            "interleaved 1F1B needs num_microbatches divisible by "
            f"num_stages (got {M} over {S})"
        )
    total = M * V

    def chunk_of(counter: int, forward: bool) -> int:
        c = (counter % (S * V)) // S
        return c if forward else V - 1 - c

    def mb_of(counter: int) -> int:
        return (counter // (S * V)) * S + counter % S

    schedules: List[List[PipeOp]] = []
    for s in range(S):
        if V == 1:
            warmup = min(M, S - 1 - s)
        else:
            warmup = min(total, (S - 1 - s) * 2 + (V - 1) * S)
        ops: List[PipeOp] = []
        f = b = 0
        for _ in range(warmup):
            ops.append(PipeOp("F", chunk_of(f, True), mb_of(f)))
            f += 1
        for _ in range(total - warmup):
            ops.append(PipeOp("F", chunk_of(f, True), mb_of(f)))
            f += 1
            ops.append(PipeOp("B", chunk_of(b, False), mb_of(b)))
            b += 1
        for _ in range(warmup):
            ops.append(PipeOp("B", chunk_of(b, False), mb_of(b)))
            b += 1
        schedules.append(ops)
    return schedules


def theoretical_bubble_fraction(
    num_stages: int, num_microbatches: int, interleave: int = 1
) -> float:
    """The classic 1F1B bubble bound: (S-1) / (S-1 + M·V)."""
    s1 = num_stages - 1
    return s1 / (s1 + num_microbatches * interleave)


# ----------------------------------------------------------- model chunks
@dataclasses.dataclass
class StageModule:
    """One virtual stage of the model.

    ``init(rng) -> params``; ``apply(params, x) -> y`` for interior
    modules, ``apply(params, x, targets) -> scalar loss`` when
    ``is_loss_stage`` (the final virtual stage).  The first module's
    ``x`` is the raw microbatch input (e.g. int32 tokens) and is treated
    as non-differentiable."""

    init: Callable
    apply: Callable
    is_loss_stage: bool = False


def gpt2_stage_modules(cfg, total_virtual: int, seed: int = 0):
    """Split a GPT-2 into ``total_virtual`` sequential chunks.

    Chunk 0 owns the embeddings + the first layers; the last chunk owns
    the remaining layers, the final layernorm, and an (untied) copy of
    the unembedding matrix + the loss.  All chunks slice their
    parameters out of one ``gpt2_init(seed)`` call, so a pipelined run
    and the sequential reference start from bit-identical weights.
    Returns a ``module_builder`` for :class:`PipelinedTrainer`.
    """
    if total_virtual < 1 or cfg.n_layer < total_virtual:
        raise ValueError(
            f"cannot split {cfg.n_layer} layers into {total_virtual} chunks"
        )
    bounds = [
        (cfg.n_layer * v // total_virtual,
         cfg.n_layer * (v + 1) // total_virtual)
        for v in range(total_virtual)
    ]

    def module_builder(v: int, total: int) -> StageModule:
        assert total == total_virtual
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.gpt2 import (
            _ce_from_logits,
            _block,
            _layernorm,
        )

        lo, hi = bounds[v]
        first, last = v == 0, v == total_virtual - 1

        def init(rng):
            # The partition is keyed by the builder's seed (not the
            # trainer rng) so every chunk derives from the same virtual
            # full model.  Mirrors gpt2_init's key SEQUENCE exactly but
            # materializes one full tensor at a time and keeps only this
            # chunk's [lo:hi] slice — a stage's resident memory is its
            # share of the model, which is the point of pipelining
            # (equality with gpt2_init slicing is regression-pinned in
            # tests/test_train_pipeline.py).
            del rng
            e, h, d, L = cfg.d_model, cfg.n_head, cfg.head_dim, cfg.n_layer
            k = iter(jax.random.split(jax.random.PRNGKey(seed), 16))
            dt = jnp.dtype(cfg.dtype)
            s = 0.02
            so = s / (2 * L) ** 0.5  # gpt-2 residual-out scaling
            n = hi - lo

            def gen(kk, shape, scale, keep, sl=None):
                # kk is consumed by the caller unconditionally (key-
                # sequence parity); generate only what this chunk keeps.
                if not keep:
                    return None
                t = (jax.random.normal(kk, shape) * scale).astype(dt)
                return t[sl] if sl is not None else t

            sl = slice(lo, hi)
            wte = gen(next(k), (cfg.vocab_size, e), s, first or last)
            wpe = gen(next(k), (cfg.max_seq, e), s, first)
            params = {
                "blocks": {
                    "ln1_g": jnp.ones((n, e), dt),
                    "ln1_b": jnp.zeros((n, e), dt),
                    "wqkv": gen(next(k), (L, e, 3, h, d), s, True, sl),
                    "bqkv": jnp.zeros((n, 3, h, d), dt),
                    "wo": gen(next(k), (L, h, d, e), so, True, sl),
                    "bo": jnp.zeros((n, e), dt),
                    "ln2_g": jnp.ones((n, e), dt),
                    "ln2_b": jnp.zeros((n, e), dt),
                    "wi": gen(next(k), (L, e, 4 * e), s, True, sl),
                    "bi": jnp.zeros((n, 4 * e), dt),
                    "wo2": gen(next(k), (L, 4 * e, e), so, True, sl),
                    "bo2": jnp.zeros((n, e), dt),
                },
            }
            if first:
                params["wte"] = wte
                params["wpe"] = wpe
            if last:
                params["lnf_g"] = jnp.ones((e,), dt)
                params["lnf_b"] = jnp.zeros((e,), dt)
                # Untied unembedding: starts equal to wte, trains on the
                # unembed gradient only (standard for pipeline splits —
                # tying would make wte's gradient span two stages).
                params["unembed"] = wte
            return params

        def run_blocks(params, x):
            def body(h, layer):
                return _block(h, layer, cfg, None), None

            x, _ = jax.lax.scan(body, x, params["blocks"])
            return x

        def apply(params, x, targets=None):
            if first:
                s = x.shape[1]
                h = params["wte"][x] + params["wpe"][:s][None]
            else:
                h = x
            h = run_blocks(params, h)
            if not last:
                return h
            h = _layernorm(h, params["lnf_g"], params["lnf_b"])
            logits = jnp.einsum("bse,ve->bsv", h, params["unembed"])
            b, s = targets.shape
            return _ce_from_logits(logits, targets, 0.0) / (b * s)

        return StageModule(init=init, apply=apply, is_loss_stage=last)

    return module_builder


# ------------------------------------------------------------ chunk state
class _Chunk:
    """Executor for one virtual stage resident on a stage actor: jitted
    forward/backward, in-flight input stash, gradient accumulator."""

    def __init__(self, vidx: int, total_v: int, module: StageModule,
                 rng_seed: int, lr: float, dp_mesh=None):
        import jax
        import optax

        self.vidx = vidx
        self.is_first = vidx == 0
        self.is_last = vidx == total_v - 1
        self.module = module
        self._stash: Dict[int, Any] = {}  # microbatch -> input (+targets)
        self.stash_hwm = 0
        self._dp_mesh = dp_mesh

        apply = module.apply
        if self.is_last:
            if self.is_first:
                self._fwd = jax.jit(lambda p, x, t: apply(p, x, t))
                self._bwd = jax.jit(
                    jax.value_and_grad(lambda p, x, t: apply(p, x, t))
                )
            else:
                self._fwd = jax.jit(lambda p, x, t: apply(p, x, t))
                self._bwd = jax.jit(jax.value_and_grad(
                    lambda p, x, t: apply(p, x, t), argnums=(0, 1)
                ))
        else:
            self._fwd = jax.jit(apply)
            if self.is_first:
                def bwd_first(p, x, gy):
                    _, pull = jax.vjp(lambda pp: apply(pp, x), p)
                    return pull(gy)[0]

                self._bwd = jax.jit(bwd_first)
            else:
                def bwd_mid(p, x, gy):
                    _, pull = jax.vjp(apply, p, x)
                    return pull(gy)

                self._bwd = jax.jit(bwd_mid)

        self.params = module.init(jax.random.PRNGKey(rng_seed))
        self._tx = optax.adamw(lr)
        self.opt_state = self._tx.init(self.params)
        self._grad_acc = None
        self._apply_updates = jax.jit(
            lambda params, opt_state, grads: self._opt_step(
                params, opt_state, grads
            )
        )

    def _opt_step(self, params, opt_state, grads):
        import optax

        updates, opt_state = self._tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def _shard(self, x):
        """DP within the stage: place the microbatch batch-axis over the
        local mesh (params stay replicated; XLA inserts the grad psum)."""
        if self._dp_mesh is None:
            return x
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(x, NamedSharding(self._dp_mesh, P("data")))

    def forward(self, mb: int, x, targets=None):
        x = self._shard(x)
        if self.is_last:
            targets = self._shard(targets)
            self._stash[mb] = (x, targets)
            self.stash_hwm = max(self.stash_hwm, len(self._stash))
            out = self._fwd(self.params, x, targets)
        else:
            self._stash[mb] = x
            self.stash_hwm = max(self.stash_hwm, len(self._stash))
            out = self._fwd(self.params, x)
        return out

    def backward(self, mb: int, gy=None):
        """Returns (loss_or_None, gx_or_None); accumulates param grads."""
        loss = gx = None
        if self.is_last:
            x, targets = self._stash.pop(mb)
            if self.is_first:
                loss, gp = self._bwd(self.params, x, targets)
            else:
                loss, (gp, gx) = self._bwd(self.params, x, targets)
        else:
            x = self._stash.pop(mb)
            if self.is_first:
                gp = self._bwd(self.params, x, gy)
            else:
                gp, gx = self._bwd(self.params, x, gy)
        import jax

        if self._grad_acc is None:
            self._grad_acc = gp
        else:
            self._grad_acc = jax.tree.map(
                lambda a, g: a + g, self._grad_acc, gp
            )
        return loss, gx

    def apply_grads(self, num_microbatches: int):
        import jax

        if self._grad_acc is None:
            return
        grads = jax.tree.map(
            lambda g: g / num_microbatches, self._grad_acc
        )
        self.params, self.opt_state = self._apply_updates(
            self.params, self.opt_state, grads
        )
        self._grad_acc = None

    def state(self):
        import jax
        import numpy as np

        return {
            "params": jax.tree.map(np.asarray, self.params),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
        }

    def load_state(self, state):
        import jax
        import jax.numpy as jnp

        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(
            jnp.asarray, state["opt_state"],
            is_leaf=lambda x: x is None or hasattr(x, "shape"),
        )
        self._grad_acc = None
        self._stash.clear()


# ------------------------------------------------------------- stage actor
@ray_tpu.remote
class PipelineStage:
    """One pipeline stage: owns ``interleave`` model chunks, executes its
    static 1F1B op list each step, streams activations/gradients to its
    neighbors over the zero-copy p2p channel, and applies its own
    optimizer after the last microbatch."""

    def __init__(self, stage_idx: int, cfg: PipelineConfig, run_id: str):
        from ray_tpu.util.debug_locks import make_lock

        self.stage = stage_idx
        self.cfg = cfg
        self.run_id = run_id
        self.chunks: Dict[int, _Chunk] = {}  # chunk slot -> executor
        self.addresses: List[str] = []
        self.channel = None
        self.generation = -1
        self._schedule: List[PipeOp] = []
        self._op_trace: List[tuple] = []
        self._last_stats: Dict[str, Any] = {}
        # Zombie-step fencing: an abandoned run_step (its driver ref was
        # dropped after a peer died) keeps executing on another actor
        # lane.  reset() raises _abort and waits for _inflight to drain
        # before touching chunk state, so a superseded step can never
        # race load_state or feed on the recovered generation.
        self._inflight = 0
        self._abort = False
        self._inflight_lock = make_lock("pipeline-stage-inflight")
        # Fault-injection state (devtools.chaos): lives on the ACTOR, so
        # a remediation respawn-and-replace — a fresh actor in the
        # bundle — clears it, the way replacing a sick process clears
        # its sickness.  reset() deliberately does NOT clear it.
        self._chaos: Dict[str, Any] = {}

    # ------------------------------------------------------------- wiring
    def rpc_address(self) -> str:
        from ray_tpu.collective.p2p import StageChannel

        return StageChannel.self_address()

    def build(self, module_builder_payload: bytes, lr: float,
              rng_seed: int) -> bool:
        """Instantiate this stage's model chunks (one per interleave
        slot); chunk slot c executes virtual stage ``c*S + stage``."""
        from ray_tpu.core.serialization import loads_function

        builder = loads_function(module_builder_payload)
        cfg = self.cfg
        total_v = cfg.total_virtual_stages
        dp_mesh = self._make_dp_mesh(cfg.dp_devices_per_stage)
        for c in range(cfg.interleave):
            v = c * cfg.num_stages + self.stage
            self.chunks[c] = _Chunk(
                v, total_v, builder(v, total_v), rng_seed, lr,
                dp_mesh=dp_mesh,
            )
        self._schedule = build_1f1b_schedule(
            cfg.num_stages, cfg.num_microbatches, cfg.interleave
        )[self.stage]
        return True

    @staticmethod
    def _make_dp_mesh(dp: int):
        if dp <= 1:
            return None
        import jax
        from jax.sharding import Mesh

        devices = jax.devices()
        if len(devices) < dp:
            raise ValueError(
                f"dp_devices_per_stage={dp} but only {len(devices)} local "
                "devices are visible to this stage"
            )
        return Mesh(devices[:dp], ("data",))

    def wire(self, addresses: List[str], generation: int) -> bool:
        """(Re)connect to the neighbor stages; bump the schedule
        generation so tensors from an aborted generation are ignored."""
        from ray_tpu.collective.p2p import StageChannel

        self.addresses = list(addresses)
        self.generation = generation
        self.channel = StageChannel(
            f"pp:{self.run_id}:g{generation}",
            recv_timeout_s=self.cfg.recv_timeout_s,
        )
        return True

    def reset(self) -> int:
        """Quiesce any superseded in-flight step, then drop parked
        tensors of EVERY generation of this run and the aborted step's
        chunk state (restart path)."""
        from ray_tpu.collective.p2p import local_mailbox

        # Fence first: zombie run_steps notice _abort within one recv
        # slice (~1s) or at their next op; only after the last one exits
        # is it safe to clear stashes / reload params.
        self._abort = True
        deadline = time.monotonic() + self.cfg.recv_timeout_s + 10.0
        while time.monotonic() < deadline:
            with self._inflight_lock:
                if self._inflight == 0:
                    break
            time.sleep(0.05)
        else:
            logger.warning(
                "stage %d reset: %d run_step(s) still in flight after "
                "quiesce deadline", self.stage, self._inflight,
            )
        self._abort = False
        dropped = local_mailbox().drop_prefix(f"pp:{self.run_id}:")
        if self.channel is not None:
            self.channel.reset()
        for chunk in self.chunks.values():
            chunk._stash.clear()
            chunk._grad_acc = None
        return dropped

    # -------------------------------------------------------------- state
    def get_state(self) -> bytes:
        return pickle.dumps(
            {c: chunk.state() for c, chunk in self.chunks.items()}
        )

    def load_state(self, blob: bytes) -> bool:
        states = pickle.loads(blob)
        for c, state in states.items():
            self.chunks[c].load_state(state)
        return True

    def prepare_evict(self) -> bytes:
        """Checkpoint-then-evict hook: the returned blob is parked in the
        cluster KV (namespace ``eviction``) by the worker runtime before
        this stage's bundle is reclaimed, so the preempted trainer's next
        incarnation resumes bit-identical (docs/scheduling.md)."""
        return self.get_state()

    def ping(self) -> bool:
        return True

    def inject_chaos(self, spec: Optional[Dict[str, Any]]) -> bool:
        """``devtools.chaos`` hook; ``None`` (or ``{}``) reverts.

        - ``{"compute_delay_s": s}`` — slow host: every forward op takes
          ``s`` longer, landing in this stage's fwd histogram while its
          PEERS accumulate the stall (the real slow-host signature: the
          straggler rule flags a waiting victim, and the trainer's
          actuator localizes the culprit by compute share — see
          ``PipelinedTrainer._remediation_actuator``).
        - ``{"recv_delay_s": s}`` — slow delivery: every neighbor-tensor
          receive stalls ``s`` extra on this stage."""
        self._chaos = dict(spec or {})
        return True

    # ---------------------------------------------------------- execution
    @staticmethod
    def _edge_fwd(channel, v: int) -> str:
        return channel.edge(f"f{v}", f"f{v + 1}")

    @staticmethod
    def _edge_bwd(channel, v: int) -> str:
        return channel.edge(f"b{v}", f"b{v - 1}")

    def _neighbor(self, stage: int) -> str:
        return self.addresses[stage % self.cfg.num_stages]

    def _recv(self, channel, edge: str, seq):
        """Blocking recv in ~1s slices so a superseded step (reset() in
        progress) bails out promptly instead of holding the quiesce."""
        delay = self._chaos.get("recv_delay_s")
        if delay:
            # Injected straggle (devtools.chaos): counted inside the
            # caller's stall window, exactly like a real slow neighbor.
            time.sleep(float(delay))
        deadline = time.monotonic() + self.cfg.recv_timeout_s
        while True:
            self._check_abort()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"stage {self.stage}: recv timed out on {edge!r} "
                    f"seq {seq!r}"
                )
            try:
                return channel.recv(edge, seq, timeout=min(1.0, remaining))
            except TimeoutError:
                continue

    def _check_abort(self):
        if self._abort:
            raise RuntimeError(
                f"stage {self.stage}: step superseded by reset()"
            )

    def run_step(self, step: int, inputs: Optional[List] = None,
                 targets: Optional[List] = None) -> Dict[str, Any]:
        """Execute this stage's 1F1B op list for one training step.

        ``inputs``: per-microbatch raw inputs (stage 0 only).
        ``targets``: per-microbatch targets (last stage only).
        Returns stats (+ per-microbatch losses on the last stage).
        """
        with self._inflight_lock:
            self._inflight += 1
        try:
            return self._run_step_fenced(step, inputs, targets)
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    def _run_step_fenced(self, step, inputs, targets) -> Dict[str, Any]:
        from ray_tpu.util import flight_recorder

        cfg = self.cfg
        S, M, V = cfg.num_stages, cfg.num_microbatches, cfg.interleave
        # Pin this step to its wiring generation: a concurrent recovery
        # swaps self.channel, but THIS step keeps sending/receiving only
        # on its own generation's edges (and aborts at the next fence).
        channel = self.channel
        self._maybe_debug_fail(step)
        t_step0 = time.perf_counter()
        fwd_s = bwd_s = stall_s = 0.0
        losses: Dict[int, float] = {}
        self._op_trace = []

        for op in self._schedule:
            self._check_abort()
            chunk = self.chunks[op.chunk]
            v = op.chunk * S + self.stage
            mb = op.microbatch
            seq = (step, mb)
            if op.kind == "F":
                if chunk.is_first:
                    x = inputs[mb]
                else:
                    t0 = time.perf_counter()
                    x = self._recv(channel, self._edge_fwd(channel, v - 1),
                                   seq)
                    stall_s += time.perf_counter() - t0
                t0 = time.perf_counter()
                y = chunk.forward(
                    mb, x, targets[mb] if chunk.is_last else None
                )
                self._block_until_ready(y)
                if self._chaos.get("compute_delay_s"):
                    # Injected slow host (devtools.chaos): lands in the
                    # forward histogram like real slow compute.
                    time.sleep(float(self._chaos["compute_delay_s"]))
                dt = time.perf_counter() - t0
                fwd_s += dt
                flight_recorder.record_pipeline_op("F", self.stage, dt)
                if not chunk.is_last:
                    channel.send(
                        self._edge_fwd(channel, v), seq, self._to_host(y),
                        self._neighbor(self.stage + 1),
                    )
            else:
                gy = None
                if not chunk.is_last:
                    t0 = time.perf_counter()
                    gy = self._recv(channel, self._edge_bwd(channel, v + 1),
                                    seq)
                    if cfg.quantized_grad_exchange:
                        gy = _dequantize_grad_tree(gy)
                    stall_s += time.perf_counter() - t0
                t0 = time.perf_counter()
                loss, gx = chunk.backward(mb, gy)
                if loss is not None:
                    losses[mb] = float(loss)
                if gx is not None:
                    self._block_until_ready(gx)
                dt = time.perf_counter() - t0
                bwd_s += dt
                flight_recorder.record_pipeline_op("B", self.stage, dt)
                if not chunk.is_first:
                    gx_wire = self._to_host(gx)
                    if cfg.quantized_grad_exchange:
                        # Opt-in EQuARX-style wire quantization of the
                        # gradient stream (the DCN-bound direction) —
                        # int8 blocks + scales, ~4x fewer bytes pushed.
                        gx_wire = _quantize_grad_tree(
                            gx_wire, cfg.quant_block_size
                        )
                    channel.send(
                        self._edge_bwd(channel, v), seq, gx_wire,
                        self._neighbor(self.stage - 1),
                    )
            self._op_trace.append((op.kind, op.chunk, mb))

        channel.flush()
        self._check_abort()
        for chunk in self.chunks.values():
            chunk.apply_grads(M)
        wall_s = time.perf_counter() - t_step0
        flight_recorder.record_pipeline_step(
            self.stage, stall_s, wall_s, M * V
        )
        stats = {
            "stage": self.stage,
            "step": step,
            "fwd_s": fwd_s,
            "bwd_s": bwd_s,
            "stall_s": stall_s,
            "wall_s": wall_s,
            "stash_hwm": max(
                (c.stash_hwm for c in self.chunks.values()), default=0
            ),
            "channel": channel.stats(),
            "op_trace": list(self._op_trace),
        }
        if losses:
            stats["losses"] = [losses[mb] for mb in sorted(losses)]
        self._last_stats = stats
        return stats

    @staticmethod
    def _block_until_ready(tree):
        import jax

        for leaf in jax.tree.leaves(tree):
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()

    @staticmethod
    def _to_host(tree):
        """Device arrays -> numpy views for the zero-copy send path (on
        CPU backends this is copy-free; on TPU it is the one D2H)."""
        import jax
        import numpy as np

        return jax.tree.map(np.asarray, tree)

    def _maybe_debug_fail(self, step: int) -> None:
        hook = self.cfg.debug_fail
        if not hook or hook.get("stage") != self.stage:
            return
        if step != hook.get("step"):
            return
        marker = hook.get("marker", "")
        if marker and os.path.exists(marker):
            return  # already died once; restarted actor runs through
        if marker:
            with open(marker, "w") as f:
                f.write("died")
        logger.warning("debug_fail: stage %d exiting at step %d",
                       self.stage, step)
        os._exit(1)


# ---------------------------------------------------------------- trainer
class PipelinedTrainer:
    """JaxTrainer-style driver for pipeline-parallel training.

    ``module_builder(virtual_idx, total_virtual) -> StageModule`` defines
    the model partition; ``data_per_step(step) -> (inputs, targets)``
    feeds each step, where both are arrays whose leading (batch) axis is
    split into ``num_microbatches`` equal microbatches.
    """

    def __init__(
        self,
        module_builder: Callable[[int, int], StageModule],
        *,
        pipeline_config: Optional[PipelineConfig] = None,
        data_per_step: Callable[[int], tuple] = None,
        num_steps: int = 1,
        learning_rate: float = 1e-3,
        rng_seed: int = 0,
        run_config: Optional[RunConfig] = None,
        resources_per_stage: Optional[Dict[str, float]] = None,
    ):
        self.module_builder = module_builder
        self.cfg = pipeline_config or PipelineConfig()
        self.data_per_step = data_per_step
        self.num_steps = num_steps
        self.learning_rate = learning_rate
        self.rng_seed = rng_seed
        self.run_config = run_config or RunConfig()
        self.resources_per_stage = resources_per_stage or {"CPU": 1.0}
        self._pg = None
        self.stages: List[Any] = []
        self._generation = 0
        self._restarts = 0
        # Last synchronized checkpoint: (step_to_resume_from, [blob/stage]).
        self._ckpt: Optional[tuple] = None
        # SLO-remediation hook: a stage index flagged (from any thread)
        # for respawn-and-replace; fit() honors it between steps via the
        # same generation-fenced recovery path stage DEATH takes.
        self._respawn_request: Optional[int] = None
        self._last_step_stats: List[Dict[str, Any]] = []

    # ------------------------------------------------------------ topology
    def _create_stages(self):
        from ray_tpu.core.placement import pipeline_stage_placement_group

        run_id = f"{os.getpid()}_{id(self):x}"
        self._run_id = getattr(self, "_run_id", run_id)
        if self._pg is None:
            self._pg = pipeline_stage_placement_group(
                self.cfg.num_stages, self.resources_per_stage
            )
            self._pg.ready(timeout=120)
        self.stages = [
            self._spawn_stage(i) for i in range(self.cfg.num_stages)
        ]
        self._build_and_wire(range(self.cfg.num_stages))

    def _spawn_stage(self, i: int):
        from ray_tpu.core.placement import placement_group_strategy

        return PipelineStage.options(
            num_cpus=self.resources_per_stage.get("CPU", 1),
            num_tpus=self.resources_per_stage.get("TPU", 0) or None,
            scheduling_strategy=placement_group_strategy(self._pg, i),
            max_concurrency=4,
        ).remote(i, self.cfg, self._run_id)

    def _build_and_wire(self, build_indices):
        payload = dumps_function(self.module_builder)
        timeout = max(120.0, self.cfg.recv_timeout_s)
        ray_tpu.get(
            [
                self.stages[i].build.remote(
                    payload, self.learning_rate, self.rng_seed
                )
                for i in build_indices
            ],
            timeout=timeout,
        )
        addresses = ray_tpu.get(
            [s.rpc_address.remote() for s in self.stages], timeout=timeout
        )
        ray_tpu.get(
            [
                s.wire.remote(addresses, self._generation)
                for s in self.stages
            ],
            timeout=timeout,
        )

    # ---------------------------------------------------------- checkpoint
    def _save_checkpoint(self, next_step: int):
        blobs = ray_tpu.get(
            [s.get_state.remote() for s in self.stages],
            timeout=max(120.0, self.cfg.recv_timeout_s),
        )
        self._ckpt = (next_step, blobs)
        run_dir = self._ckpt_dir()
        if run_dir:
            d = os.path.join(run_dir, f"pipeline_ckpt_{next_step:08d}")
            os.makedirs(d, exist_ok=True)
            for i, blob in enumerate(blobs):
                with open(os.path.join(d, f"stage_{i}.pkl"), "wb") as f:
                    f.write(blob)

    def _ckpt_dir(self) -> str:
        path = self.run_config.storage_path
        if not path:
            return ""
        d = os.path.join(path, self.run_config.name or "pipeline_run")
        os.makedirs(d, exist_ok=True)
        return d

    def _restore_checkpoint(self):
        step, blobs = self._ckpt
        ray_tpu.get(
            [
                s.load_state.remote(blobs[i])
                for i, s in enumerate(self.stages)
            ],
            timeout=max(120.0, self.cfg.recv_timeout_s),
        )
        return step

    # ------------------------------------------------------------ recovery
    def _recover(self) -> int:
        """Restart dead stages into their bundles, reset survivors, reload
        the last synchronized checkpoint everywhere, bump the channel
        generation.  Returns the step to resume from."""
        from ray_tpu.util import flight_recorder

        self._restarts += 1
        dead = []
        for i, s in enumerate(self.stages):
            try:
                ray_tpu.get(s.ping.remote(), timeout=10)
            except Exception:  # noqa: BLE001 — dead or wedged: replace
                dead.append(i)
        logger.warning(
            "pipeline recovery #%d: restarting stages %s from checkpoint "
            "step %s", self._restarts, dead, self._ckpt and self._ckpt[0],
        )
        for i in dead:
            try:
                ray_tpu.kill(self.stages[i])
            except Exception:  # raylint: waive[RTL003] already-dead actor kill is best-effort
                pass
            self.stages[i] = self._spawn_stage(i)
            flight_recorder.record_pipeline_restart(i)
        self._generation += 1
        # Survivors drop parked tensors before (re)wiring; new actors
        # need build() first.
        alive = [i for i in range(len(self.stages)) if i not in dead]
        ray_tpu.get(
            [self.stages[i].reset.remote() for i in alive],
            timeout=max(120.0, self.cfg.recv_timeout_s),
        )
        self._build_and_wire(dead)  # build() on replacements; wire() on all
        return self._restore_checkpoint()

    # ------------------------------------------------------- remediation
    def request_stage_respawn(self, stage_idx: int,
                              reason: str = "") -> bool:
        """Flag ``stage_idx`` for respawn-and-replace (a fresh actor in
        its bundle, every stage rolled back to the last synchronized
        checkpoint, generation fence bumped).  Callable from any thread
        — the remediation controller's straggler actuator; fit() applies
        it between steps."""
        if not 0 <= stage_idx < self.cfg.num_stages:
            return False
        logger.warning(
            "stage %d flagged for remediation respawn%s", stage_idx,
            f" ({reason})" if reason else "",
        )
        self._respawn_request = stage_idx
        return True

    def _remediation_actuator(self, target: str, violation, **_kw) -> str:
        """``pipeline_stage_respawn`` actuator (registered while fit()
        runs): target is the SLO subject's ``stage=N``.

        The straggler rule flags the stage with the high STALL — in a
        barrier-synced pipeline that is the victim waiting on a slow
        peer, not necessarily the culprit.  Before acting, localize the
        culprit from the last step's per-stage compute times (fwd+bwd,
        the signal the stall correlates against): respawn the stage
        doing outsized compute if one stands out, else the flagged
        stage itself."""
        from ray_tpu.util.remediation import RemediationSkipped, subject_tags

        stage = subject_tags(target).get("stage")
        if stage is None or not stage.isdigit():
            raise RemediationSkipped(f"unparseable stage target {target!r}")
        victim = int(stage)
        culprit, note = victim, ""
        stats = self._last_step_stats
        if stats and len(stats) == self.cfg.num_stages:
            compute = [s.get("fwd_s", 0.0) + s.get("bwd_s", 0.0)
                       for s in stats]
            peak = max(range(len(compute)), key=compute.__getitem__)
            peers = [c for i, c in enumerate(compute) if i != peak]
            if peers and compute[peak] > 2.0 * max(
                sum(peers) / len(peers), 1e-6
            ):
                culprit = peak
                if culprit != victim:
                    note = (f" (victim stage {victim}; culprit by compute "
                            f"share: {compute[peak]:.3f}s vs peer mean "
                            f"{sum(peers) / len(peers):.3f}s)")
        if not self.request_stage_respawn(
            culprit, reason=getattr(violation, "detail", "") or "slo"
        ):
            raise RemediationSkipped(f"no such stage {culprit}")
        return (f"stage {culprit} respawn requested (applied between "
                f"steps){note}")

    def _apply_pending_respawn(self) -> Optional[int]:
        """Honor a flagged respawn: kill the target stage, then run the
        normal generation-fenced recovery.  Returns the resume step, or
        None when nothing was pending."""
        pending, self._respawn_request = self._respawn_request, None
        if pending is None or not 0 <= pending < len(self.stages):
            return None
        logger.warning("remediation respawn: replacing stage %d", pending)
        try:
            ray_tpu.kill(self.stages[pending])
        except Exception:  # raylint: waive[RTL003] already-dead target kill is best-effort
            pass
        return self._recover()

    # ----------------------------------------------------------------- fit
    def fit(self) -> Result:
        from ray_tpu.core.usage import record_library_usage
        from ray_tpu.util import remediation

        record_library_usage("train.pipeline")
        cfg = self.cfg
        failure_cfg: FailureConfig = self.run_config.failure_config
        self._create_stages()
        self._save_checkpoint(0)  # synchronized step-0 baseline
        step_timeout = cfg.step_timeout_s or (cfg.recv_timeout_s * 3 + 60)
        metrics_history: List[Dict[str, Any]] = []
        attempts = 0
        step = 0
        actuator = remediation.register_actuator(
            "pipeline_stage_respawn", self._remediation_actuator
        )
        try:
            return self._fit_loop(
                cfg, failure_cfg, step_timeout, metrics_history,
                attempts, step,
            )
        finally:
            remediation.unregister_actuator(actuator)

    def _fit_loop(self, cfg, failure_cfg, step_timeout, metrics_history,
                  attempts, step) -> Result:
        def failed(e) -> Result:
            return Result(
                metrics=metrics_history[-1] if metrics_history else {},
                checkpoint=None,
                path=self._ckpt_dir(),
                error=e,
                metrics_history=metrics_history,
            )

        err = [None]

        def recover_bounded():
            """Bounded recovery: each attempt — including recoveries
            interrupted by ANOTHER death (chaos soak: kills landing
            mid-rebuild) — spends a failure attempt, so a kill loop
            exhausts the budget instead of escaping the fence.  Returns
            the resume step, or None when the budget is spent (the
            caller returns the failed Result)."""
            nonlocal attempts
            while True:
                attempts += 1
                if attempts > max(0, failure_cfg.max_failures):
                    return None
                try:
                    return self._recover()
                except Exception as e2:  # noqa: BLE001 — death mid-recovery
                    err[0] = e2

        def rolled_back(new_step: int) -> int:
            # The rolled-back steps will be re-run: drop their history
            # entries so consumers never see duplicate step numbers.
            metrics_history[:] = [
                m for m in metrics_history if m["step"] < new_step
            ]
            return new_step

        final_ckpt_done = False
        while step < self.num_steps or not final_ckpt_done:
            if step >= self.num_steps:
                # Training done: the FINAL synchronized checkpoint is
                # inside the fence too — a stage dying under it rolls
                # back and re-runs the tail instead of escaping fit()
                # as a raw exception.
                try:
                    self._save_checkpoint(self.num_steps)
                    final_ckpt_done = True
                    continue
                except Exception as e:  # noqa: BLE001 — death racing the final checkpoint
                    err[0] = e
                    new_step = recover_bounded()
                    if new_step is None:
                        return failed(err[0])
                    step = rolled_back(new_step)
                    if step >= self.num_steps:
                        continue  # checkpoint was current: retry it
            try:
                respawn_step = self._apply_pending_respawn()
            except Exception as e:  # noqa: BLE001 — death racing the respawn
                err[0] = e
                respawn_step = recover_bounded()
                if respawn_step is None:
                    return failed(err[0])
            if respawn_step is not None:
                step = rolled_back(respawn_step)
            # Outside the failure fence: a bad batch shape is a config
            # error and must RAISE, not be "recovered".
            inputs, targets = self._microbatches(step)
            t_step = time.perf_counter()
            try:
                # One span per step: every stage's run_step (and, through
                # the p2p trace propagation, every pipeline_push edge
                # between stages) stitches into a single cluster trace.
                from ray_tpu.util import tracing

                with tracing.start_span(
                    "pipeline.step",
                    {"step": step, "num_stages": cfg.num_stages},
                ):
                    refs = []
                    for i, s in enumerate(self.stages):
                        kw = {}
                        if i == 0:
                            kw["inputs"] = inputs
                        if i == cfg.num_stages - 1:
                            kw["targets"] = targets
                        refs.append(s.run_step.remote(step, **kw))
                    stats = ray_tpu.get(refs, timeout=step_timeout)
            except Exception as e:  # noqa: BLE001 — stage death/step loss
                err[0] = e
                new_step = recover_bounded()
                if new_step is None:
                    return failed(err[0])
                step = rolled_back(new_step)
                continue
            losses = stats[-1].get("losses") or []
            loss = sum(losses) / len(losses) if losses else float("nan")
            self._last_step_stats = stats
            bubble = self._record_step_metrics(stats)
            metrics_history.append({
                "step": step,
                "loss": loss,
                "bubble_fraction": bubble,
                "step_wall_s": time.perf_counter() - t_step,
                "restarts": self._restarts,
            })
            step += 1
            if (
                cfg.checkpoint_every_n_steps
                and step % cfg.checkpoint_every_n_steps == 0
            ):
                try:
                    self._save_checkpoint(step)
                except Exception as e:  # noqa: BLE001 — death racing the checkpoint
                    err[0] = e
                    new_step = recover_bounded()
                    if new_step is None:
                        return failed(err[0])
                    step = rolled_back(new_step)
        return Result(
            metrics=metrics_history[-1] if metrics_history else {},
            checkpoint=None,
            path=self._ckpt_dir(),
            error=None,
            metrics_history=metrics_history,
        )

    def _microbatches(self, step: int):
        import numpy as np

        inputs, targets = self.data_per_step(step)
        M = self.cfg.num_microbatches
        n = inputs.shape[0]
        if n % M:
            raise ValueError(
                f"batch axis {n} must be divisible by "
                f"num_microbatches={M}"
            )
        return (
            list(np.split(np.asarray(inputs), M)),
            list(np.split(np.asarray(targets), M)),
        )

    def _record_step_metrics(self, stats: List[Dict[str, Any]]) -> float:
        from ray_tpu.util import flight_recorder

        total_stall = sum(s["stall_s"] for s in stats)
        total_wall = sum(s["wall_s"] for s in stats)
        bubble = total_stall / total_wall if total_wall > 0 else 0.0
        flight_recorder.record_pipeline_bubble(bubble, per_stage={
            s["stage"]: (s["stall_s"] / s["wall_s"] if s["wall_s"] else 0.0)
            for s in stats
        })
        return bubble

    def shutdown(self):
        for s in self.stages:
            try:
                ray_tpu.kill(s)
            except Exception:  # raylint: waive[RTL003] teardown kill is best-effort
                pass
        self.stages = []
        if self._pg is not None:
            from ray_tpu.core.placement import remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:  # raylint: waive[RTL003] teardown remove is best-effort
                pass
            self._pg = None

    def get_stage_states(self) -> List[dict]:
        """Materialized chunk states per stage (tests/inspection)."""
        blobs = ray_tpu.get(
            [s.get_state.remote() for s in self.stages],
            timeout=max(120.0, self.cfg.recv_timeout_s),
        )
        return [pickle.loads(b) for b in blobs]


# --------------------------------------------------------------- reference
def reference_run(
    module_builder: Callable[[int, int], StageModule],
    total_virtual: int,
    data_per_step: Callable[[int], tuple],
    num_steps: int,
    *,
    num_microbatches: int = 1,
    learning_rate: float = 1e-3,
    rng_seed: int = 0,
):
    """Sequential (non-pipelined) execution of the SAME chunked model
    with the SAME microbatch gradient accumulation — the 1-stage
    self-baseline for loss-parity checks and bench `vs` ratios.

    Returns (per-step mean losses, final [chunk state dicts]); per-step
    wall times are exposed on the returned list as ``.step_walls`` via
    :class:`_LossList` (the bench's steady-state timing hook).
    """
    import numpy as np

    chunks = [
        _Chunk(v, total_virtual, module_builder(v, total_virtual),
               rng_seed, learning_rate)
        for v in range(total_virtual)
    ]
    losses_per_step = _LossList()
    for step in range(num_steps):
        t_step = time.perf_counter()
        inputs, targets = data_per_step(step)
        mb_inputs = np.split(np.asarray(inputs), num_microbatches)
        mb_targets = np.split(np.asarray(targets), num_microbatches)
        mb_losses = []
        for mb in range(num_microbatches):
            x = mb_inputs[mb]
            for chunk in chunks:
                y = chunk.forward(
                    mb, x, mb_targets[mb] if chunk.is_last else None
                )
                x = y
            gy = None
            for chunk in reversed(chunks):
                loss, gy = chunk.backward(mb, gy)
                if loss is not None:
                    mb_losses.append(float(loss))
        for chunk in chunks:
            chunk.apply_grads(num_microbatches)
        losses_per_step.append(sum(mb_losses) / len(mb_losses))
        losses_per_step.step_walls.append(time.perf_counter() - t_step)
    return losses_per_step, [c.state() for c in chunks]


class _LossList(list):
    """Per-step losses with per-step wall times riding along."""

    def __init__(self, *args):
        super().__init__(*args)
        self.step_walls: List[float] = []
