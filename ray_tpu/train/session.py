"""Per-worker training session: the user-facing ``report`` /
``get_checkpoint`` / ``get_context`` API (reference: ray
``python/ray/train/v2/api/train_fn_utils.py:22,153``).

``report`` hands metrics (and optionally a checkpoint directory) to the
worker actor, which queues them for the controller's poll loop.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional

from .checkpoint import Checkpoint

_session = threading.local()


@dataclass
class TrainContext:
    world_rank: int
    world_size: int
    local_rank: int
    node_rank: int
    trial_name: str = ""
    latest_checkpoint: Optional[Checkpoint] = None
    # Per-worker dataset shards (reference: the DatasetsCallback's
    # streaming_split delivery; ray ``train/v2``).
    dataset_shards: Optional[dict] = None
    # filled by the worker actor:
    _report_fn: Any = None
    _should_stop_fn: Any = None


def _set_session(ctx: TrainContext):
    _session.ctx = ctx


def _clear_session():
    _session.ctx = None


def get_context() -> TrainContext:
    ctx = getattr(_session, "ctx", None)
    if ctx is None:
        raise RuntimeError(
            "No train session active — call inside train_loop_per_worker"
        )
    return ctx


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    ctx = get_context()
    if ctx._report_fn is not None:
        ctx._report_fn(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_context().latest_checkpoint


def should_stop() -> bool:
    """True once the controller asked this worker to stop cooperatively —
    the elastic-resize offer.  A loop that honors it (checkpoint via
    ``report``, then return) lets the trainer re-form the gang at a new
    world size and resume from that checkpoint; a loop that ignores it
    simply runs to completion."""
    ctx = get_context()
    if ctx._should_stop_fn is None:
        return False
    return bool(ctx._should_stop_fn())


def get_dataset_shard(name: str = "train"):
    """This worker's shard of a dataset passed to the trainer via
    ``datasets={name: ds}`` (reference: ``ray.train.get_dataset_shard``;
    the shard is a ``DataIterator`` whose transforms run worker-side)."""
    ctx = get_context()
    shards = ctx.dataset_shards or {}
    if name not in shards:
        raise KeyError(
            f"no dataset shard {name!r}; trainer datasets: {sorted(shards)}"
        )
    return shards[name]
